"""Framed request/response transport for the PS stack.

The reference runs its parameter server over brpc (N21
distributed/service/brpc_ps_server.cc) or gRPC (N20
operators/distributed/grpc/). Neither is warranted here: PS traffic is a
handful of large tensors per step between trusted cluster processes, so
the transport is a length-prefixed binary frame over TCP — numpy payloads
ride as raw buffers (zero-copy out of the socket), metadata as a small
pickled header. One thread per live connection on the server; clients
hold one persistent connection per server and serialize calls on it.

Fault tolerance (the reference's brpc channel carries connect_timeout +
timeout_ms + max_retry; HeartBeatMonitor assumes peers churn): every call
runs under a per-call deadline, transient transport failures (RST, EOF,
timeout, garbled frame) tear the socket down, back off exponentially with
jitter, transparently re-dial (re-running the auth handshake) and resend,
up to a retry budget — after which DeadlineExceeded / ConnectionError
propagates naming the method and endpoint. Retrying a MUTATING call is
made safe by idempotent replay: the client stamps such requests with a
(client_id, seq) request id and the server keeps a bounded per-client LRU
of recently applied ids, replaying the cached reply instead of
re-applying — a retry after a lost *response* cannot double-count a
gradient. Frame lengths are bounded by PADDLE_PS_MAX_FRAME on both ends
so one garbled header cannot OOM a peer. Flakiness is visible before it
becomes an outage through core.monitor counters: ps.rpc.retries,
ps.rpc.reconnects, ps.rpc.deadline_exceeded, ps.rpc.replays,
ps.rpc.bad_frames.

Security: deserialization uses a RESTRICTED unpickler that only resolves
numpy array/dtype reconstructors and plain containers — an arbitrary
`__reduce__` gadget from a hostile peer raises UnpicklingError instead of
executing (the reference's protobuf transport has no gadget surface; this
restores that property). Defense in depth: set PADDLE_PS_TOKEN in the job
environment and every connection must open with a matching token
handshake before any request is served (`__ping__` alone is answered
pre-auth so supervisors can health-check without the token). PS endpoints
are still cluster infrastructure — bind them to loopback or a trusted
network, never the open internet.
"""
from __future__ import annotations

import hmac
import importlib
import io
import os
import pickle
import random
import socket
import struct
import threading
import time
import uuid
from collections import OrderedDict

from ...core import monitor as _monitor
from ...core import trace as _trace
from ...core.flags import flag as _flag

__all__ = ["send_msg", "recv_msg", "Connection", "serve", "FrameError",
           "AuthError", "DeadlineExceeded", "ConnectRefused", "ReplayCache",
           "set_fault_injector"]

_HDR = struct.Struct("!Q")


class FrameError(ConnectionError):
    """Oversized or garbled frame — the stream is unusable past it, so
    the connection is dropped (ConnectionError subclass: generic
    transport-failure handlers treat it as such)."""


class AuthError(ConnectionError):
    """Token handshake rejected. ConnectionError subclass for callers'
    sake, but never retried — a bad token stays bad."""


class DeadlineExceeded(TimeoutError):
    """A call stalled past PADDLE_PS_CALL_TIMEOUT on every attempt of its
    retry budget. TimeoutError subclass (and therefore OSError), so
    existing `except (ConnectionError, OSError)` cleanup paths catch it.
    """


class ConnectRefused(ConnectionError):
    """The endpoint actively refused the dial — a *dead server* signal,
    distinct from a transient mid-call failure. Raised immediately (no
    retry-budget burn) when the fault injector scripts a PARTITION at
    the dial boundary, or when a real ECONNREFUSED lands on a connection
    with `fail_fast_refused` set (the shard-map client sets it once a
    replicated map is live, so a dead primary triggers failover to the
    promoted backup instead of 30s of redial)."""


# --- fault-injection seam (paddle_tpu.testing.faults) --------------------
# A test-only hook consulted at frame boundaries. None in production; the
# branch is one global load per event, negligible next to a socket op.
_fault_injector = None


def set_fault_injector(injector):
    """Install (or clear, with None) the process-global fault injector.
    Use paddle_tpu.testing.faults.inject(...) rather than calling this
    directly."""
    global _fault_injector
    _fault_injector = injector


def _fault(side, event, method, endpoint=None):
    inj = _fault_injector
    if inj is None:
        return None
    return inj.on_event(side, event, method, endpoint)


# --- restricted deserialization ------------------------------------------

# modules:names the restricted unpickler will resolve — numpy array/dtype
# reconstruction plus the stdlib pieces numpy's reducers reference
_SAFE_GLOBALS = {
    "builtins": {"complex", "slice", "range", "frozenset", "set",
                 "bytearray"},
    "numpy": {"ndarray", "dtype", "matrix", "generic", "bool_", "number",
              "int8", "int16", "int32", "int64", "uint8", "uint16",
              "uint32", "uint64", "float16", "float32", "float64",
              "complex64", "complex128", "longlong", "ulonglong", "intc",
              "uintc", "frombuffer"},
    "numpy.core.multiarray": {"_reconstruct", "scalar"},
    "numpy._core.multiarray": {"_reconstruct", "scalar"},
    "numpy.core.numeric": {"_frombuffer"},
    "numpy._core.numeric": {"_frombuffer"},
    "numpy.dtypes": None,   # dtype singletons (Float32DType, ...)
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if module in _SAFE_GLOBALS and (
                _SAFE_GLOBALS[module] is None
                or name in _SAFE_GLOBALS[module]):
            return getattr(importlib.import_module(module), name)
        raise pickle.UnpicklingError(
            f"ps rpc: refusing to unpickle global {module}.{name} "
            "(only numpy payloads are allowed on this transport)")


def _loads(data, buffers=None):
    return _RestrictedUnpickler(io.BytesIO(data),
                                buffers=buffers or []).load()


def _pack(obj) -> bytes:
    """Pickle with numpy arrays extracted to raw out-of-band buffers
    (pickle-5 semantics) so big tensors aren't copied through the
    pickler."""
    buffers = []
    payload = pickle.dumps(obj, protocol=5,
                           buffer_callback=lambda b: buffers.append(b))
    parts = [payload] + [bytes(b) for b in buffers]
    head = pickle.dumps([len(p) for p in parts])
    return _HDR.pack(len(head)) + head + b"".join(parts)


def _unpack(data: bytes):
    n = _HDR.unpack_from(data)[0]
    sizes = _loads(data[_HDR.size:_HDR.size + n])
    if not isinstance(sizes, list) \
            or not all(isinstance(s, int) and 0 <= s <= len(data)
                       for s in sizes):
        raise pickle.UnpicklingError("ps rpc: malformed frame header")
    off = _HDR.size + n
    parts = []
    for s in sizes:
        parts.append(data[off:off + s])
        off += s
    return _loads(parts[0], buffers=parts[1:])


def send_msg(sock: socket.socket, obj, max_frame=None) -> None:
    data = _pack(obj)
    limit = _flag("PADDLE_PS_MAX_FRAME") if max_frame is None else max_frame
    if len(data) > limit:
        raise FrameError(
            f"ps rpc: refusing to send a {len(data)}-byte frame "
            f"(PADDLE_PS_MAX_FRAME={limit})")
    sock.sendall(_HDR.pack(len(data)) + data)


def recv_msg(sock: socket.socket, max_frame=None):
    """One framed message, None on clean EOF. Raises FrameError on a
    length prefix over PADDLE_PS_MAX_FRAME (no allocation happens) or a
    payload the restricted unpickler rejects — after either, the stream
    is desynced and the connection must be dropped."""
    head = _recv_exact(sock, _HDR.size)
    if head is None:
        return None
    (n,) = _HDR.unpack(head)
    limit = _flag("PADDLE_PS_MAX_FRAME") if max_frame is None else max_frame
    if n > limit:
        raise FrameError(
            f"ps rpc: peer announced a {n}-byte frame "
            f"(PADDLE_PS_MAX_FRAME={limit}) — dropping connection")
    data = _recv_exact(sock, n)
    if data is None:
        return None
    try:
        return _unpack(data)
    except pickle.UnpicklingError:
        raise
    except (struct.error, ValueError, EOFError, IndexError, KeyError) as e:
        raise FrameError(f"ps rpc: garbled frame: {e}") from e


def _recv_exact(sock, n):
    buf = io.BytesIO()
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            return None
        buf.write(chunk)
        got += len(chunk)
    return buf.getvalue()


# --- client side ----------------------------------------------------------

class Connection:
    """Client side: one persistent socket, calls serialized by a lock,
    transparent retry/reconnect under a per-call deadline.

    `timeout` is the per-attempt deadline (socket-level, covers send and
    recv); `max_retries` extra attempts follow a failed one after an
    exponentially growing jittered backoff. Reconnects re-run the
    PADDLE_PS_TOKEN auth handshake. Mutating calls pass _mutating=True so
    a resend carries the same (client_id, seq) request id and the server
    can replay instead of re-applying (see serve/ReplayCache)."""

    def __init__(self, endpoint: str, timeout=None, connect_retry_s=None,
                 max_retries=None, backoff_base=None, backoff_max=None,
                 fail_fast_refused=False, quiet=False):
        self.endpoint = endpoint
        # a quiet connection bumps no ps.rpc.* counters and records no
        # spans: the telemetry shipper (core/telemetry.py) rides one so
        # SHIPPING the observability stream never feeds back into it —
        # the hub's counter totals must equal what the app did, not
        # what the app did plus the act of reporting it
        self._quiet = bool(quiet)
        # a refused connect normally retries within the connect window
        # (workers race the server's bind at job start); with a live
        # replicated shard map the client flips this on so a dead
        # endpoint raises ConnectRefused immediately and failover runs
        self.fail_fast_refused = bool(fail_fast_refused)
        self._timeout = float(_flag("PADDLE_PS_CALL_TIMEOUT")
                              if timeout is None else timeout)
        self._max_retries = int(_flag("PADDLE_PS_MAX_RETRIES")
                                if max_retries is None else max_retries)
        self._backoff_base = float(_flag("PADDLE_PS_BACKOFF_BASE_S")
                                   if backoff_base is None else backoff_base)
        self._backoff_max = float(_flag("PADDLE_PS_BACKOFF_MAX_S")
                                  if backoff_max is None else backoff_max)
        connect_retry_s = float(_flag("PADDLE_PS_CONNECT_RETRY_S")
                                if connect_retry_s is None
                                else connect_retry_s)
        self._lock = threading.Lock()
        self._sock = None
        # request-id namespace for idempotent replay: unique per client
        # connection object, stable across reconnects
        self._client_id = uuid.uuid4().hex
        self._seq = 0
        self._dial(connect_retry_s)

    # ---------------------------------------------------------- transport
    def _dial(self, connect_retry_s):
        """Connect + auth handshake. Only the TCP connect is retried
        within the window (workers routinely race the server's bind at
        job start — the reference's brpc channel does the same via
        connect_timeout + retry policy); an auth REJECTION is final."""
        host, port = self.endpoint.rsplit(":", 1)
        try:
            # testing/faults.py PARTITION boundary: a scripted dead or
            # partitioned endpoint refuses the dial without any real
            # process being killed
            _fault("client", "dial", self.endpoint, self.endpoint)
        except ConnectionRefusedError as e:
            raise ConnectRefused(
                f"ps rpc: endpoint {self.endpoint} refused connection "
                "(injected partition)") from e
        deadline = time.monotonic() + connect_retry_s
        while True:
            try:
                sock = socket.create_connection(
                    (host, int(port)), timeout=self._timeout)
                break
            except ConnectionRefusedError as e:
                if self.fail_fast_refused:
                    raise ConnectRefused(
                        f"ps rpc: endpoint {self.endpoint} refused "
                        "connection") from e
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(self._timeout)
        token = os.environ.get("PADDLE_PS_TOKEN")
        if token:
            try:
                send_msg(sock, {"method": "__auth__", "token": token})
                reply = recv_msg(sock)
            except OSError:
                sock.close()
                raise
            if not reply or reply.get("error"):
                sock.close()
                raise AuthError(
                    "ps auth handshake rejected: "
                    f"{(reply or {}).get('error', 'closed')}")
        self._sock = sock

    def _teardown(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # --------------------------------------------------------------- calls
    def call(self, method: str, _mutating=False, _key=None, _timeout=None,
             _rid=None, **kwargs):
        """One RPC under the retry/deadline policy. `_mutating` stamps a
        replay id; `_key` (optional, any hashable) pins that id so an
        OUTER retry loop (e.g. the Communicator's send thread) stays
        exactly-once too; `_rid` overrides the stamped (client_id, key)
        pair entirely — the shard-map client mints one rid per LOGICAL
        call so a failover retry to a different server (and a primary's
        forward to its backups) dedupes against the original apply;
        `_timeout` overrides the per-attempt deadline (barriers
        legitimately block longer than data calls)."""
        timeout = self._timeout if _timeout is None else float(_timeout)
        # one span per logical CALL (not per attempt): its context rides
        # in the frame — which is packed once, so every retry/resend
        # carries the SAME trace id and the server's apply/replay spans
        # correlate with this call across the process boundary
        sp = _trace.begin(f"ps.rpc/{method}", endpoint=self.endpoint,
                          mutating=bool(_mutating))
        t0 = time.perf_counter()
        try:
            result = self._call_impl(sp, method, _mutating, _key, _rid,
                                     timeout, kwargs)
            if not self._quiet:
                dt_ms = (time.perf_counter() - t0) * 1e3
                _monitor.observe("ps.rpc/latency_ms", dt_ms)
                # per-endpoint copy feeds the hub's shard-skew /
                # straggler detector (core/slo.py latency_skew)
                _monitor.observe(
                    f"ps.rpc/endpoint_ms/{self.endpoint}", dt_ms)
            return result
        except BaseException as e:
            sp.attrs.setdefault("error", type(e).__name__)
            _trace.end(sp, discard=self._quiet)
            # record BEFORE the dump snapshots the ring
            extra = getattr(e, "_flight_extra", None)
            if extra is not None and not self._quiet:
                # retry budget exhausted: the transport is dead for this
                # call — flight-record the span/metric history
                from ...core import flight_recorder as _fr
                _fr.dump("ps_transport_death", e, extra=extra)
            raise
        finally:
            _trace.end(sp, discard=self._quiet)

    def _call_impl(self, sp, method, _mutating, _key, _rid, timeout, kwargs):
        req = {"method": method, **kwargs}
        with self._lock:
            if _rid is not None:
                req["__rid__"] = tuple(_rid)
            elif _mutating:
                if _key is None:
                    self._seq += 1
                    _key = self._seq
                req["__rid__"] = (self._client_id, _key)
            req["__trace__"] = sp.context
            # pack ONCE, outside the retry loop: an oversized request is
            # a deterministic local error (no retry, nothing hit the
            # wire), and resends reuse the bytes instead of re-pickling
            payload = _pack(req)
            limit = _flag("PADDLE_PS_MAX_FRAME")
            if len(payload) > limit:
                raise FrameError(
                    f"ps rpc: request for {method!r} on {self.endpoint} "
                    f"is {len(payload)} bytes "
                    f"(PADDLE_PS_MAX_FRAME={limit})")
            frame = _HDR.pack(len(payload)) + payload
            if not self._quiet:
                _monitor.stat_add("ps.rpc.bytes_out", len(frame))
            attempts = self._max_retries + 1
            last_err = None
            for attempt in range(attempts):
                if attempt:
                    if not self._quiet:
                        _monitor.stat_add("ps.rpc.retries")
                    delay = min(self._backoff_max,
                                self._backoff_base * (2 ** (attempt - 1)))
                    # full jitter on [delay/2, delay] — decorrelates
                    # thundering-herd retries across workers
                    time.sleep(delay * (0.5 + random.random() / 2))
                try:
                    if self._sock is None:
                        self._dial(timeout)
                        if not self._quiet:
                            _monitor.stat_add("ps.rpc.reconnects")
                    self._sock.settimeout(timeout)
                    _fault("client", "send", method, self.endpoint)
                    self._sock.sendall(frame)
                    _fault("client", "recv", method, self.endpoint)
                    reply = recv_msg(self._sock)
                    if reply is None:
                        raise ConnectionError("peer closed connection")
                except AuthError:
                    self._teardown()
                    raise          # auth rejection is never transient
                except ConnectRefused:
                    # dead/partitioned endpoint: this connection cannot
                    # help — surface immediately so a shard-map client
                    # fails over instead of burning the retry budget
                    self._teardown()
                    raise
                except (OSError, pickle.UnpicklingError) as e:
                    # covers ConnectionError, FrameError, socket timeout
                    last_err = e
                    self._teardown()
                    continue
                sp.attrs["attempts"] = attempt + 1
                if reply.get("error"):
                    if reply["error"] == "ShardMapStale":
                        # structured redirect: the server's map rode
                        # along, the shard-map client re-routes with it
                        from .shard_map import ShardMapStale
                        sp.attrs["error"] = "ShardMapStale"
                        raise ShardMapStale(reply.get("shard_map"),
                                            f"{method!r} redirected by "
                                            f"{self.endpoint}")
                    raise RuntimeError(f"ps server error in {method!r}: "
                                       f"{reply['error']}")
                return reply.get("result")
        # retry budget exhausted: tag the exception so call() writes a
        # flight-recorder dump AFTER the span lands in the ring
        sp.attrs["attempts"] = attempts
        if isinstance(last_err, TimeoutError):
            if not self._quiet:
                _monitor.stat_add("ps.rpc.deadline_exceeded")
            err = DeadlineExceeded(
                f"ps rpc deadline exceeded calling {method!r} on "
                f"{self.endpoint}: {attempts} attempts of {timeout:.1f}s "
                "each (PADDLE_PS_CALL_TIMEOUT / PADDLE_PS_MAX_RETRIES)")
        else:
            err = ConnectionError(
                f"ps rpc failed calling {method!r} on {self.endpoint} "
                f"after {attempts} attempts: {last_err}")
        err._flight_extra = {"method": method, "endpoint": self.endpoint,
                             "attempts": attempts}
        raise err from last_err

    def ping(self, timeout=None):
        """Transport liveness probe; served by the peer before auth, so
        it works for supervisors that don't hold the job token."""
        return self.call("__ping__", _timeout=timeout)

    def close(self):
        self._teardown()


# --- server side ----------------------------------------------------------

class ReplayCache:
    """Bounded per-client LRU of recently applied mutating requests
    (rid -> reply), the correctness keystone that makes retry safe: a
    retry after a lost response replays the cached reply instead of
    re-applying the gradient. Entries in flight (handler still running
    when the retry lands on a fresh connection) park the retry on an
    Event rather than double-executing."""

    _PENDING, _DONE = 0, 1

    def __init__(self, per_client=None, max_clients=1024):
        self._per_client = int(_flag("PADDLE_PS_REPLAY_CACHE")
                               if per_client is None else per_client)
        self._max_clients = int(max_clients)
        self._clients: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def begin(self, rid):
        """-> ("replay", reply) | ("wait", event) | ("run", None)."""
        cid, seq = rid
        with self._lock:
            entries = self._clients.get(cid)
            if entries is None:
                entries = self._clients[cid] = OrderedDict()
                while len(self._clients) > self._max_clients:
                    _, evicted = self._clients.popitem(last=False)
                    # wake any retry parked on an in-flight entry of the
                    # evicted client — a fast "never committed" error
                    # beats a 600s hang on an orphaned Event
                    for state, pay in evicted.values():
                        if state == self._PENDING:
                            pay.set()
            else:
                self._clients.move_to_end(cid)
            entry = entries.get(seq)
            if entry is not None:
                if entry[0] == self._DONE:
                    return "replay", entry[1]
                return "wait", entry[1]
            entries[seq] = (self._PENDING, threading.Event())
            return "run", None

    def commit(self, rid, reply):
        cid, seq = rid
        with self._lock:
            entries = self._clients.get(cid)
            if entries is None:
                return
            entry = entries.get(seq)
            entries[seq] = (self._DONE, reply)
            entries.move_to_end(seq)
            # evict oldest DONE entries only — a pending one belongs to a
            # live handler that will commit into it
            while len(entries) > self._per_client:
                for k, v in entries.items():
                    if v[0] == self._DONE and k != seq:
                        del entries[k]
                        break
                else:
                    break
        if entry is not None and entry[0] == self._PENDING:
            entry[1].set()

    def abort(self, rid):
        """Drop a PENDING entry without caching a reply — used for
        routing rejections (ShardMapStale): the client WILL retry the
        same rid against the right server, and a cached redirect would
        replay forever. Parked retries are woken; begin() then hands
        them 'run'."""
        cid, seq = rid
        with self._lock:
            entries = self._clients.get(cid)
            entry = entries.pop(seq, None) if entries is not None else None
        if entry is not None and entry[0] == self._PENDING:
            entry[1].set()

    def lookup(self, rid):
        cid, seq = rid
        with self._lock:
            entry = self._clients.get(cid, {}).get(seq)
        if entry is not None and entry[0] == self._DONE:
            return entry[1]
        return None


def _trace_ctx_of(req):
    """Pop the client-shipped trace context (trace_id, span_id) from a
    request, validating shape — a peer without the tracer (or a garbled
    field) degrades to a fresh local trace, never an error."""
    ctx = req.pop("__trace__", None)
    try:
        trace_id, span_id = ctx
        return (str(trace_id), None if span_id is None else str(span_id))
    except (TypeError, ValueError):
        return None


def _rid_of(req):
    rid = req.pop("__rid__", None)
    if rid is None:
        return None
    try:
        cid, seq = rid
        hash(seq)
    except (TypeError, ValueError):
        return None
    return str(cid), seq


def serve(endpoint: str, handler, stop_event: threading.Event, replay=None):
    """Accept loop: one daemon thread per connection, each dispatching
    framed requests to handler(method, kwargs) until the peer closes or
    stop_event fires. Returns the bound port (endpoint may say :0).

    Per-connection fault policy: a garbled/oversized frame gets a
    best-effort error reply, bumps ps.rpc.bad_frames, and drops ONLY that
    connection (the stream past it is desynced) — the server and its
    other connections keep running. `__ping__` is answered before auth.
    Requests carrying a replay id go through the shared ReplayCache so a
    retried mutation is applied exactly once; pass `replay` to share the
    cache with other machinery (the replica catch-up path registers
    delta-log rids in it so live forwards dedupe against them).

    A handler declaring a third parameter — handler(method, req, rid) —
    receives the request's replay id so it can thread the SAME id through
    primary->backup forwards (exactly-once across the whole replica
    chain); two-parameter handlers keep working unchanged."""
    host, port = endpoint.rsplit(":", 1)
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, int(port)))
    srv.listen(128)
    srv.settimeout(0.2)
    bound = srv.getsockname()[1]

    token = os.environ.get("PADDLE_PS_TOKEN")
    if replay is None:
        replay = ReplayCache()
    try:
        import inspect
        _sig = inspect.signature(handler)
        wants_rid = len(_sig.parameters) >= 3
    except (TypeError, ValueError):
        wants_rid = False

    def _serve_one(conn, method, req):
        """Run the handler (through the replay cache when the request is
        stamped) and send the reply, honoring injected reply faults.
        Returns False when the connection must close. The span parents to
        the trace context the CLIENT shipped in the frame (same bytes on
        every retry), so apply AND replay spans of one logical call share
        its trace id across the process boundary."""
        tctx = _trace_ctx_of(req)
        rid = _rid_of(req)
        sp = _trace.begin(f"ps.server/{method}", parent=tctx,
                          outcome="apply")
        try:
            reply = None
            run = rid is None
            if rid is not None:
                for _round in range(3):
                    state, payload = replay.begin(rid)
                    if state == "run":
                        run = True
                        break
                    if state == "replay":
                        _monitor.stat_add("ps.rpc.replays")
                        sp.attrs["outcome"] = "replay"
                        reply = payload
                        break
                    # the original attempt is still executing on another
                    # connection thread — parking beats double-applying
                    sp.attrs["outcome"] = "wait"
                    payload.wait(timeout=600.0)
                    reply = replay.lookup(rid)
                    if reply is not None:
                        _monitor.stat_add("ps.rpc.replays")
                        break
                    # original aborted (stale-map redirect) or evicted:
                    # loop to re-begin — this retry becomes the runner
                if not run and reply is None:
                    reply = {"error": "ps rpc: in-flight original "
                                      "never committed (server "
                                      "overloaded?)"}
            if run:
                cacheable = True
                try:
                    result = handler(method, req, rid) if wants_rid \
                        else handler(method, req)
                    reply = {"result": result}
                except Exception as e:  # noqa: BLE001 — reported to peer
                    sp.attrs["error"] = type(e).__name__
                    stale = getattr(e, "shard_map_dict", None)
                    if stale is not None:
                        # routing redirect, not an application error:
                        # ship the server's map and DON'T cache — the
                        # same rid must run for real on the right server
                        reply = {"error": "ShardMapStale",
                                 "shard_map": stale}
                        cacheable = False
                    else:
                        reply = {"error": f"{type(e).__name__}: {e}"}
                        if getattr(e, "replay_uncacheable", False):
                            # e.g. a quorum failure: the error must not
                            # poison the rid — the retry re-runs (the
                            # replica layer dedupes the apply itself)
                            cacheable = False
                if rid is not None:
                    # commit BEFORE the reply leaves: if the response is
                    # lost from here on, the retry replays instead of
                    # re-applying
                    if cacheable:
                        replay.commit(rid, reply)
                    else:
                        replay.abort(rid)
        finally:
            _trace.end(sp)
        try:
            act = _fault("server", "reply", method)
        except ConnectionError:
            return False            # injected reset at the reply boundary
        if act == "drop":
            return False            # applied, but the response is lost
        if act == "garble":
            conn.sendall(_HDR.pack(10) + b"\x00" * 10)
            return True
        if act == "oversize":
            conn.sendall(_HDR.pack(1 << 41))
            return False
        send_msg(conn, reply)
        return True

    def _conn_loop(conn):
        conn.settimeout(None)
        authed = not token
        try:
            while not stop_event.is_set():
                try:
                    req = recv_msg(conn)
                except (FrameError, pickle.UnpicklingError) as e:
                    _monitor.stat_add("ps.rpc.bad_frames")
                    try:
                        send_msg(conn, {"error": f"bad frame: {e}"})
                    except OSError:
                        pass
                    break
                # re-check AFTER the blocking recv: a request that raced
                # shutdown must not be applied to a dying server's tables
                # (the client will retry against the restarted one)
                if req is None or stop_event.is_set():
                    break
                if not isinstance(req, dict) or "method" not in req:
                    _monitor.stat_add("ps.rpc.bad_frames")
                    send_msg(conn, {"error": "bad frame: no method"})
                    break
                method = req.pop("method")
                if method == "__ping__":
                    # liveness probe, answered before auth by design
                    send_msg(conn, {"result": "pong"})
                    continue
                if not authed:
                    # first real frame must be the token handshake
                    if method == "__auth__" and hmac.compare_digest(
                            str(req.get("token", "")), token):
                        authed = True
                        send_msg(conn, {"result": "ok"})
                        continue
                    send_msg(conn, {"error": "auth required"})
                    break
                if method == "__auth__":
                    send_msg(conn, {"result": "ok"})
                    continue
                if not _serve_one(conn, method, req):
                    break
        except OSError:
            pass                    # peer vanished mid-reply: their retry
        finally:                    # lands on a fresh connection
            conn.close()

    def _accept_loop():
        with srv:
            while not stop_event.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=_conn_loop, args=(conn,),
                                 daemon=True).start()

    t = threading.Thread(target=_accept_loop, daemon=True)
    t.start()
    return bound, t
