"""Framed request/response transport for the PS stack.

The reference runs its parameter server over brpc (N21
distributed/service/brpc_ps_server.cc) or gRPC (N20
operators/distributed/grpc/). Neither is warranted here: PS traffic is a
handful of large tensors per step between trusted cluster processes, so
the transport is a length-prefixed binary frame over TCP — numpy payloads
ride as raw buffers (zero-copy out of the socket), metadata as a small
pickled header. One thread per live connection on the server; clients
hold one persistent connection per server and serialize calls on it.

Security: deserialization uses a RESTRICTED unpickler that only resolves
numpy array/dtype reconstructors and plain containers — an arbitrary
`__reduce__` gadget from a hostile peer raises UnpicklingError instead of
executing (the reference's protobuf transport has no gadget surface; this
restores that property). Defense in depth: set PADDLE_PS_TOKEN in the job
environment and every connection must open with a matching token
handshake before any request is served. PS endpoints are still cluster
infrastructure — bind them to loopback or a trusted network, never the
open internet.
"""
from __future__ import annotations

import hmac
import importlib
import io
import os
import pickle
import socket
import struct
import threading

import numpy as np

__all__ = ["send_msg", "recv_msg", "Connection", "serve"]

_HDR = struct.Struct("!Q")

# modules:names the restricted unpickler will resolve — numpy array/dtype
# reconstruction plus the stdlib pieces numpy's reducers reference
_SAFE_GLOBALS = {
    "builtins": {"complex", "slice", "range", "frozenset", "set",
                 "bytearray"},
    "numpy": {"ndarray", "dtype", "matrix", "generic", "bool_", "number",
              "int8", "int16", "int32", "int64", "uint8", "uint16",
              "uint32", "uint64", "float16", "float32", "float64",
              "complex64", "complex128", "longlong", "ulonglong", "intc",
              "uintc", "frombuffer"},
    "numpy.core.multiarray": {"_reconstruct", "scalar"},
    "numpy._core.multiarray": {"_reconstruct", "scalar"},
    "numpy.core.numeric": {"_frombuffer"},
    "numpy._core.numeric": {"_frombuffer"},
    "numpy.dtypes": None,   # dtype singletons (Float32DType, ...)
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if module in _SAFE_GLOBALS and (
                _SAFE_GLOBALS[module] is None
                or name in _SAFE_GLOBALS[module]):
            return getattr(importlib.import_module(module), name)
        raise pickle.UnpicklingError(
            f"ps rpc: refusing to unpickle global {module}.{name} "
            "(only numpy payloads are allowed on this transport)")


def _loads(data, buffers=None):
    return _RestrictedUnpickler(io.BytesIO(data),
                                buffers=buffers or []).load()


def _pack(obj) -> bytes:
    """Pickle with numpy arrays extracted to raw out-of-band buffers
    (pickle-5 semantics) so big tensors aren't copied through the
    pickler."""
    buffers = []
    payload = pickle.dumps(obj, protocol=5,
                           buffer_callback=lambda b: buffers.append(b))
    parts = [payload] + [bytes(b) for b in buffers]
    head = pickle.dumps([len(p) for p in parts])
    return _HDR.pack(len(head)) + head + b"".join(parts)


def _unpack(data: bytes):
    n = _HDR.unpack_from(data)[0]
    sizes = _loads(data[_HDR.size:_HDR.size + n])
    if not isinstance(sizes, list) \
            or not all(isinstance(s, int) and 0 <= s <= len(data)
                       for s in sizes):
        raise pickle.UnpicklingError("ps rpc: malformed frame header")
    off = _HDR.size + n
    parts = []
    for s in sizes:
        parts.append(data[off:off + s])
        off += s
    return _loads(parts[0], buffers=parts[1:])


def send_msg(sock: socket.socket, obj) -> None:
    data = _pack(obj)
    sock.sendall(_HDR.pack(len(data)) + data)


def recv_msg(sock: socket.socket):
    head = _recv_exact(sock, _HDR.size)
    if head is None:
        return None
    (n,) = _HDR.unpack(head)
    data = _recv_exact(sock, n)
    if data is None:
        return None
    return _unpack(data)


def _recv_exact(sock, n):
    buf = io.BytesIO()
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            return None
        buf.write(chunk)
        got += len(chunk)
    return buf.getvalue()


class Connection:
    """Client side: one persistent socket, calls serialized by a lock.
    Connect retries briefly — workers routinely race the server's bind at
    job start (the reference's brpc channel does the same via
    connect_timeout + retry policy)."""

    def __init__(self, endpoint: str, timeout=120.0, connect_retry_s=30.0):
        import time
        host, port = endpoint.rsplit(":", 1)
        deadline = time.monotonic() + connect_retry_s
        while True:
            try:
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=timeout)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        token = os.environ.get("PADDLE_PS_TOKEN")
        if token:
            send_msg(self._sock, {"method": "__auth__", "token": token})
            reply = recv_msg(self._sock)
            if not reply or reply.get("error"):
                raise ConnectionError(
                    "ps auth handshake rejected: "
                    f"{(reply or {}).get('error', 'closed')}")

    def call(self, method: str, **kwargs):
        with self._lock:
            send_msg(self._sock, {"method": method, **kwargs})
            reply = recv_msg(self._sock)
        if reply is None:
            raise ConnectionError(f"server closed during {method!r}")
        if reply.get("error"):
            raise RuntimeError(f"ps server error in {method!r}: "
                               f"{reply['error']}")
        return reply.get("result")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def serve(endpoint: str, handler, stop_event: threading.Event):
    """Accept loop: one daemon thread per connection, each dispatching
    framed requests to handler(method, kwargs) until the peer closes or
    stop_event fires. Returns the bound port (endpoint may say :0)."""
    host, port = endpoint.rsplit(":", 1)
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, int(port)))
    srv.listen(128)
    srv.settimeout(0.2)
    bound = srv.getsockname()[1]

    token = os.environ.get("PADDLE_PS_TOKEN")

    def _conn_loop(conn):
        conn.settimeout(None)
        authed = not token
        try:
            while not stop_event.is_set():
                req = recv_msg(conn)
                if req is None:
                    break
                method = req.pop("method")
                if not authed:
                    # first frame must be the token handshake
                    if method == "__auth__" and hmac.compare_digest(
                            str(req.get("token", "")), token):
                        authed = True
                        send_msg(conn, {"result": "ok"})
                        continue
                    send_msg(conn, {"error": "auth required"})
                    break
                if method == "__auth__":
                    send_msg(conn, {"result": "ok"})
                    continue
                try:
                    result = handler(method, req)
                    send_msg(conn, {"result": result})
                except Exception as e:  # noqa: BLE001 — reported to peer
                    send_msg(conn, {"error": f"{type(e).__name__}: {e}"})
        finally:
            conn.close()

    def _accept_loop():
        with srv:
            while not stop_event.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=_conn_loop, args=(conn,),
                                 daemon=True).start()

    t = threading.Thread(target=_accept_loop, daemon=True)
    t.start()
    return bound, t
