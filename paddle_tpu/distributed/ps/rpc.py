"""Framed request/response transport for the PS stack.

The reference runs its parameter server over brpc (N21
distributed/service/brpc_ps_server.cc) or gRPC (N20
operators/distributed/grpc/). Neither is warranted here: PS traffic is a
handful of large tensors per step between trusted cluster processes, so
the transport is a length-prefixed binary frame over TCP — numpy payloads
ride as raw buffers (zero-copy out of the socket), metadata as a small
pickled header. One thread per live connection on the server; clients
hold one persistent connection per server and serialize calls on it.
"""
from __future__ import annotations

import io
import pickle
import socket
import struct
import threading

import numpy as np

__all__ = ["send_msg", "recv_msg", "Connection", "serve"]

_HDR = struct.Struct("!Q")


def _pack(obj) -> bytes:
    """Pickle with numpy arrays extracted to raw out-of-band buffers
    (pickle-5 semantics) so big tensors aren't copied through the
    pickler."""
    buffers = []
    payload = pickle.dumps(obj, protocol=5,
                           buffer_callback=lambda b: buffers.append(b))
    parts = [payload] + [bytes(b) for b in buffers]
    head = pickle.dumps([len(p) for p in parts])
    return _HDR.pack(len(head)) + head + b"".join(parts)


def _unpack(data: bytes):
    n = _HDR.unpack_from(data)[0]
    sizes = pickle.loads(data[_HDR.size:_HDR.size + n])
    off = _HDR.size + n
    parts = []
    for s in sizes:
        parts.append(data[off:off + s])
        off += s
    return pickle.loads(parts[0], buffers=parts[1:])


def send_msg(sock: socket.socket, obj) -> None:
    data = _pack(obj)
    sock.sendall(_HDR.pack(len(data)) + data)


def recv_msg(sock: socket.socket):
    head = _recv_exact(sock, _HDR.size)
    if head is None:
        return None
    (n,) = _HDR.unpack(head)
    data = _recv_exact(sock, n)
    if data is None:
        return None
    return _unpack(data)


def _recv_exact(sock, n):
    buf = io.BytesIO()
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            return None
        buf.write(chunk)
        got += len(chunk)
    return buf.getvalue()


class Connection:
    """Client side: one persistent socket, calls serialized by a lock.
    Connect retries briefly — workers routinely race the server's bind at
    job start (the reference's brpc channel does the same via
    connect_timeout + retry policy)."""

    def __init__(self, endpoint: str, timeout=120.0, connect_retry_s=30.0):
        import time
        host, port = endpoint.rsplit(":", 1)
        deadline = time.monotonic() + connect_retry_s
        while True:
            try:
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=timeout)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def call(self, method: str, **kwargs):
        with self._lock:
            send_msg(self._sock, {"method": method, **kwargs})
            reply = recv_msg(self._sock)
        if reply is None:
            raise ConnectionError(f"server closed during {method!r}")
        if reply.get("error"):
            raise RuntimeError(f"ps server error in {method!r}: "
                               f"{reply['error']}")
        return reply.get("result")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def serve(endpoint: str, handler, stop_event: threading.Event):
    """Accept loop: one daemon thread per connection, each dispatching
    framed requests to handler(method, kwargs) until the peer closes or
    stop_event fires. Returns the bound port (endpoint may say :0)."""
    host, port = endpoint.rsplit(":", 1)
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, int(port)))
    srv.listen(128)
    srv.settimeout(0.2)
    bound = srv.getsockname()[1]

    def _conn_loop(conn):
        conn.settimeout(None)
        try:
            while not stop_event.is_set():
                req = recv_msg(conn)
                if req is None:
                    break
                method = req.pop("method")
                try:
                    result = handler(method, req)
                    send_msg(conn, {"result": result})
                except Exception as e:  # noqa: BLE001 — reported to peer
                    send_msg(conn, {"error": f"{type(e).__name__}: {e}"})
        finally:
            conn.close()

    def _accept_loop():
        with srv:
            while not stop_event.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=_conn_loop, args=(conn,),
                                 daemon=True).start()

    t = threading.Thread(target=_accept_loop, daemon=True)
    t.start()
    return bound, t
