"""Data-parallel training entry points.

Analog of reference python/paddle/distributed/parallel.py
(init_parallel_env :57) and python/paddle/fluid/dygraph/parallel.py
(DataParallel :313 with the C++ bucketing Reducer, imperative/reducer.cc).

Design delta: there is no gradient Reducer. Under the single-controller
SPMD model, batches are dp-sharded arrays and parameters are replicated;
XLA inserts the gradient all-reduce (fused and overlapped) when the step
is jitted — the reference's bucket-fusion machinery (reducer.cc:321
MarkGroupReady) is the compiler's problem now.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import mesh as mesh_mod
from .env import ParallelEnv, get_world_size

__all__ = ["init_parallel_env", "DataParallel", "ParallelEnv",
           "get_world_size"]


def init_parallel_env(mesh_shape=None):
    """Join the multi-host runtime (when PADDLE_TRAINERS_NUM > 1, via
    jax.distributed — see bootstrap.py, the c_gen_nccl_id + c_comm_init
    analog) and declare the default mesh over the global device set."""
    from .bootstrap import maybe_initialize_distributed
    maybe_initialize_distributed()
    mesh_mod.init_mesh(mesh_shape)
    return ParallelEnv()


def _shard_batch(value, mesh):
    spec = P("dp") if "dp" in mesh.axis_names else P()
    return jax.device_put(value, NamedSharding(mesh, spec))


class DataParallel(Layer):
    """reference fluid/dygraph/parallel.py:313 DataParallel.

    Wraps a layer so inputs are dp-sharded and parameters replicated;
    gradient synchronization is implicit in SPMD execution.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        super().__init__()
        self._layers = layers
        mesh = mesh_mod.auto_mesh()
        self._mesh = mesh
        # replicate parameters across the mesh once
        repl = NamedSharding(mesh, P())
        for p in layers.parameters():
            p._value = jax.device_put(p._value, repl)
        for b in layers.buffers():
            b._value = jax.device_put(b._value, repl)

    def forward(self, *inputs, **kwargs):
        sharded = []
        for x in inputs:
            if isinstance(x, Tensor):
                x = Tensor(_shard_batch(x._value, self._mesh),
                           stop_gradient=x.stop_gradient, _internal=True)
                x._node = None
            sharded.append(x)
        return self._layers(*sharded, **kwargs)

    def scale_loss(self, loss):
        return loss  # grads are globally correct already

    def apply_collective_grads(self):
        pass  # no-op: XLA emitted the all-reduce

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)
