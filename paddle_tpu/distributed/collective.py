"""Collective communication API.

Analog of reference python/paddle/distributed/collective.py (broadcast :99,
all_reduce :155, reduce :229, all_gather :311, scatter :384, barrier :455)
backed by operators/collective/* NCCL kernels (c_allreduce_op.h:123 etc.).

Design delta (SURVEY.md §2.3/§5.8): `ring_id`+comm-stream plumbing is gone.
Inside an SPMD region (shard_map/pjit over a named mesh axis) these calls
emit XLA collectives over ICI — the compiler schedules/overlaps them
(c_sync_calc_stream/c_sync_comm_stream have no analog, by design). Called
eagerly with world_size==1 they are identity, preserving single-process
semantics of reference scripts.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.tensor import Tensor
from ..ops._dispatch import defop
from . import mesh as mesh_mod
from .env import get_world_size

__all__ = ["ReduceOp", "all_reduce", "all_gather", "reduce", "broadcast",
           "scatter", "alltoall", "reduce_scatter", "hierarchical_all_reduce",
           "send", "recv", "barrier", "split", "new_group", "wait",
           "get_group"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """Mesh-axis-backed process group (replaces ring_id registries,
    platform/collective_helper.h:63). A rank subset becomes XLA
    `axis_index_groups` — members collect among themselves, non-members
    pass through as singleton groups."""

    def __init__(self, axis_name="dp", ranks=None, group_id=0):
        self.axis = axis_name
        self.ranks = sorted(ranks) if ranks is not None else None
        self.id = group_id

    @property
    def nranks(self):
        if self.ranks is not None:
            return len(self.ranks)
        return mesh_mod.mesh_axis_size(self.axis)

    def get_group_rank(self, rank):
        if self.ranks is not None:
            return self.ranks.index(rank) if rank in self.ranks else -1
        return rank

    def index_groups(self):
        """axis_index_groups partitioning the axis: [members] + singletons.
        None when the group spans the whole axis."""
        if self.ranks is None:
            return None
        n = mesh_mod.mesh_axis_size(self.axis)
        if list(self.ranks) == list(range(n)):
            return None
        others = [[r] for r in range(n) if r not in self.ranks]
        return [list(self.ranks)] + others


_groups = {0: Group("dp", group_id=0)}


def new_group(ranks=None, backend=None, axis_name="dp"):
    gid = max(_groups) + 1
    g = Group(axis_name, ranks, gid)
    _groups[gid] = g
    return g


def get_group(gid=0):
    return _groups.get(gid)


def _axis_of(group) -> str:
    if group is None or group == 0:
        return "dp"
    if isinstance(group, Group):
        return group.axis
    if isinstance(group, str):
        return group
    return "dp"


def _groups_of(group):
    return group.index_groups() if isinstance(group, Group) else None


def _in_region(axis):
    return mesh_mod.in_spmd_region(axis)


_REDUCERS = {
    ReduceOp.SUM: lax.psum,
    ReduceOp.MAX: lax.pmax,
    ReduceOp.MIN: lax.pmin,
    ReduceOp.AVG: lax.pmean,
}


def _hashable(groups):
    """axis_index_groups as nested tuples so defop kwargs stay hashable."""
    if groups is None:
        return None
    return tuple(tuple(g) for g in groups)


def _identity_for(op, dtype):
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        return jnp.zeros((), dtype)
    if op == ReduceOp.MAX:
        if dtype == jnp.bool_:
            return jnp.asarray(False)  # MAX on bool == OR
        return jnp.asarray(jnp.finfo(dtype).min
                           if jnp.issubdtype(dtype, jnp.floating)
                           else jnp.iinfo(dtype).min, dtype)
    if op == ReduceOp.MIN:
        if dtype == jnp.bool_:
            return jnp.asarray(True)  # MIN on bool == AND
        return jnp.asarray(jnp.finfo(dtype).max
                           if jnp.issubdtype(dtype, jnp.floating)
                           else jnp.iinfo(dtype).max, dtype)
    return jnp.ones((), dtype)  # PROD


def _member_mask(axis, members):
    idx = lax.axis_index(axis)
    m = jnp.zeros((), jnp.bool_)
    for r in members:
        m = m | (idx == r)
    return m


@defop(name="c_allreduce")
def _allreduce_raw(x, axis, op, groups=None):
    """All-reduce, optionally over a rank subset.

    Subset semantics (XLA axis_index_groups is unavailable inside shard_map
    in current JAX): members reduce among themselves via identity-element
    masking, non-members keep their own value — exactly the
    [members]+singletons partition a reference sub-communicator gives."""
    members = list(groups[0]) if groups else None
    if op == ReduceOp.PROD:
        # exact product (zeros/signs included): gather the axis, reduce
        # locally. Reference c_allreduce_prod is a real ncclProd; XLA has no
        # product all-reduce, and the log/exp trick misreduces zeros.
        g = lax.all_gather(x, axis)
        if members is None:
            return jnp.prod(g, axis=0)
        red = jnp.prod(g[jnp.asarray(members)], axis=0)
        return jnp.where(_member_mask(axis, members), red, x)
    if members is None:
        return _REDUCERS[op](x, axis)
    mask = _member_mask(axis, members)
    masked = jnp.where(mask, x, _identity_for(op, x.dtype))
    if op == ReduceOp.AVG:
        red = lax.psum(masked, axis) / len(members)
    else:
        red = _REDUCERS[op](masked, axis)
    return jnp.where(mask, red.astype(x.dtype), x)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis_of(group)
    if not _in_region(axis):
        if get_world_size() == 1 or mesh_mod.mesh_axis_size(axis) == 1:
            return tensor  # identity in single-process semantics
        raise RuntimeError(
            f"all_reduce over axis '{axis}' called outside an SPMD region; "
            "wrap the computation in paddle_tpu.distributed.shard (shard_map)"
            " or use sharded training via fleet/Model.fit")
    out = _allreduce_raw(tensor, axis=axis, op=op,
                         groups=_hashable(_groups_of(group)))
    if isinstance(tensor, Tensor):
        tensor._rebind(out)  # paddle mutates in place
        return tensor
    return out


@defop(name="c_allgather")
def _allgather_raw(x, axis):
    return lax.all_gather(x, axis, axis=0, tiled=False)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    axis = _axis_of(group)
    if not _in_region(axis):
        if mesh_mod.mesh_axis_size(axis) == 1:
            tensor_list.append(tensor)
            return tensor_list
        raise RuntimeError("all_gather outside SPMD region")
    gathered = _allgather_raw(tensor, axis=axis)
    n = mesh_mod.mesh_axis_size(axis)
    from .. import ops
    for i in range(n):
        tensor_list.append(gathered[i])
    return tensor_list


def all_gather_object(obj_list, obj, group=None):
    obj_list.append(obj)
    return obj_list


@defop(name="c_reduce")
def _reduce_raw(x, axis, op, dst, groups=None):
    red = _allreduce_raw.raw(x, axis, op, groups)
    idx = lax.axis_index(axis)
    return jnp.where(idx == dst, red, x)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis_of(group)
    if not _in_region(axis):
        if mesh_mod.mesh_axis_size(axis) == 1:
            return tensor
        raise RuntimeError("reduce outside SPMD region")
    out = _reduce_raw(tensor, axis=axis, op=op, dst=dst,
                      groups=_hashable(_groups_of(group)))
    if isinstance(tensor, Tensor):
        tensor._rebind(out)
        return tensor
    return out


@defop(name="c_broadcast")
def _broadcast_raw(x, axis, src, members=None):
    """Butterfly broadcast: log2(n) collective_permute rounds, so the source
    link is never an O(n) hotspot and any dtype (incl. bool/int) is exact —
    replaces the psum(x*mask) trick. Non-member ranks of a subset group keep
    their own value."""
    n = mesh_mod.mesh_axis_size(axis)
    members = list(members) if members is not None else list(range(n))
    m = len(members)
    if m == 1:
        return x
    src_pos = members.index(src)
    ring = [members[(src_pos + i) % m] for i in range(m)]  # pos->rank
    # pos of this rank in the member ring (-1 for non-members), statically
    # tabulated and indexed by the dynamic axis index
    pos_np = np.full((n,), -1, np.int32)
    for j, r in enumerate(ring):
        pos_np[r] = j
    pos = jnp.asarray(pos_np)[lax.axis_index(axis)]
    stride = 1
    while stride < m:
        perm = tuple((ring[i], ring[i + stride])
                     for i in range(stride) if i + stride < m)
        recv = lax.ppermute(x, axis, perm)
        newly = (pos >= stride) & (pos < 2 * stride)
        x = jnp.where(newly, recv, x)
        stride *= 2
    return x


def broadcast(tensor, src=0, group=None, sync_op=True):
    axis = _axis_of(group)
    if not _in_region(axis):
        if mesh_mod.mesh_axis_size(axis) == 1:
            return tensor
        raise RuntimeError("broadcast outside SPMD region")
    members = tuple(group.ranks) if isinstance(group, Group) and \
        group.ranks is not None else None
    out = _broadcast_raw(tensor, axis=axis, src=src, members=members)
    if isinstance(tensor, Tensor):
        tensor._rebind(out)
        return tensor
    return out


@defop(name="c_scatter")
def _scatter_raw(stacked, axis, src):
    full = _broadcast_raw.raw(stacked, axis, src, None)
    idx = lax.axis_index(axis)
    return lax.dynamic_index_in_dim(full, idx, axis=0, keepdims=False)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    axis = _axis_of(group)
    if not _in_region(axis):
        if mesh_mod.mesh_axis_size(axis) == 1:
            if tensor_list:
                tensor._rebind(tensor_list[0])
            return tensor
        raise RuntimeError("scatter outside SPMD region")
    from .. import ops
    stacked = ops.stack(tensor_list, axis=0) if tensor_list else tensor
    out = _scatter_raw(stacked, axis=axis, src=src)
    if isinstance(tensor, Tensor):
        tensor._rebind(out)
        return tensor
    return out


@defop(name="c_alltoall")
def _alltoall_raw(x, axis):
    n = mesh_mod.mesh_axis_size(axis)
    return lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    axis = _axis_of(group)
    from .. import ops
    if not _in_region(axis):
        if mesh_mod.mesh_axis_size(axis) == 1:
            if out_tensor_list is not None:
                out_tensor_list.extend(in_tensor_list)
                return out_tensor_list
            return in_tensor_list
        raise RuntimeError("alltoall outside SPMD region")
    x = ops.stack(in_tensor_list, axis=0) if isinstance(in_tensor_list, list) \
        else in_tensor_list
    out = _alltoall_raw(x, axis=axis)
    if out_tensor_list is not None:
        n = mesh_mod.mesh_axis_size(axis)
        for i in range(n):
            out_tensor_list.append(out[i])
        return out_tensor_list
    return out


@defop(name="c_reducescatter")
def _reduce_scatter_raw(x, axis, op):
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
        if op == ReduceOp.AVG:
            out = out / mesh_mod.mesh_axis_size(axis)
        return out
    # MAX/MIN/PROD: no fused XLA reduce-scatter variant — reduce over the
    # axis then slice this rank's chunk (reference c_reducescatter supports
    # all ncclRedOps; silent SUM here would be a wrong answer).
    n = mesh_mod.mesh_axis_size(axis)
    if x.shape[0] % n != 0:
        raise ValueError(
            f"reduce_scatter: leading dim {x.shape[0]} not divisible by "
            f"axis '{axis}' size {n}")
    red = _allreduce_raw.raw(x, axis, op, None)
    chunk = x.shape[0] // n
    idx = lax.axis_index(axis)
    return lax.dynamic_slice_in_dim(red, idx * chunk, chunk, axis=0)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    axis = _axis_of(group)
    from .. import ops
    if not _in_region(axis):
        if mesh_mod.mesh_axis_size(axis) == 1:
            src = tensor_list[0] if tensor_list else tensor
            if isinstance(tensor, Tensor):
                tensor._rebind(src)
            return tensor
        raise RuntimeError("reduce_scatter outside SPMD region")
    x = ops.concat(tensor_list, axis=0) if tensor_list else tensor
    out = _reduce_scatter_raw(x, axis=axis, op=op)
    if isinstance(tensor, Tensor):
        tensor._rebind(out)
        return tensor
    return out


@defop(name="c_hierarchical_allreduce")
def _hierarchical_allreduce_raw(x, inner_axis, outer_axis, op):
    """Pod-aware three-phase all-reduce — the decomposition
    spmd_analyzer.SpmdReport.hierarchical_sync prices: reduce-scatter
    over the fast `inner_axis` (ICI), all-reduce the resulting 1/n shard
    over the slow `outer_axis` (DCN), then all-gather the shard back
    over `inner_axis`. Numerically equal to a psum over both axes for
    SUM/AVG while shrinking the inter-pod payload by the inner axis
    size. MAX/MIN/PROD have no scatter decomposition — they nest the
    flat form per axis (same wire shape, still axis-local traffic)."""
    n_in = mesh_mod.mesh_axis_size(inner_axis)
    n_out = mesh_mod.mesh_axis_size(outer_axis)
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        red = _allreduce_raw.raw(x, inner_axis, op, None)
        return _allreduce_raw.raw(red, outer_axis, op, None)
    shape = x.shape
    flat = jnp.reshape(x, (-1,))
    pad = (-flat.shape[0]) % n_in
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    shard = lax.psum_scatter(flat, inner_axis, scatter_dimension=0,
                             tiled=True)
    shard = lax.psum(shard, outer_axis)
    full = lax.all_gather(shard, inner_axis, axis=0, tiled=True)
    if pad:
        full = full[:-pad]
    out = jnp.reshape(full, shape)
    if op == ReduceOp.AVG:
        out = out / (n_in * n_out)
    return out


def hierarchical_all_reduce(tensor, op=ReduceOp.SUM, inner_axis="dp",
                            outer_axis="pod", sync_op=True):
    """All-reduce across a nested two-tier mesh: intra-pod reduce-scatter,
    inter-pod all-reduce of the 1/n shard, intra-pod all-gather. Selected
    by ShardingPlan.as_strategy() when the planned mesh declares a slow
    tier; degrades to a plain all_reduce when either axis is unbound or
    trivial, so flat-mesh callers keep flat-mesh semantics."""
    if not _in_region(inner_axis):
        return all_reduce(tensor, op=op, group=outer_axis)
    if not _in_region(outer_axis):
        return all_reduce(tensor, op=op, group=inner_axis)
    out = _hierarchical_allreduce_raw(tensor, inner_axis=inner_axis,
                                      outer_axis=outer_axis, op=op)
    if isinstance(tensor, Tensor):
        tensor._rebind(out)
        return tensor
    return out


@defop(name="send_v2")
def _ppermute_raw(x, axis, perm):
    return lax.ppermute(x, axis, perm)


def send(tensor, dst=0, group=None, sync_op=True):
    """Point-to-point send (reference operators/collective/send_v2).
    In SPMD form, send/recv pairs become a collective_permute; use
    paddle_tpu.distributed.p2p_permute for the fused form."""
    raise NotImplementedError(
        "raw send/recv do not exist in SPMD — use p2p_permute(x, perm) "
        "(collective_permute) inside shard_map, or the pipeline API")


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "raw send/recv do not exist in SPMD — use p2p_permute(x, perm) "
        "(collective_permute) inside shard_map, or the pipeline API")


def p2p_permute(x, perm, axis="pp"):
    """collective_permute over an axis: perm = [(src, dst), ...]."""
    if not _in_region(axis):
        if mesh_mod.mesh_axis_size(axis) == 1:
            return x
        raise RuntimeError("p2p_permute outside SPMD region")
    return _ppermute_raw(x, axis=axis, perm=tuple(perm))


def barrier(group=None):
    """Host-level barrier (reference operators/collective/barrier_op).
    Single-controller SPMD needs no in-graph barrier; multi-host sync goes
    through the jax distributed runtime."""
    if get_world_size() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def wait(tensor, group=None, use_calc_stream=True):
    return tensor  # stream sync is XLA's job


def split(x, num_partitions, axis="tp"):
    """Megatron-style sharded view helper (reference fleet collective split)."""
    idx = lax.axis_index(axis) if _in_region(axis) else 0
    from .. import ops
    parts = ops.split(x, num_partitions, axis=-1)
    if not _in_region(axis):
        return parts[0]
    return parts[int(idx)] if isinstance(idx, int) else \
        lax.switch(idx, [lambda p=p: p for p in parts])
