"""paddle_tpu.testing — deterministic test harnesses.

`faults` scripts seeded fault injection into the PS transport so chaos
suites (tests/test_ps_faults.py) and downstream users can prove their
training loops survive resets, lost replies, stalls, and garbage on the
wire without flaky sleeps or real network partitions.
"""
from . import faults

__all__ = ["faults"]
