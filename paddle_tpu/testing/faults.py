"""Deterministic fault injection for the PS transport.

The rpc layer (distributed/ps/rpc.py) consults a process-global injector
at four boundaries:

    ("client", "dial", endpoint) before a (re)connect — note the third
                                 field is the ENDPOINT, not a method, so
                                 rules can target one server
    ("client", "send", method)   before the request frame leaves
    ("client", "recv", method)   after send, before reading the reply
    ("server", "reply", method)  after the handler ran AND the replay
                                 cache committed, before the reply frame

The STREAMING dataset (dataset/streaming.py) consults the same injector
in front of every batch delivery as ("stream", "deliver", <stream
name>): a scripted STALL there is a deterministic BACKLOG BURST —
delivery pauses, the bounded queue fills, watermark/backlog gauges move,
and nothing is dropped (see `backlog_burst()` below); a RESET there is
absorbed by the dataset as a transient delivery fault and retried.

Client-side events additionally carry the peer ENDPOINT, so a rule can
target one shard server across every method: `Fault("client", "send",
STALL, endpoint="127.0.0.1:7001", times=10**9, delay=0.05)` is a
LATENCY-SKEW rule — that one server is slow (every call to it stalls),
the rest of the cluster is healthy. Slow-shard is a different failure
mode than dead-shard: nothing retries, nothing fails over; the tail
latency just lands on whoever waits for that shard synchronously — the
prefetch stage exists to absorb exactly this (tests/
test_ps_sharded_embedding.py proves it absorbs it WITHOUT changing
results).

An injector decides per event whether to fault. Faults are either
SCRIPTED — an ordered list of `Fault` rules with after/times counters, so
a test can say "drop exactly the first push_sparse_grad reply" — or
SEEDED — per-(side, event, method) probability streams keyed off a string
seed (sha-based, independent of PYTHONHASHSEED and thread interleaving
within each stream), for chaos runs.

Actions:
    RESET      raise ConnectionResetError at the boundary (any site
               except dial). Client side it models a TCP RST before/
               after the send; server side the reply path closes the
               connection.
    DROP       server reply only: the request WAS applied, the response
               is lost — the case idempotent replay exists for.
    STALL      sleep `delay` seconds at the boundary (models a hung
               peer; pair with a small PADDLE_PS_CALL_TIMEOUT).
    GARBLE     server reply only: a well-framed garbage payload.
    OVERSIZE   server reply only: a length prefix over the frame bound.
    PARTITION  client dial only: the (re)connect is refused —
               rpc.ConnectRefused — which is how a PERMANENTLY dead or
               partitioned server looks at dial time, distinct from a
               RESET mid-call. Target one endpoint with
               `method="host:port"` (times=N keeps it refused for N
               dials) to script dead-server and split-brain scenarios
               without killing real processes; combine with RESET rules
               on the data methods to sever already-established
               connections too.

Usage:

    from paddle_tpu.testing import faults
    with faults.inject(faults.Fault("server", "reply", faults.DROP,
                                    method="push_sparse_grad")):
        client.push_sparse_grad("emb", ids, grads)   # applied ONCE

    with faults.inject(seed=7, p={faults.RESET: 0.05, faults.DROP: 0.05}):
        train(...)   # chaos mode: seeded random resets + lost replies

Every fired fault is appended to `injector.log` as
(side, event, method, action) for post-run assertions.
"""
from __future__ import annotations

import contextlib
import hashlib
import threading
import time

from ..distributed.ps import rpc as _rpc

__all__ = ["RESET", "DROP", "STALL", "GARBLE", "OVERSIZE", "PARTITION",
           "Fault", "FaultInjector", "backlog_burst", "inject",
           "install", "uninstall"]

RESET = "reset"
DROP = "drop"
STALL = "stall"
GARBLE = "garble"
OVERSIZE = "oversize"
PARTITION = "partition"

# actions that only make sense where the reply frame is produced
_SERVER_REPLY_ONLY = frozenset({DROP, GARBLE, OVERSIZE})


def _eligible(action, side, event):
    if action in _SERVER_REPLY_ONLY:
        return side == "server" and event == "reply"
    if action == PARTITION:
        return side == "client" and event == "dial"
    if event == "dial":
        # the only fault a dial can exhibit is a refused connect
        return False
    return True


class Fault:
    """One scripted fault rule.

    side/event: which boundary ('client'/'send', 'client'/'recv',
    'server'/'reply'). method: exact RPC method name, or None for any.
    endpoint: restrict a CLIENT-side rule to calls against one peer
    ("host:port") — the per-endpoint latency-skew/slow-shard hook;
    None matches any peer (server-side events carry no endpoint).
    after: let that many matching frames through first. times: how many
    matches fire (then the rule is spent). delay: STALL sleep seconds.
    """

    def __init__(self, side, event, action, method=None, after=0, times=1,
                 delay=1.0, endpoint=None):
        if not _eligible(action, side, event):
            raise ValueError(
                f"action {action!r} is only injectable at server/reply")
        if endpoint is not None and side != "client":
            raise ValueError("endpoint= targeting only exists client-side "
                             "(the server does not know who dialed it)")
        self.side, self.event, self.action = side, event, action
        self.method, self.after, self.times = method, int(after), int(times)
        self.endpoint = endpoint
        self.delay = float(delay)
        self._seen = 0
        self._fired = 0

    def _try_fire(self, side, event, method, endpoint=None):
        if side != self.side or event != self.event:
            return False
        if self.method is not None and method != self.method:
            return False
        if self.endpoint is not None and endpoint != self.endpoint:
            return False
        self._seen += 1
        if self._seen <= self.after or self._fired >= self.times:
            return False
        self._fired += 1
        return True


class FaultInjector:
    """Scripted + seeded-random fault source. Install via `inject(...)`
    (context manager) or `install()`; rpc.py calls `on_event` at each
    frame boundary from whatever thread owns the socket, so all state is
    lock-protected."""

    def __init__(self, faults=(), seed=0, p=None, stall_delay=1.0):
        self.faults = [faults] if isinstance(faults, Fault) else list(faults)
        self.seed = seed
        self.p = dict(p or {})
        self.stall_delay = float(stall_delay)
        self.log = []
        self._counts = {}
        self._lock = threading.Lock()
        for action in self.p:
            if action not in (RESET, DROP, STALL, GARBLE, OVERSIZE,
                              PARTITION):
                raise ValueError(f"unknown fault action {action!r}")

    def _draw(self, side, event, method):
        """Seeded per-stream Bernoulli draw: the n-th event of a given
        (side, event, method) stream always sees the same uniform sample
        for a given seed — deterministic regardless of how server threads
        interleave ACROSS streams, and independent of PYTHONHASHSEED."""
        n = self._counts.get((side, event, method), 0)
        self._counts[(side, event, method)] = n + 1
        digest = hashlib.sha256(
            f"{self.seed}:{side}:{event}:{method}:{n}".encode()).digest()
        u = int.from_bytes(digest[:8], "big") / 2.0 ** 64
        acc = 0.0
        for action in sorted(self.p):
            if not _eligible(action, side, event):
                continue
            acc += self.p[action]
            if u < acc:
                return action
        return None

    def on_event(self, side, event, method, endpoint=None):
        # system frames are never faulted: auth is part of (re)dialing,
        # ping is the health probe the harness itself relies on
        if method in ("__auth__", "__ping__"):
            return None
        with self._lock:
            action = None
            for f in self.faults:
                if f._try_fire(side, event, method, endpoint):
                    action = f.action
                    delay = f.delay
                    break
            else:
                if self.p:
                    action = self._draw(side, event, method)
                    delay = self.stall_delay
            if action is None:
                return None
            self.log.append((side, event, method, action))
        if action == STALL:
            time.sleep(delay)
            return None
        if action == RESET:
            raise ConnectionResetError(
                f"fault injected: reset at {side}/{event} of {method!r}")
        if action == PARTITION:
            # rpc.Connection._dial converts this into ConnectRefused
            raise ConnectionRefusedError(
                f"fault injected: partitioned endpoint {method}")
        return action

    def fired(self, action=None):
        """Count of injected faults (optionally of one action)."""
        with self._lock:
            return sum(1 for rec in self.log
                       if action is None or rec[3] == action)


def backlog_burst(name=None, after=0, times=1, delay=0.2):
    """Scripted backlog burst for the streaming queue: a STALL rule at
    the ("stream", "deliver") boundary. Each firing pauses ONE batch
    delivery for `delay` seconds while producers keep offering — the
    backlog grows, the watermark holds, and every record is delivered
    once the burst passes (pause/resume, never drop). `name` targets
    one StreamingDataset (its `name=`), None matches any; after/times
    script where in the delivery sequence the burst lands, mirroring
    the endpoint-targetable STALL used for slow-shard skew."""
    return Fault("stream", "deliver", STALL, method=name, after=after,
                 times=times, delay=delay)


def install(injector: FaultInjector) -> FaultInjector:
    _rpc.set_fault_injector(injector)
    return injector


def uninstall():
    _rpc.set_fault_injector(None)


@contextlib.contextmanager
def inject(*faults, seed=0, p=None, stall_delay=1.0):
    """Context manager: install a FaultInjector built from scripted
    `Fault` rules and/or seeded probabilities, uninstall on exit, yield
    the injector (inspect `.log` / `.fired()` afterwards)."""
    inj = FaultInjector(faults, seed=seed, p=p, stall_delay=stall_delay)
    install(inj)
    try:
        yield inj
    finally:
        uninstall()
