"""paddle.dataset — fluid-era reader-creator dataset modules.

Analog of reference python/paddle/dataset/ (mnist.py, cifar.py,
uci_housing.py, imdb.py, imikolov.py, ...): each submodule exposes
train()/test() *reader creators* (zero-arg callables yielding samples)
over the same data the 2.x Dataset classes serve (vision/datasets,
text/datasets — local files when present, deterministic synthetic data in
zero-egress environments). Combine with paddle.reader decorators.
"""
from __future__ import annotations

import sys
import types

import numpy as np

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov",
           "flowers", "movielens"]


def _reader_from(dataset_factory, transform=None):
    def reader():
        ds = dataset_factory()
        for i in range(len(ds)):
            item = ds[i]
            yield transform(item) if transform is not None else item
    return reader


def _module(name):
    m = types.ModuleType(f"{__name__}.{name}")
    sys.modules[m.__name__] = m
    return m


# -- mnist: samples are (flat float32[784] in [-1,1], int label) ------------
mnist = _module("mnist")


def _mnist_reader(mode):
    from ..vision.datasets import MNIST

    def tf(item):
        img, lab = item
        flat = (np.asarray(img, np.float32).reshape(-1) * 2.0) - 1.0
        return flat, int(np.asarray(lab).reshape(-1)[0])
    return _reader_from(lambda: MNIST(mode=mode), tf)


mnist.train = lambda: _mnist_reader("train")
mnist.test = lambda: _mnist_reader("test")


# -- cifar: (flat float32[3072] in [0,1], int label) ------------------------
cifar = _module("cifar")


def _cifar_reader(mode, cls):
    def tf(item):
        img, lab = item
        return (np.asarray(img, np.float32).reshape(-1),
                int(np.asarray(lab).reshape(-1)[0]))

    def make():
        from ..vision.datasets import Cifar10, Cifar100
        ds_cls = Cifar10 if cls == 10 else Cifar100
        return ds_cls(mode=mode)
    return _reader_from(make, tf)


cifar.train10 = lambda: _cifar_reader("train", 10)
cifar.test10 = lambda: _cifar_reader("test", 10)
cifar.train100 = lambda: _cifar_reader("train", 100)
cifar.test100 = lambda: _cifar_reader("test", 100)


# -- uci_housing: (float32[13], float32[1]) ---------------------------------
uci_housing = _module("uci_housing")


def _uci_reader(mode):
    from ..text.datasets import UCIHousing
    return _reader_from(lambda: UCIHousing(mode=mode))


uci_housing.train = lambda: _uci_reader("train")
uci_housing.test = lambda: _uci_reader("test")


# -- imdb: (word-id list, 0/1 label) ----------------------------------------
imdb = _module("imdb")


def _imdb_reader(mode):
    from ..text.datasets import Imdb

    def tf(item):
        ids, lab = item
        return list(np.asarray(ids).reshape(-1)), int(np.asarray(lab))
    return _reader_from(lambda: Imdb(mode=mode), tf)


imdb.train = lambda word_dict=None: _imdb_reader("train")
imdb.test = lambda word_dict=None: _imdb_reader("test")
imdb.word_dict = lambda: {i: i for i in range(5149)}


# -- imikolov: n-gram tuples ------------------------------------------------
imikolov = _module("imikolov")


def _imikolov_reader(mode, n, data_file):
    from ..text.datasets import Imikolov

    def tf(item):
        return tuple(int(x) for x in np.asarray(item).reshape(-1))
    return _reader_from(lambda: Imikolov(data_file=data_file, mode=mode,
                                         data_type="NGRAM",
                                         window_size=n), tf)


imikolov.train = lambda word_dict=None, n=5, *, data_file=None: \
    _imikolov_reader("train", n, data_file)
imikolov.test = lambda word_dict=None, n=5, *, data_file=None: \
    _imikolov_reader("test", n, data_file)
imikolov.build_dict = lambda: {i: i for i in range(2073)}


# -- flowers ----------------------------------------------------------------
flowers = _module("flowers")


def _flowers_reader(mode, **files):
    from ..vision.datasets import Flowers

    def tf(item):
        img, lab = item
        return (np.asarray(img, np.float32),
                int(np.asarray(lab).reshape(-1)[0]))
    return _reader_from(lambda: Flowers(mode=mode, **files), tf)


flowers.train = lambda **files: _flowers_reader("train", **files)
flowers.test = lambda **files: _flowers_reader("test", **files)
flowers.valid = lambda **files: _flowers_reader("valid", **files)


# -- movielens --------------------------------------------------------------
movielens = _module("movielens")


def _movielens_reader(mode, data_file):
    from ..text.datasets import Movielens
    return _reader_from(lambda: Movielens(data_file=data_file, mode=mode))


movielens.train = lambda data_file=None: _movielens_reader("train",
                                                           data_file)
movielens.test = lambda data_file=None: _movielens_reader("test",
                                                          data_file)


# -- streaming: online-learning completion-record stream (a REAL
# -- submodule, not a fluid reader shim — see docs/online_learning.md) ------
from .streaming import StreamingDataset  # noqa: E402

__all__ += ["streaming", "StreamingDataset"]
