"""Streaming dataset over serve-traffic completion records.

The input half of the online-learning loop (docs/online_learning.md):
`inference/serving.ServeLoop(on_complete=ds.offer)` pushes one
structured completion record per retired request; a continuous trainer
consumes them through `batches()` exactly like any other
`train_from_dataset` source.

Delivery semantics, in transport terms:

- **at-least-once in**: producers may re-offer a record any number of
  times (a completion log replayed after a crash, a duplicated queue
  message). A bounded window of accepted record ids
  (PADDLE_STREAM_DEDUPE_WINDOW) rejects re-offers, so duplicates cost
  one counter bump, never a training step.
- **exactly-once training batches out, relative to the checkpoint
  cut**: `state_dict()` captures the undelivered buffer, the dedupe
  window, and the delivered-batch cursor. A restarted trainer that
  restores the snapshot and resumes with `batches(start_batch=cursor)`
  re-trains nothing it committed and loses nothing that was accepted:
  records buffered at the cut are redelivered, records accepted after
  the cut are re-admitted when the transport re-offers them (their ids
  are not in the restored window). Batches delivered after the cut but
  before the crash redeliver — the restored trainer never saw them, so
  the cut stays consistent as long as trainer state and dataset state
  checkpoint together (which incubate/checkpoint.py does).
- **bounded queue**: `offer()` blocks once PADDLE_STREAM_QUEUE_CAP
  records are undelivered — backpressure into the serving tier instead
  of unbounded growth.

The delivery boundary consults the process-global fault injector
(paddle_tpu.testing.faults) as ("stream", "deliver", <name>): a
scripted STALL there is a deterministic BACKLOG BURST (delivery pauses,
records pile up, nothing is dropped — `faults.backlog_burst(...)`), and
a seeded chaos RESET is absorbed as a transient delivery fault
(counted, retried; records are never dropped at this boundary).

Observability: `stream.{backlog,watermark,accepted,duplicates,
delivered_records,delivered_batches,delivery_faults,rejected_full}`
published as gauges on every offer/delivery.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

__all__ = ["StreamingDataset"]


class StreamingDataset:
    """Bounded, deduplicating record queue with checkpointable cursors.

    batch_size: records per training batch. collate: list-of-records ->
    feed dict (None yields the raw record list). capacity /
    dedupe_window: 0 = take the PADDLE_STREAM_* flag defaults. name:
    the fault-injection / gauge identity of this stream.
    """

    def __init__(self, batch_size, collate=None, capacity=0,
                 dedupe_window=0, name="serve", poll_s=0.02):
        from ..core import flags as _flags
        self.batch_size = int(batch_size)
        if self.batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.collate = collate
        self.capacity = int(capacity
                            or _flags.flag("PADDLE_STREAM_QUEUE_CAP"))
        self.dedupe_window = int(
            dedupe_window or _flags.flag("PADDLE_STREAM_DEDUPE_WINDOW"))
        self.name = str(name)
        self.poll_s = float(poll_s)
        self._buf: deque = deque()          # accepted, undelivered
        self._seen: OrderedDict = OrderedDict()  # rid -> None, FIFO
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._watermark = -1                # highest accepted rid
        self._accepted = 0
        self._duplicates = 0
        self._rejected_full = 0
        self._delivered_batches = 0
        self._delivered_records = 0
        self._delivery_faults = 0

    # -- producer side -------------------------------------------------------
    def offer(self, record, timeout=None):
        """Offer one completion record (a dict with an int "rid").
        Returns True if accepted, False if deduped / closed / timed out
        waiting on a full queue. Blocks while the queue is at capacity
        (backpressure); `timeout` bounds that wait. Thread-safe —
        usable directly as a ServeLoop on_complete hook."""
        rid = int(record["rid"])
        deadline = None if timeout is None \
            else time.perf_counter() + float(timeout)
        with self._cond:
            if self._closed:
                return False
            if rid in self._seen:
                self._duplicates += 1
                self._publish_gauges_locked()
                return False
            while len(self._buf) >= self.capacity and not self._closed:
                wait = self.poll_s
                if deadline is not None:
                    wait = min(wait, deadline - time.perf_counter())
                    if wait <= 0:
                        self._rejected_full += 1
                        self._publish_gauges_locked()
                        return False
                self._cond.wait(wait)
            if self._closed:
                return False
            if rid in self._seen:       # raced with a duplicate offer
                self._duplicates += 1
                self._publish_gauges_locked()
                return False
            self._seen[rid] = None
            while len(self._seen) > self.dedupe_window:
                self._seen.popitem(last=False)
            self._buf.append(dict(record))
            self._accepted += 1
            self._watermark = max(self._watermark, rid)
            self._publish_gauges_locked()
            self._cond.notify_all()
            return True

    def close(self):
        """End of stream: blocked offers return False, `batches()`
        flushes a final partial batch and stops."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- consumer side -------------------------------------------------------
    def batches(self, start_batch=0):
        """Yield collated training batches. `start_batch` must equal
        the delivered-batch cursor (0 fresh, or the cursor of the
        restored `state_dict()` after a trainer restart) — delivered
        records are deliberately not retained, so an out-of-sync resume
        is an error, not a silent skip or replay."""
        if int(start_batch) != self._delivered_batches:
            raise ValueError(
                f"start_batch {start_batch} != delivered cursor "
                f"{self._delivered_batches}; restore the matching "
                f"state_dict() before resuming")
        while True:
            self._deliver_gate()
            with self._cond:
                while len(self._buf) < self.batch_size \
                        and not self._closed:
                    self._cond.wait(self.poll_s)
                if not self._buf and self._closed:
                    self._publish_gauges_locked()
                    return
                take = min(self.batch_size, len(self._buf))
                recs = [self._buf.popleft() for _ in range(take)]
                self._delivered_batches += 1
                self._delivered_records += take
                self._publish_gauges_locked()
                self._cond.notify_all()
            yield self.collate(recs) if self.collate is not None \
                else recs

    def _deliver_gate(self):
        """The fault-injection boundary in front of every delivery:
        STALL = scripted backlog burst, RESET = transient delivery
        fault (absorbed + retried — records are never dropped here)."""
        from ..distributed.ps import rpc as _rpc
        while True:
            try:
                _rpc._fault("stream", "deliver", self.name)
                return
            except ConnectionResetError:
                with self._cond:
                    self._delivery_faults += 1
                    self._publish_gauges_locked()
                time.sleep(self.poll_s)

    # -- checkpointing -------------------------------------------------------
    def state_dict(self):
        """Snapshot for the trainer checkpoint: undelivered buffer,
        dedupe window, and cursors. Restoring it on a fresh instance
        resumes delivery exactly at the cut."""
        with self._cond:
            return {
                "buffered": [dict(r) for r in self._buf],
                "seen": list(self._seen),
                "watermark": self._watermark,
                "accepted": self._accepted,
                "duplicates": self._duplicates,
                "delivered_batches": self._delivered_batches,
                "delivered_records": self._delivered_records,
            }

    def load_state_dict(self, state):
        with self._cond:
            self._buf = deque(dict(r) for r in state["buffered"])
            self._seen = OrderedDict((int(r), None)
                                     for r in state["seen"])
            self._watermark = int(state["watermark"])
            self._accepted = int(state["accepted"])
            self._duplicates = int(state["duplicates"])
            self._delivered_batches = int(state["delivered_batches"])
            self._delivered_records = int(state["delivered_records"])
            self._publish_gauges_locked()
            self._cond.notify_all()

    # -- observability -------------------------------------------------------
    def stats(self):
        with self._cond:
            return {
                "backlog": len(self._buf),
                "watermark": self._watermark,
                "accepted": self._accepted,
                "duplicates": self._duplicates,
                "rejected_full": self._rejected_full,
                "delivered_batches": self._delivered_batches,
                "delivered_records": self._delivered_records,
                "delivery_faults": self._delivery_faults,
            }

    def _publish_gauges_locked(self):
        from ..core import monitor as _monitor
        _monitor.stat_set_many({
            "stream.backlog": len(self._buf),
            "stream.watermark": self._watermark,
            "stream.accepted": self._accepted,
            "stream.duplicates": self._duplicates,
            "stream.rejected_full": self._rejected_full,
            "stream.delivered_batches": self._delivered_batches,
            "stream.delivered_records": self._delivered_records,
            "stream.delivery_faults": self._delivery_faults,
        })
