"""Dygraph autograd engine.

TPU-native analog of the reference's imperative stack:
  - op recording       ~ Tracer::TraceOp (reference: paddle/fluid/imperative/tracer.cc:59)
  - grad graph node    ~ OpBase + GradOpNode (imperative/layer.h)
  - backward executor  ~ BasicEngine::Init/PrepareDeps/Execute
                         (imperative/basic_engine.cc:39,148,185)
  - multi-consumer sum ~ GradientAccumulator (imperative/gradient_accumulator.cc)
  - paddle.grad        ~ PartialGradEngine (imperative/partial_grad_engine.cc)

Design delta (SURVEY.md §7.1): instead of per-op hand-written grad kernels
chosen through GradOpDescMaker, every eager op is executed through `jax.vjp`,
which both computes the forward value and returns the exact cotangent
function XLA would differentiate under jit. The graph is implicit — each
output Tensor links to its producing Node — so Python GC frees dead
subgraphs with no global tape list (the reference needs eager GC passes for
the same job, framework/executor_gc_helper.cc).

The same op wrappers run unmodified under `jax.jit` tracing (values are then
tracers and recording is usually disabled), which is how the compiled
training paths (hapi, static.Program) reuse this single op library.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import flags as _flags

__all__ = [
    "no_grad", "enable_grad", "is_grad_enabled", "set_grad_enabled",
    "Node", "record_op", "backward", "grad",
]

_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


class _GradScope:
    """Usable as context manager AND decorator, like paddle.no_grad."""

    def __init__(self, mode: bool):
        self._mode = mode

    def __call__(self, func=None):
        if func is None:
            return self
        import functools

        @functools.wraps(func)
        def inner(*a, **k):
            with _GradScope(self._mode):
                return func(*a, **k)
        return inner

    def __enter__(self):
        self._old = is_grad_enabled()
        set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._old)
        return False


def no_grad(func=None):
    scope = _GradScope(False)
    return scope(func) if func is not None else scope


def enable_grad(func=None):
    scope = _GradScope(True)
    return scope(func) if func is not None else scope


_seq_lock = threading.Lock()
_seq_counter = [0]


class Node:
    """One recorded op: holds the vjp closure and edges to differentiable inputs."""

    __slots__ = ("vjp_fn", "inputs", "out_avals", "seq", "name", "multi_out",
                 "out_hooks", "closed_fn", "__weakref__")

    def __init__(self, vjp_fn, inputs, out_avals, name, multi_out,
                 closed_fn=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # list[Tensor] — differentiable inputs only
        self.out_avals = out_avals    # list[(shape, dtype)]
        self.name = name
        self.multi_out = multi_out
        self.out_hooks = None         # {out_index: [hook]} via register_hook
        self.closed_fn = closed_fn    # primal fn over diff inputs — lets
                                      # create_graph re-derive a RECORDED vjp
        with _seq_lock:
            _seq_counter[0] += 1
            self.seq = _seq_counter[0]


def record_op(fn: Callable, args: Sequence[Any], kwargs: dict, name: str = None):
    """Execute `fn` on raw values, recording a grad Node if needed.

    `fn` is a pure function of raw jax arrays (plus static kwargs). Tensor
    arguments are unwrapped; if recording is on and any floating Tensor input
    has stop_gradient=False, the op is run under jax.vjp and its outputs are
    linked into the implicit graph.
    """
    from .tensor import Tensor  # cycle: Tensor uses record_op for operators

    is_t = lambda v: isinstance(v, Tensor)  # noqa: E731
    # Flatten kwargs so keyword Tensors (e.g. layer_norm(x, weight=w)) are
    # first-class differentiable inputs, not closure constants.
    kw_leaves, kw_tree = jax.tree_util.tree_flatten(kwargs, is_leaf=is_t)
    flat = list(args) + kw_leaves
    n_args = len(args)

    # static-graph mode: symbolic Variables route to program recording
    # (reference: Program.append_op, fluid/framework.py) instead of executing
    if _has_static_var(flat):
        return _record_static(fn, flat, n_args, kw_tree,
                              name or getattr(fn, "__name__", "op"))

    raw = [a._value if is_t(a) else a for a in flat]

    def _diffable(a):
        v = a._value
        dt = v.dtype if hasattr(v, "dtype") else np.asarray(v).dtype
        # jnp.issubdtype, not np: bfloat16 is an ml_dtypes extension that
        # numpy's lattice calls non-inexact, which would silently freeze
        # bf16 params out of autograd
        return not a.stop_gradient and jnp.issubdtype(dt, jnp.inexact)

    diff_idx = [i for i, a in enumerate(flat)
                if is_t(a) and _diffable(a)] if is_grad_enabled() else []

    def _call(full):
        # AMP: cast inside the differentiated region (analog of the
        # reference tracer's per-op auto-cast, imperative/tracer.cc:84-87) so
        # the cast's vjp returns cotangents in the source dtype
        import sys
        amp = sys.modules.get("paddle_tpu.amp")
        if amp is not None and amp.amp_active():
            full = amp.cast_inputs(name or getattr(fn, "__name__", "op"),
                                   full)
        kw = jax.tree_util.tree_unflatten(kw_tree, full[n_args:])
        return fn(*full[:n_args], **kw)

    # host-side op annotation (reference RecordEvent around the op loop,
    # framework/operator.cc:1074); under jit this times trace/dispatch
    _rec = None
    if _flags.flag("FLAGS_enable_profiler"):
        from .. import profiler as _prof
        _rec = _prof.RecordEvent(
            "op/" + (name or getattr(fn, "__name__", "op"))).begin()

    if not diff_idx:
        out_val = _call(raw)
        if _rec is not None:
            _rec.end()
        if _flags.flag("FLAGS_check_nan_inf"):
            from .numeric_check import check_op_outputs
            check_op_outputs(name or getattr(fn, "__name__", "op"), out_val)
        return _wrap_outputs(out_val, node=None, stop_gradient=True)

    def closed(*diff_vals):
        full = list(raw)
        for i, v in zip(diff_idx, diff_vals):
            full[i] = v
        return _call(full)

    out_val, vjp_fn = jax.vjp(closed, *[raw[i] for i in diff_idx])
    if _rec is not None:
        _rec.end()
    if _flags.flag("FLAGS_check_nan_inf"):
        from .numeric_check import check_op_outputs
        check_op_outputs(name or getattr(fn, "__name__", "op"), out_val)
    multi_out = isinstance(out_val, (tuple, list))
    outs = list(out_val) if multi_out else [out_val]
    out_avals = [(tuple(o.shape), o.dtype) for o in outs]
    node = Node(vjp_fn, [flat[i] for i in diff_idx], out_avals,
                name or getattr(fn, "__name__", "op"), multi_out,
                closed_fn=closed)
    return _wrap_outputs(out_val, node=node, stop_gradient=False)


def _has_static_var(flat) -> bool:
    import sys
    mod = sys.modules.get("paddle_tpu.static.program")
    if mod is None:
        return False
    if not any(isinstance(a, mod.Variable) for a in flat):
        return False
    if not mod.in_static_mode():
        raise RuntimeError(
            "an op received static-graph Variables while dynamic mode is "
            "active; run the program through paddle.static.Executor, or "
            "re-enter paddle.enable_static() before building more graph")
    return True


def _record_static(fn, flat, n_args, kw_tree, name):
    """Append an op to the current static Program and return symbolic
    Variables with shapes inferred via jax.eval_shape (the analog of the
    reference's compile-time InferShape, framework/op_desc.cc)."""
    from ..static.program import (Variable, default_main_program,
                                  forced_program)
    from .tensor import Tensor

    program = forced_program()
    if program is None:
        for a in flat:
            if isinstance(a, Variable) and a.program is not None:
                program = a.program
                break
    program = program or default_main_program()

    def is_dyn(a):
        return isinstance(a, Tensor) or (hasattr(a, "dtype")
                                         and hasattr(a, "shape"))

    dyn_idx = [i for i, a in enumerate(flat) if is_dyn(a)]

    def abstract(a):
        if isinstance(a, Variable):
            return a.aval
        if isinstance(a, Tensor):
            return a._value
        return a

    def call(*dyn_vals):
        vals = list(flat)
        for i, v in zip(dyn_idx, dyn_vals):
            vals[i] = v
        kw = jax.tree_util.tree_unflatten(kw_tree, vals[n_args:])
        return fn(*vals[:n_args], **kw)

    # sandbox the PRNG chain: kernels may draw keys inside eval_shape's
    # trace, which must not leak tracers into the global generator
    from . import rng as _rng
    with _rng.rng_state(jax.random.PRNGKey(0)):
        out = jax.eval_shape(call, *[abstract(flat[i]) for i in dyn_idx])
    multi = isinstance(out, (tuple, list))
    avals = list(out) if multi else [out]

    # literals: eager Tensors become captured constants
    rec_args = [a._value if (isinstance(a, Tensor)
                             and not isinstance(a, Variable)) else a
                for a in flat]
    out_vars = program.append_op(fn, name, rec_args, n_args, kw_tree, avals)
    return tuple(out_vars) if multi else out_vars[0]


def _wrap_outputs(out_val, node, stop_gradient):
    from .tensor import Tensor

    def wrap_one(v, idx):
        sg = stop_gradient
        if hasattr(v, "dtype") and not jnp.issubdtype(v.dtype, jnp.inexact):
            sg = True  # integer/bool outputs never carry grad (jnp lattice:
            # bf16/f16 count as inexact, unlike numpy's)
        t = Tensor(v, stop_gradient=sg, _internal=True)
        if node is not None and not sg:
            t._node = node
            t._out_index = idx
        return t

    if isinstance(out_val, (tuple, list)):
        return tuple(wrap_one(v, i) for i, v in enumerate(out_val))
    return wrap_one(out_val, 0)


def _zero_cot(shape, dtype):
    import jax.numpy as jnp
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def _run_engine(seeds, accumulate_leaf=True, capture=None, retain_graph=False):
    """Reverse-topological sweep.

    seeds: list[(tensor, cotangent_array)]
    capture: optional dict id(tensor)->slot to collect grads for paddle.grad
    """
    # cot maps (node_id, out_index) -> accumulated cotangent
    cot = {}
    node_by_id = {}
    leaf_grads = {}

    def seed_tensor(t, g):
        if t._node is None:
            key = id(t)
            leaf_grads[key] = g if key not in leaf_grads else leaf_grads[key] + g
        else:
            k = (id(t._node), t._out_index)
            node_by_id[id(t._node)] = t._node
            cot[k] = g if k not in cot else cot[k] + g

    for t, g in seeds:
        seed_tensor(t, g)

    # reachable set
    seen = set()
    stack = [t._node for t, _ in seeds if t._node is not None]
    order = []
    while stack:
        n = stack.pop()
        if n is None or id(n) in seen:
            continue
        seen.add(id(n))
        node_by_id[id(n)] = n
        order.append(n)
        for inp in n.inputs:
            if inp._node is not None:
                stack.append(inp._node)

    # process in reverse creation order (valid topological order)
    order.sort(key=lambda n: n.seq, reverse=True)

    for n in order:
        outs_cot = [cot.pop((id(n), i), None) for i in range(len(n.out_avals))]
        if all(c is None for c in outs_cot):
            continue
        full = [c if c is not None else _zero_cot(*n.out_avals[i])
                for i, c in enumerate(outs_cot)]
        if n.out_hooks:
            # Tensor.register_hook: fires with the tensor's accumulated
            # grad; a non-None return REPLACES the grad flowing upstream
            # (reference imperative/hooks.h GradAccumulatorPostHook)
            from .tensor import Tensor
            for i, hooks in n.out_hooks.items():
                for h in hooks:
                    res = h(Tensor(full[i], stop_gradient=True,
                                   _internal=True))
                    if res is not None:
                        full[i] = res._value if isinstance(res, Tensor) \
                            else res
        if n.vjp_fn is None:
            raise RuntimeError(
                f"grad graph for op '{n.name}' was already freed; "
                "pass retain_graph=True to backward() to reuse it")
        arg = tuple(full) if n.multi_out else full[0]
        in_cots = n.vjp_fn(arg)
        if not retain_graph:
            n.vjp_fn = None  # free residual memory, like eager GC of grad graph
        for inp, g in zip(n.inputs, in_cots):
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            if g is None or inp.stop_gradient:
                continue  # PyLayer may list non-diff inputs; drop their cots
            if inp._node is not None:
                k = (id(inp._node), inp._out_index)
                cot[k] = g if k not in cot else cot[k] + g
            else:
                key = id(inp)
                leaf_grads[key] = g if key not in leaf_grads else leaf_grads[key] + g
            if capture is not None and id(inp) in capture:
                capture[id(inp)] = (g if capture[id(inp)] is None
                                    else capture[id(inp)] + g)

    return leaf_grads


def backward(tensor, grad_tensor=None, retain_graph=False):
    """Tensor.backward(): accumulate .grad on leaf tensors."""
    import jax.numpy as jnp
    from .tensor import Tensor

    if tensor.stop_gradient:
        raise RuntimeError("backward() on a tensor with stop_gradient=True")
    if grad_tensor is None:
        g = jnp.ones(tensor.shape, tensor._value.dtype)
    else:
        g = grad_tensor._value if isinstance(grad_tensor, Tensor) else grad_tensor

    # track leaves reachable so we can assign .grad; walk graph collecting leaf tensors
    leaves = {}
    stack = [tensor]
    seen_nodes = set()
    while stack:
        t = stack.pop()
        if t._node is None:
            leaves[id(t)] = t
            continue
        if id(t._node) in seen_nodes:
            continue
        seen_nodes.add(id(t._node))
        stack.extend(t._node.inputs)

    leaf_grads = _run_engine([(tensor, g)], retain_graph=retain_graph)
    if tensor._node is None:
        leaf_grads.setdefault(id(tensor), g)

    for key, gval in leaf_grads.items():
        leaf = leaves.get(key)
        if leaf is None and key == id(tensor):
            leaf = tensor
        if leaf is None:
            continue
        for h in (getattr(leaf, "_leaf_hooks", None) or ()):
            res = h(Tensor(gval, stop_gradient=True, _internal=True))
            if res is not None:
                gval = res._value if isinstance(res, Tensor) else res
        if leaf.grad is None:
            leaf.grad = Tensor(gval, stop_gradient=True, _internal=True)
        else:
            leaf.grad = Tensor(leaf.grad._value + gval, stop_gradient=True,
                               _internal=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """paddle.grad equivalent (PartialGradEngine, partial_grad_engine.cc).

    Returns grads of `outputs` w.r.t. `inputs` without touching .grad.
    create_graph (double backward) is not yet supported.
    """
    import jax.numpy as jnp
    from .tensor import Tensor

    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    seeds = []
    for o, go in zip(outputs, grad_outputs):
        g = (go._value if isinstance(go, Tensor) else go) if go is not None \
            else jnp.ones(o.shape, o._value.dtype)
        seeds.append((o, g))

    capture = {id(t): None for t in inputs}
    if create_graph:
        # recorded backward: gradients come out tape-linked (Tensors) and
        # the primal graph is left intact (vjp closures untouched) —
        # retain implied
        leaf_grads = _run_engine_recorded(seeds, capture=capture)
        results = []
        for t in inputs:
            seed_g = None
            for o, gg in seeds:
                if o is t:
                    seed_g = gg if seed_g is None else seed_g + gg
            if t._node is None:
                gval = leaf_grads.get(id(t))
                if gval is None:
                    gval = capture[id(t)]
            else:
                gval = capture[id(t)]
            if seed_g is not None:
                seed_t = Tensor(seed_g, stop_gradient=True, _internal=True)
                gval = seed_t if gval is None else record_op(
                    jnp.add, (gval, seed_t), {}, name="grad_accumulate")
            if gval is None:
                if not allow_unused:
                    raise RuntimeError(
                        "one of the inputs was not used in the graph "
                        "(pass allow_unused=True to get None)")
                results.append(None)
                continue
            results.append(gval)
        return results

    retain = bool(retain_graph) if retain_graph is not None else False
    leaf_grads = _run_engine(seeds, capture=capture, retain_graph=retain)

    results = []
    for t in inputs:
        seed_g = None  # identity cotangent when the input IS an output
        for o, g in seeds:
            if o is t:
                seed_g = g if seed_g is None else seed_g + g
        if t._node is None:
            # leaf: the engine merges seed + consumer paths into leaf_grads
            gval = leaf_grads.get(id(t))
            if gval is None:
                gval = capture[id(t)]
            if gval is None:
                gval = seed_g
        else:
            # non-leaf: capture holds consumer-path grads only; the seed
            # contribution must be SUMMED in, not used as a mere fallback
            gval = capture[id(t)]
            if seed_g is not None:
                gval = seed_g if gval is None else gval + seed_g
        if gval is None:
            if not allow_unused:
                raise RuntimeError("one of the inputs was not used in the graph "
                                   "(pass allow_unused=True to get None)")
            results.append(None)
        else:
            results.append(Tensor(gval, stop_gradient=True, _internal=True))
    return results


def _run_engine_recorded(seeds, capture=None):
    """create_graph backward: same reverse-topological sweep as
    _run_engine, but every node's vjp is RE-DERIVED from its stored primal
    closure inside record_op — so the produced gradients are themselves
    tape-linked Tensors and differentiate again (the reference's
    imperative partial_grad_engine create_graph mode; double backward for
    gradient penalties etc.). Costs one extra forward per node, the
    standard price of re-execution-based higher-order autodiff."""
    import jax.numpy as jnp

    from .tensor import Tensor

    def as_tensor(v):
        return v if isinstance(v, Tensor) else Tensor(v, stop_gradient=True,
                                                      _internal=True)

    def add_t(a, b):
        return record_op(jnp.add, (a, b), {}, name="grad_accumulate")

    cot = {}
    leaf_grads = {}

    def seed_tensor(t, g):
        g = as_tensor(g)
        if t._node is None:
            key = id(t)
            leaf_grads[key] = g if key not in leaf_grads \
                else add_t(leaf_grads[key], g)
        else:
            k = (id(t._node), t._out_index)
            cot[k] = g if k not in cot else add_t(cot[k], g)

    for t, g in seeds:
        seed_tensor(t, g)

    seen = set()
    stack = [t._node for t, _ in seeds if t._node is not None]
    order = []
    while stack:
        n = stack.pop()
        if n is None or id(n) in seen:
            continue
        seen.add(id(n))
        order.append(n)
        for inp in n.inputs:
            if inp._node is not None:
                stack.append(inp._node)
    order.sort(key=lambda n: n.seq, reverse=True)

    for n in order:
        outs_cot = [cot.pop((id(n), i), None)
                    for i in range(len(n.out_avals))]
        if all(c is None for c in outs_cot):
            continue
        if n.closed_fn is None:
            raise RuntimeError(
                f"create_graph backward through op '{n.name}' which has no "
                "re-derivable primal (graph built before this feature?)")
        full = [c if c is not None
                else Tensor(jnp.zeros(n.out_avals[i][0],
                                      n.out_avals[i][1]),
                            stop_gradient=True, _internal=True)
                for i, c in enumerate(outs_cot)]
        if n.out_hooks:
            for i, hooks in n.out_hooks.items():
                for h in hooks:
                    res = h(full[i])
                    if res is not None:
                        full[i] = as_tensor(res)

        k = len(n.inputs)

        def bwd(*vals, _closed=n.closed_fn, _multi=n.multi_out, _k=k):
            prim, cots = vals[:_k], vals[_k:]
            _, vjp = jax.vjp(_closed, *prim)
            arg = tuple(cots) if _multi else cots[0]
            return tuple(vjp(arg))

        in_cots = record_op(bwd, (*n.inputs, *full), {},
                            name=n.name + "_grad")
        in_cots = in_cots if isinstance(in_cots, (tuple, list)) \
            else [in_cots]
        for inp, g in zip(n.inputs, in_cots):
            gv = g._value if isinstance(g, Tensor) else g
            if isinstance(gv, np.ndarray) and gv.dtype == jax.dtypes.float0:
                continue
            if g is None or inp.stop_gradient:
                continue
            g = as_tensor(g)
            if inp._node is not None:
                key = (id(inp._node), inp._out_index)
                cot[key] = g if key not in cot else add_t(cot[key], g)
            else:
                key = id(inp)
                leaf_grads[key] = g if key not in leaf_grads \
                    else add_t(leaf_grads[key], g)
            if capture is not None and id(inp) in capture:
                capture[id(inp)] = (g if capture[id(inp)] is None
                                    else add_t(capture[id(inp)], g))

    return leaf_grads
