"""Global runtime counters.

Analog of the reference monitor (reference platform/monitor.h:77
StatRegistry singleton, STAT_ADD :130 — process-wide named counters like
GPU memory stats, exported to Python through
pybind/global_value_getter_setter.cc). Same shape here: cheap named
int/float counters the runtime bumps at interesting points (program
lowerings, train steps, dataloader batches), snapshotted for dashboards
and tests.
"""
from __future__ import annotations

import threading
from collections import defaultdict

__all__ = ["stat_add", "stat_set", "stat_set_many", "stat_get", "stats",
           "reset"]

_lock = threading.Lock()
_stats = defaultdict(float)


def stat_add(name: str, value=1):
    """STAT_ADD analog (reference monitor.h:130)."""
    with _lock:
        _stats[name] += value


def stat_set(name: str, value):
    with _lock:
        _stats[name] = value


def stat_set_many(values: dict):
    """Set a group of gauges atomically (one lock round-trip) — e.g. the
    spmd.{collective_bytes,hbm_estimate,resharding_count} trio published
    by static/spmd_analyzer.py SpmdReport.publish()."""
    with _lock:
        _stats.update(values)


def stat_get(name: str):
    with _lock:
        return _stats.get(name, 0)


def stats(prefix: str = None) -> dict:
    """Snapshot all counters; `prefix` filters to one subsystem (e.g.
    stats("ps.rpc.") for the PS transport health counters)."""
    with _lock:
        if prefix is None:
            return dict(_stats)
        return {k: v for k, v in _stats.items() if k.startswith(prefix)}


def reset(name: str = None, prefix: str = None):
    """Drop one counter, every counter under a prefix (e.g.
    reset(prefix="pallas.") between bench modes), or everything."""
    with _lock:
        if prefix is not None:
            for k in [k for k in _stats if k.startswith(prefix)]:
                del _stats[k]
        elif name is None:
            _stats.clear()
        else:
            _stats.pop(name, None)
