"""Global runtime metrics: counters, gauges, histograms, time series.

Analog of the reference monitor (reference platform/monitor.h:77
StatRegistry singleton, STAT_ADD :130 — process-wide named counters like
GPU memory stats, exported to Python through
pybind/global_value_getter_setter.cc), grown into a typed registry:

- **Counters / gauges** keep the original `stat_add`/`stat_set`/`stats()`
  surface — every existing gauge name (`executor/runs`, `ps.rpc.retries`,
  `ps.replica.{forwards,promotions,catchups,stale_maps}`,
  `pallas.fallback.*`, `spmd.*`) works unchanged. A counter is any name
  first touched by `stat_add`, a gauge any name first touched by
  `stat_set` — the distinction only matters to the Prometheus export.
- **Time series**: every write appends `(unix_time, value)` to a bounded
  per-name ring (FLAGS_monitor_series_len), so a dump or dashboard can
  see the last N minutes of a counter's trajectory, not just its final
  value. The flight recorder (core/flight_recorder.py) snapshots these.
- **Histograms**: `observe(name, v)` records value distributions
  (count/sum/min/max + Prometheus-style cumulative buckets) — step wall
  times, RPC latencies — without unbounded memory.
- **Export**: `snapshot()` (structured dict; the dump format),
  `export_jsonl()` (one JSON line per metric), `prometheus_text()`
  (text exposition format for scrape endpoints).

Concurrency: ONE lock guards every structure, and `reset(prefix=...)`
clears values, types, series, and histograms in a single critical
section. That atomicity is load-bearing for benches: bench.py resets
`pallas.`/`executor/` between modes while pipeline prefetch and
communicator send threads are still writing — a reset that cleared the
value map and the series map in separate lock acquisitions would let a
racing `stat_add` resurrect a just-reset counter with its stale series
attached, and the next mode's report would carry the previous mode's
samples (tests/test_monitor_metrics.py pins the invariant).
"""
from __future__ import annotations

import json
import re
import threading
import time
from collections import defaultdict, deque

__all__ = ["stat_add", "stat_set", "stat_set_many", "stat_get", "stats",
           "reset", "observe", "ensure_hist", "counter", "gauge",
           "histogram", "series", "histogram_summary", "snapshot",
           "export_jsonl", "prometheus_text", "DEFAULT_BUCKETS",
           "Counter", "Gauge", "Histogram"]

_lock = threading.Lock()
_stats = defaultdict(float)
_types: dict = {}      # name -> "counter" | "gauge" | "histogram"
_series: dict = {}     # name -> deque[(unix_ts, value)]
_hists: dict = {}      # name -> _Hist

# Latency-ish spread in ms; callers with other units pass explicit buckets.
DEFAULT_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                   100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)


def _series_len():
    try:
        from . import flags as _flags
        return max(1, int(_flags.flag("FLAGS_monitor_series_len")))
    except Exception:
        return 256


def _sample_locked(name, value):
    s = _series.get(name)
    if s is None:
        s = _series[name] = deque(maxlen=_series_len())
    s.append((time.time(), float(value)))


class _Hist:
    __slots__ = ("count", "sum", "mn", "mx", "bounds", "buckets")

    def __init__(self, bounds):
        self.bounds = tuple(sorted(float(b) for b in bounds))
        self.buckets = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.count = 0
        self.sum = 0.0
        self.mn = float("inf")
        self.mx = float("-inf")

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.sum += v
        self.mn = min(self.mn, v)
        self.mx = max(self.mx, v)
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.buckets[i] += 1
                return
        self.buckets[-1] += 1

    def summary(self):
        return {"count": self.count, "sum": self.sum,
                "min": self.mn if self.count else 0.0,
                "max": self.mx if self.count else 0.0,
                "avg": (self.sum / self.count) if self.count else 0.0,
                "bounds": list(self.bounds), "buckets": list(self.buckets)}


# -- writers (back-compat surface) -------------------------------------------

def stat_add(name: str, value=1):
    """STAT_ADD analog (reference monitor.h:130)."""
    with _lock:
        _stats[name] += value
        _types.setdefault(name, "counter")
        _sample_locked(name, _stats[name])


def stat_set(name: str, value):
    with _lock:
        _stats[name] = value
        _types.setdefault(name, "gauge")
        _sample_locked(name, value)


def stat_set_many(values: dict):
    """Set a group of gauges atomically (one lock round-trip) — e.g. the
    spmd.{collective_bytes,hbm_estimate,resharding_count} trio published
    by static/spmd_analyzer.py SpmdReport.publish()."""
    with _lock:
        for name, value in values.items():
            _stats[name] = value
            _types.setdefault(name, "gauge")
            _sample_locked(name, value)


def observe(name: str, value, buckets=None):
    """One histogram observation (also sampled into the time series)."""
    with _lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = _Hist(buckets or DEFAULT_BUCKETS)
            _types.setdefault(name, "histogram")
        h.observe(value)
        _sample_locked(name, value)


def ensure_hist(name: str, buckets):
    """Pre-register a histogram with explicit bucket bounds. A histogram's
    bounds are fixed by whoever observes it first; latency consumers that
    need finer resolution than DEFAULT_BUCKETS (the traffic harness scores
    serve/ttft_ms against a ±25% error band) register theirs up front,
    before the serving path's first `observe` wins with the defaults."""
    with _lock:
        if name not in _hists:
            _hists[name] = _Hist(buckets)
            _types.setdefault(name, "histogram")


# -- readers -----------------------------------------------------------------

def stat_get(name: str):
    with _lock:
        return _stats.get(name, 0)


def stats(prefix: str = None) -> dict:
    """Snapshot all counters/gauges (histograms surface as
    `{name}.count/.sum/.min/.max/.avg`); `prefix` filters to one
    subsystem (e.g. stats("ps.rpc.") for the PS transport health
    counters)."""
    with _lock:
        out = dict(_stats)
        for name, h in _hists.items():
            s = h.summary()
            for k in ("count", "sum", "min", "max", "avg"):
                out[f"{name}.{k}"] = s[k]
    if prefix is None:
        return out
    return {k: v for k, v in out.items() if k.startswith(prefix)}


def series(name: str):
    """[(unix_ts, value), ...] ring for one metric (newest last)."""
    with _lock:
        s = _series.get(name)
        return list(s) if s else []


def histogram_summary(name: str):
    with _lock:
        h = _hists.get(name)
        return h.summary() if h else None


def snapshot(include_series: bool = True) -> dict:
    """One consistent structured snapshot of everything — the flight
    recorder's `metrics` section and bench's per-mode metrics line."""
    with _lock:
        out = {"values": dict(_stats),
               "types": dict(_types),
               "histograms": {n: h.summary() for n, h in _hists.items()}}
        if include_series:
            out["series"] = {n: [list(p) for p in s]
                             for n, s in _series.items() if s}
    return out


# -- reset -------------------------------------------------------------------

def reset(name: str = None, prefix: str = None):
    """Drop one counter, every counter under a prefix (e.g.
    reset(prefix="pallas.") between bench modes), or everything.
    Values, types, series, and histograms are cleared in ONE critical
    section, so a concurrent writer observes either the fully-old or the
    fully-new world — never a value without its series or vice versa."""
    with _lock:
        if prefix is not None:
            for store in (_stats, _types, _series, _hists):
                for k in [k for k in store if k.startswith(prefix)]:
                    del store[k]
        elif name is None:
            _stats.clear()
            _types.clear()
            _series.clear()
            _hists.clear()
        else:
            for store in (_stats, _types, _series, _hists):
                store.pop(name, None)


# -- typed handles -----------------------------------------------------------

class Counter:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name
        with _lock:
            _types.setdefault(name, "counter")

    def add(self, value=1):
        stat_add(self.name, value)

    def value(self):
        return stat_get(self.name)


class Gauge:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name
        with _lock:
            _types.setdefault(name, "gauge")

    def set(self, value):
        stat_set(self.name, value)

    def value(self):
        return stat_get(self.name)


class Histogram:
    __slots__ = ("name", "buckets")

    def __init__(self, name, buckets=None):
        self.name = name
        self.buckets = buckets

    def observe(self, value):
        observe(self.name, value, buckets=self.buckets)

    def summary(self):
        return histogram_summary(self.name)


def counter(name) -> Counter:
    return Counter(name)


def gauge(name) -> Gauge:
    return Gauge(name)


def histogram(name, buckets=None) -> Histogram:
    return Histogram(name, buckets)


# -- export ------------------------------------------------------------------

def export_jsonl(path_or_file, include_series: bool = True):
    """One JSON line per metric: {"name", "type", "value" | histogram
    aggregates, "series": [[ts, v], ...]}. Tailable by any dashboard."""
    snap = snapshot(include_series=include_series)
    own = isinstance(path_or_file, str)
    f = open(path_or_file, "w") if own else path_or_file
    try:
        names = set(snap["values"]) | set(snap["histograms"])
        for name in sorted(names):
            rec = {"name": name,
                   "type": snap["types"].get(name, "gauge")}
            if name in snap["histograms"]:
                rec["histogram"] = snap["histograms"][name]
            else:
                rec["value"] = snap["values"][name]
            if include_series and name in snap.get("series", {}):
                rec["series"] = snap["series"][name]
            f.write(json.dumps(rec) + "\n")
    finally:
        if own:
            f.close()


def _prom_name(name: str) -> str:
    n = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return n if re.match(r"[a-zA-Z_:]", n) else "_" + n


def prometheus_text() -> str:
    """Prometheus text exposition format (counters/gauges/histograms)."""
    snap = snapshot(include_series=False)
    lines = []
    for name in sorted(snap["values"]):
        pn = _prom_name(name)
        kind = snap["types"].get(name, "gauge")
        lines.append(f"# TYPE {pn} {kind}")
        lines.append(f"{pn} {snap['values'][name]}")
    for name in sorted(snap["histograms"]):
        pn = _prom_name(name)
        h = snap["histograms"][name]
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        for bound, cnt in zip(h["bounds"], h["buckets"]):
            cum += cnt
            lines.append(f'{pn}_bucket{{le="{bound}"}} {cum}')
        cum += h["buckets"][-1]
        lines.append(f'{pn}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{pn}_sum {h['sum']}")
        lines.append(f"{pn}_count {h['count']}")
    return "\n".join(lines) + "\n"
