"""RaggedTensor — the LoDTensor analog.

The reference threads ragged structure through LoDTensor (reference
framework/lod_tensor.h: a dense buffer + level-of-detail offset table) and
~40 sequence_* ops that walk the offsets. XLA wants static shapes, so the
TPU-native design (SURVEY hard part 1) maps ragged data to the two forms
compilers love:

- **packed**: values [total, ...] + row_splits [n+1] (= the reference's
  level-0 LoD offsets verbatim) — segment-reduction ops consume this via
  segment ids;
- **padded**: dense [n, maxlen, ...] + lengths [n] — attention/matmul ops
  consume this with masks.

`RaggedTensor` holds the packed form, converts losslessly to/from padded,
and exposes the reference's recursive_sequence_lengths/lod accessors.
Sequence ops over it live in ops/sequence.py.
"""
from __future__ import annotations

import numpy as np

__all__ = ["RaggedTensor"]


class RaggedTensor:
    __slots__ = ("values", "row_splits")

    def __init__(self, values, row_splits):
        import jax.numpy as jnp
        self.values = values if hasattr(values, "dtype") \
            else jnp.asarray(values)
        self.row_splits = jnp.asarray(row_splits, jnp.int32)

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_rows(rows):
        """From a list of per-sequence arrays."""
        import jax.numpy as jnp
        lengths = [int(np.shape(r)[0]) for r in rows]
        splits = np.zeros(len(rows) + 1, np.int32)
        np.cumsum(lengths, out=splits[1:])
        values = jnp.concatenate([jnp.asarray(r) for r in rows], axis=0) \
            if rows else jnp.zeros((0,), jnp.float32)
        return RaggedTensor(values, splits)

    @staticmethod
    def from_padded(padded, lengths):
        """Inverse of to_padded: gather the valid prefix of every row.
        Eager-only (output length is data-dependent)."""
        import jax.numpy as jnp
        lengths = np.asarray(lengths, np.int64)
        rows = [np.asarray(padded[i, :int(n)]) for i, n in enumerate(lengths)]
        out = RaggedTensor.from_rows([jnp.asarray(r) for r in rows])
        return out

    # -- reference LoD accessors -------------------------------------------
    @property
    def lod(self):
        """Level-0 offsets, the reference LoD table (lod_tensor.h)."""
        return [list(np.asarray(self.row_splits))]

    def recursive_sequence_lengths(self):
        s = np.asarray(self.row_splits)
        return [list((s[1:] - s[:-1]).astype(np.int64))]

    @property
    def lengths(self):
        return self.row_splits[1:] - self.row_splits[:-1]

    @property
    def nrows(self):
        return int(self.row_splits.shape[0]) - 1

    @property
    def dtype(self):
        return self.values.dtype

    # -- segment ids: what segment-reduction kernels consume ---------------
    def segment_ids(self):
        """int32 [total]: row index of every value (the ragged->segment-ids
        mapping XLA ops reduce over)."""
        import jax.numpy as jnp
        total = self.values.shape[0]
        return jnp.searchsorted(self.row_splits[1:],
                                jnp.arange(total, dtype=jnp.int32),
                                side="right").astype(jnp.int32)

    # -- padded <-> packed --------------------------------------------------
    def to_padded(self, maxlen=None, pad_value=0):
        """Dense [n, maxlen, ...] + the mask implied by self.lengths.
        maxlen must be static under jit (defaults to max length, eager)."""
        import jax.numpy as jnp
        lens = self.lengths
        if maxlen is None:
            maxlen = int(np.asarray(lens).max()) if self.nrows else 0
        n = self.nrows
        tail = self.values.shape[1:]
        idx = self.row_splits[:-1, None] + jnp.arange(maxlen)[None, :]
        valid = jnp.arange(maxlen)[None, :] < lens[:, None]
        idx = jnp.clip(idx, 0, max(self.values.shape[0] - 1, 0))
        out = self.values[idx.reshape(-1)].reshape((n, maxlen) + tail)
        mask = valid.reshape((n, maxlen) + (1,) * len(tail))
        return jnp.where(mask, out, jnp.asarray(pad_value, out.dtype))

    def to_list(self):
        s = np.asarray(self.row_splits)
        v = np.asarray(self.values)
        return [v[s[i]:s[i + 1]] for i in range(self.nrows)]

    def __repr__(self):
        return (f"RaggedTensor(nrows={self.nrows}, "
                f"values={tuple(self.values.shape)}, dtype={self.dtype})")
