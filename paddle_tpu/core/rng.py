"""Random state.

The reference threads per-device curand generators through DeviceContext
(reference: paddle/fluid/platform/device_context.h:297). TPU-native design:
one functional PRNG key chain (jax.random) held in a `Generator`. Eager ops
split the key per call; traced training steps re-seat the chain on an
explicit per-step key (see hapi/model.py) so compiled steps get fresh
randomness without retracing.
"""
from __future__ import annotations

import contextlib
import threading

import jax


class Generator:
    """A splittable PRNG chain. `next_key()` advances the chain."""

    def __init__(self, seed: int = 0):
        self._key = jax.random.PRNGKey(seed)
        self._lock = threading.Lock()

    def manual_seed(self, seed: int):
        self._key = jax.random.PRNGKey(seed)
        return self

    def seat(self, key):
        """Replace the chain head (used by jitted steps to thread step keys)."""
        self._key = key

    def next_key(self):
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub


_default = Generator(0)


def default_generator() -> Generator:
    return _default


def seed(value: int):
    """paddle.seed equivalent."""
    _default.manual_seed(int(value))
    return _default


def next_key():
    return _default.next_key()


@contextlib.contextmanager
def rng_state(key):
    """Temporarily seat the global chain on `key` (used inside traced steps)."""
    old = _default._key
    _default.seat(key)
    try:
        yield
    finally:
        _default.seat(old)
