"""Random state.

The reference threads per-device curand generators through DeviceContext
(reference: paddle/fluid/platform/device_context.h:297). TPU-native design:
one functional PRNG key chain (jax.random) held in a `Generator`. Eager ops
split the key per call; traced training steps re-seat the chain on an
explicit per-step key (see hapi/model.py) so compiled steps get fresh
randomness without retracing.
"""
from __future__ import annotations

import contextlib
import threading

import jax


class Generator:
    """A splittable PRNG chain. `next_key()` advances the chain.

    Key creation is LAZY (first use, not construction): the module-level
    default generator is built at import time, and materializing a key
    there would initialize the XLA backend — which must not happen before
    a multi-host job calls jax.distributed.initialize
    (distributed/bootstrap.py)."""

    def __init__(self, seed: int = 0):
        self._seed = seed
        self._key_ = None
        self._lock = threading.Lock()

    @property
    def _key(self):
        if self._key_ is None:
            self._key_ = jax.random.PRNGKey(self._seed)
        return self._key_

    @_key.setter
    def _key(self, value):
        self._key_ = value

    def manual_seed(self, seed: int):
        self._seed = seed
        self._key_ = None  # stays lazy: re-materialized on next use
        return self

    def seat(self, key):
        """Replace the chain head (used by jitted steps to thread step keys)."""
        self._key = key

    def next_key(self):
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub


_default = Generator(0)


def default_generator() -> Generator:
    return _default


def seed(value: int):
    """paddle.seed equivalent."""
    _default.manual_seed(int(value))
    return _default


def next_key():
    return _default.next_key()


@contextlib.contextmanager
def rng_state(key):
    """Temporarily seat the global chain on `key` (used inside traced steps)."""
    old = _default._key
    _default.seat(key)
    try:
        yield
    finally:
        _default.seat(old)
