"""Cluster telemetry plane: every fleet process ships its monitor
registry and finished spans to one `TelemetryHub`, which merges them,
evaluates SLOs, and coordinates incident capture.

The design rides what PRs 2/7 already built instead of inventing a
second transport:

  - The hub is an `rpc.serve()` endpoint with a shared `ReplayCache`.
    A `TelemetryShipper` ships each flush as ONE mutating call whose
    replay key is pinned to the shipment's sequence number
    (`(client_id, seq)`), so a batch retried through RESET/DROP chaos
    or a reconnect is applied exactly once — counter deltas are safe to
    sum at the hub, bitwise.
  - Merge semantics by metric type: counters ship as DELTAS against the
    last acked snapshot and the hub sums them; gauges are last-wins;
    histograms ship their full cumulative summary per process and merge
    bucket-wise at read time (core/slo.py merge_hists); spans ship in
    bounded batches.
  - The hot path never blocks on telemetry: finished spans land in a
    bounded in-memory buffer via a trace sink (overflow sheds and
    counts `telemetry.dropped_spans` / `telemetry.dropped_batches`);
    the monitor registry is only read, on the shipper's own thread;
    the shipper's connection is `quiet` so shipping the stream does not
    feed back into it.
  - Incident protocol: a member's flight-recorder trigger (transport
    death, PipelineStepError, signal — register_dump_listener) reports
    to the hub; the hub opens an incident (or joins one open within
    PADDLE_TELEMETRY_INCIDENT_WINDOW_S) and piggybacks the incident id
    on every ship ack, so the WHOLE fleet dumps the same window under
    one id within a flush cadence. Member records merge into
    `incident_<id>.json`, rendered by `tools/obs_report.py --incident`.
    SLO breaches found by the hub's burn-rate engine open incidents the
    same way.

See docs/observability.md "Cluster telemetry" / "SLOs and incidents".
"""
import json
import os
import threading
import time
import uuid
from collections import OrderedDict, deque

from . import flags as _flags
from . import flight_recorder as _fr
from . import monitor as _monitor
from . import slo as _slo
from . import trace as _trace

__all__ = ["TelemetryHub", "TelemetryShipper", "fetch_snapshot",
           "stitch_incident", "INCIDENT_SCHEMA"]

# merged incident file format version (distinct from the per-process
# flight-recorder schema: an incident file CONTAINS member records)
INCIDENT_SCHEMA = 1

_DEF_RPC_OPTS = dict(timeout=5.0, max_retries=2, backoff_base=0.05,
                     backoff_max=0.5, connect_retry_s=5.0)

# at most this many spans ride one shipment — bounds the frame size;
# the rest stay buffered for the next flush
MAX_SPANS_PER_SHIP = 512


def _flag(name):
    return _flags.flag(name)


def _rpc():
    # lazy: core must stay importable without the ps package loaded
    from ..distributed.ps import rpc
    return rpc


# --------------------------------------------------------------------------
# hub
# --------------------------------------------------------------------------

class TelemetryHub:
    """The aggregation endpoint. Thread-safe; one instance per cluster
    (typically in the supervisor / drill parent process).

    `specs` is a list of slo.SLOSpec evaluated every PADDLE_SLO_EVAL_S
    seconds over the MERGED counters/histograms; breaches append
    structured alerts and open an incident. `dump_dir` (default
    PADDLE_TPU_DUMP_DIR) is where merged `incident_<id>.json` files go.
    """

    def __init__(self, endpoint="127.0.0.1:0", specs=(), dump_dir=None,
                 fast_s=None, slow_s=None, eval_s=None,
                 burn_threshold=1.0, incident_window_s=None,
                 span_capacity=65536, clock=time.time):
        rpc = _rpc()
        self._clock = clock
        self._lock = threading.Lock()
        self._members: OrderedDict = OrderedDict()
        self._counters: dict = {}
        self._member_counters: dict = {}
        self._gauges: dict = {}
        self._member_hists: dict = {}
        self._spans: deque = deque(maxlen=int(span_capacity))
        self.alerts: list = []
        self._incidents: OrderedDict = OrderedDict()
        self._open_incident = None
        self._incident_window_s = float(
            _flag("PADDLE_TELEMETRY_INCIDENT_WINDOW_S")
            if incident_window_s is None else incident_window_s)
        self._dump_dir = (dump_dir if dump_dir is not None
                          else os.environ.get("PADDLE_TPU_DUMP_DIR", ""))
        self._member_id = f"hub-{os.getpid()}"
        self.engine = _slo.SLOEngine(
            specs,
            fast_s=(_flag("PADDLE_SLO_FAST_WINDOW_S")
                    if fast_s is None else fast_s),
            slow_s=(_flag("PADDLE_SLO_SLOW_WINDOW_S")
                    if slow_s is None else slow_s),
            burn_threshold=burn_threshold, now=clock)
        self._eval_s = float(_flag("PADDLE_SLO_EVAL_S")
                             if eval_s is None else eval_s)
        self._stop = threading.Event()
        self._replay = rpc.ReplayCache()
        host = endpoint.rsplit(":", 1)[0]
        port, self._serve_thread = rpc.serve(
            endpoint, self._handle, self._stop, replay=self._replay)
        self.endpoint = f"{host}:{port}"
        # prime the burn-rate series with a t0 baseline so the very
        # first real evaluation has a reference point to diff against
        self.evaluate()
        self._eval_thread = threading.Thread(
            target=self._eval_loop, daemon=True,
            name="telemetry-hub-slo")
        self._eval_thread.start()

    # ------------------------------------------------------------- rpc side
    def _handle(self, method, req, rid):
        if method == "telemetry_ship":
            return self._apply_ship(req)
        if method == "telemetry_incident":
            iid, _ = self._open_or_join(
                req.get("reason", "unknown"),
                trigger=req.get("member"))
            return {"incident_id": iid}
        if method == "telemetry_incident_dump":
            return {"attached": self._attach_record(
                req.get("incident_id"), req.get("member"),
                req.get("record"))}
        if method == "telemetry_snapshot":
            return self.snapshot()
        if method == "telemetry_spans":
            with self._lock:
                return [dict(s, member=m, role=r, pid=p)
                        for m, r, p, s in list(self._spans)]
        raise ValueError(f"telemetry hub: unknown method {method!r}")

    def _apply_ship(self, req):
        member = str(req.get("member"))
        now = self._clock()
        counters = req.get("counters") or {}
        gauges = req.get("gauges") or {}
        hists = req.get("hists") or {}
        spans = req.get("spans") or ()
        with self._lock:
            m = self._members.get(member)
            if m is None:
                m = self._members[member] = {
                    "role": req.get("role", ""),
                    "pid": req.get("pid"),
                    "first": now, "ships": 0, "spans": 0}
            m["last"] = now
            m["ships"] += 1
            mc = self._member_counters.setdefault(member, {})
            for name, d in counters.items():
                self._counters[name] = self._counters.get(name, 0.0) + d
                mc[name] = mc.get(name, 0.0) + d
            for name, v in gauges.items():
                self._gauges[name] = v
            if hists:
                self._member_hists.setdefault(member, {}).update(hists)
            for s in spans:
                self._spans.append((member, m["role"], m["pid"], s))
            m["spans"] += len(spans)
            incident = self._pending_incident_locked(member, now)
        return {"ok": True, "incident": incident}

    def _pending_incident_locked(self, member, now):
        iid = self._open_incident
        if iid is None:
            return None
        inc = self._incidents[iid]
        if now - inc["time"] > self._incident_window_s:
            self._open_incident = None
            return None
        if member in inc["members"]:
            return None
        return {"id": iid, "reason": inc["reason"]}

    # -------------------------------------------------------- incident flow
    def _open_or_join(self, reason, trigger=None, now=None):
        """Returns (incident_id, opened): triggers within the
        coalescing window of an open incident JOIN it."""
        now = self._clock() if now is None else now
        with self._lock:
            iid = self._open_incident
            if iid is not None:
                inc = self._incidents[iid]
                if now - inc["time"] <= self._incident_window_s:
                    if trigger and trigger not in inc["triggers"]:
                        inc["triggers"].append(trigger)
                    return iid, False
            iid = "inc_" + uuid.uuid4().hex[:10]
            inc = self._incidents[iid] = {
                "incident_id": iid, "reason": reason, "time": now,
                "triggers": [trigger] if trigger else [],
                "alerts": [], "members": {}}
            self._open_incident = iid
        self._write_incident(iid)
        return iid, True

    def _attach_record(self, incident_id, member, record):
        with self._lock:
            inc = self._incidents.get(incident_id)
            if inc is None or not member:
                return False
            inc["members"][str(member)] = record
        self._write_incident(incident_id)
        return True

    def _write_incident(self, incident_id):
        d = self._dump_dir
        if not d:
            return None
        with self._lock:
            inc = self._incidents.get(incident_id)
            if inc is None:
                return None
            payload = {"schema": INCIDENT_SCHEMA,
                       "slo_specs": [s.to_dict()
                                     for s in self.engine.specs],
                       **{k: (dict(v) if isinstance(v, dict) else
                              list(v) if isinstance(v, list) else v)
                          for k, v in inc.items()}}
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"incident_{incident_id}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
            return path
        except OSError:
            return None

    # ----------------------------------------------------------- evaluation
    def _eval_loop(self):
        while not self._stop.wait(self._eval_s):
            try:
                self.evaluate()
            except Exception:
                pass

    def merged_hists(self):
        with self._lock:
            per_member = list(self._member_hists.values())
        names = set()
        for h in per_member:
            names.update(h)
        return {n: _slo.merge_hists([h.get(n) for h in per_member])
                for n in names}

    def evaluate(self, now=None):
        """One SLO engine tick over the merged state; returns new breach
        alerts (each also opens/joins an incident)."""
        with self._lock:
            counters = dict(self._counters)
        hists = self.merged_hists()
        skew = _slo.latency_skew(
            {n[len("ps.rpc/endpoint_ms/"):]: s.get("avg")
             for n, s in hists.items()
             if n.startswith("ps.rpc/endpoint_ms/") and s.get("count")})
        with self._lock:
            self._gauges["telemetry.ps_latency_skew"] = \
                (skew[0] if skew else None)
        alerts = self.engine.observe(counters, hists, now=now)
        for alert in alerts:
            iid, opened = self._open_or_join(
                f"slo_breach:{alert['slo']}", trigger=self._member_id,
                now=alert["time"])
            alert["incident_id"] = iid
            with self._lock:
                self.alerts.append(alert)
                inc = self._incidents.get(iid)
                if inc is not None:
                    inc["alerts"].append(alert)
            if opened:
                # the hub contributes its own record so the merged dump
                # carries the alert context even if members are slow
                self._attach_record(
                    iid, self._member_id,
                    _fr.record(f"slo_breach:{alert['slo']}",
                               incident_id=iid))
            else:
                self._write_incident(iid)
        return alerts

    # -------------------------------------------------------------- reading
    def snapshot(self):
        """Aggregated fleet view (also the telemetry_snapshot RPC)."""
        hists = self.merged_hists()
        with self._lock:
            return {
                "members": {m: dict(v)
                            for m, v in self._members.items()},
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "hists": hists,
                "alerts": list(self.alerts),
                "active_slos": self.engine.active(),
                "incidents": [
                    {"incident_id": i["incident_id"],
                     "reason": i["reason"], "time": i["time"],
                     "members": sorted(i["members"])}
                    for i in self._incidents.values()],
                "span_count": len(self._spans),
            }

    def member_counters(self, member):
        with self._lock:
            return dict(self._member_counters.get(member, {}))

    def incidents(self):
        with self._lock:
            return {iid: {"reason": i["reason"], "time": i["time"],
                          "members": dict(i["members"]),
                          "alerts": list(i["alerts"]),
                          "triggers": list(i["triggers"])}
                    for iid, i in self._incidents.items()}

    def chrome_trace(self, path=None):
        """The cluster timeline: every member's spans on its own
        process lane (pid), plus process_name metadata rows naming the
        member roles — serve -> primary -> backup flows render as one
        chain because the trace ids crossed the wire in ps.rpc frames.
        Returns the event list (and writes JSON to `path` if given)."""
        with self._lock:
            spans = list(self._spans)
        lanes = OrderedDict()
        for member, role, pid, s in spans:
            lane = pid if pid is not None else member
            lanes.setdefault(lane, (f"{role or member} ({member})", []))
            lanes[lane][1].append(s)
        events = []
        for lane, (label, lane_spans) in lanes.items():
            events.append({"name": "process_name", "ph": "M",
                           "pid": lane, "args": {"name": label}})
            events.extend(_trace.to_chrome_events(lane_spans, pid=lane))
        if path:
            with open(path, "w") as f:
                json.dump({"traceEvents": events}, f)
        return events

    def stop(self):
        self._stop.set()
        self._eval_thread.join(timeout=5.0)
        self._serve_thread.join(timeout=5.0)


# --------------------------------------------------------------------------
# shipper
# --------------------------------------------------------------------------

class TelemetryShipper:
    """Background thread that ships this process's telemetry to a hub.

    Exactly-once accounting: each flush snapshots the monitor registry,
    computes counter deltas against the last ACKED snapshot, and ships
    them as one mutating RPC whose replay key is pinned to the shipment
    seq — a retry (chaos, reconnect) replays at the hub instead of
    double-applying, and an un-acked shipment is re-sent with the SAME
    key next cadence. Gauges ship current values; histograms ship their
    full cumulative summaries (last-wins per member at the hub, merged
    across members at read time).

    Span capture is a trace sink appending to a bounded buffer — when
    the hub is slow or dead the buffer sheds (telemetry.dropped_spans
    per span, telemetry.dropped_batches per affected flush) rather than
    ever blocking the thread that finished the span.

    Incident duty: a local flight-recorder trigger is reported to the
    hub (opening/joining an incident); an incident id piggybacked on a
    ship ack makes this member write its own schema-v2 dump and ship
    the record to the merged incident file.
    """

    def __init__(self, hub_endpoint=None, member_id=None, role="",
                 peers=None, snapshot_fn=None, flush_s=None,
                 span_buffer=None, rpc_opts=None, capture_spans=True,
                 report_incidents=True, clock=time.time):
        hub_endpoint = hub_endpoint or _flag("PADDLE_TELEMETRY_HUB")
        if not hub_endpoint:
            raise ValueError("TelemetryShipper needs a hub endpoint "
                             "(arg or PADDLE_TELEMETRY_HUB)")
        self.hub_endpoint = hub_endpoint
        self.role = str(role)
        self.member_id = member_id or (
            f"{role or 'member'}-{os.getpid()}-{uuid.uuid4().hex[:6]}")
        self._snapshot = snapshot_fn or (
            lambda: _monitor.snapshot(include_series=False))
        self._flush_s = float(_flag("PADDLE_TELEMETRY_FLUSH_S")
                              if flush_s is None else flush_s)
        self._span_cap = int(_flag("PADDLE_TELEMETRY_SPAN_BUFFER")
                             if span_buffer is None else span_buffer)
        self._clock = clock
        opts = dict(_DEF_RPC_OPTS)
        opts.update(rpc_opts or {})
        self._rpc_opts = opts
        # the connection dials lazily (first flush): a hub that is down
        # when a member attaches — or dies later — must degrade to
        # dropped batches, never take the member down with it
        self._conn = None
        self._flush_lock = threading.Lock()
        self._last_acked: dict = {}      # counter -> acked cumulative
        self._seq = 0
        self._pending = None             # (key, payload, snap, spans)
        self._spans: deque = deque()
        self._overflowed = False
        self._seen_incidents = set()
        self._stop = threading.Event()
        self._thread = None
        _fr.set_identity(role=self.role or None, peers=peers)
        self._capture_spans = bool(capture_spans)
        if self._capture_spans:
            _trace.add_sink(self._span_sink)
        self._report_incidents = bool(report_incidents)
        if self._report_incidents:
            _fr.register_dump_listener(self._on_dump_trigger)

    def _ensure_conn(self):
        """Dial on first use. A failed dial raises to the caller (flush
        returns False / the beat thread swallows it) and leaves the
        shipper intact for the next attempt."""
        if self._conn is None:
            self._conn = _rpc().Connection(self.hub_endpoint, quiet=True,
                                           **self._rpc_opts)
        return self._conn

    # ------------------------------------------------------------ hot path
    def _span_sink(self, sp):
        """Called for every finished span, on whatever thread finished
        it — must stay O(1) and never block. Telemetry-transport spans
        are excluded for the same reason the shipper's connection is
        quiet: shipping the stream must not generate the stream (an
        in-process hub would otherwise hand every ship's server span
        right back to the shipper, and drains would chase their own
        tail forever)."""
        if sp.name.startswith("ps.server/telemetry_"):
            return
        if len(self._spans) >= self._span_cap:
            self._overflowed = True
            _monitor.stat_add("telemetry.dropped_spans")
            return
        self._spans.append(sp)

    # ---------------------------------------------------------- background
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"telemetry-shipper-{self.member_id}")
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self._flush_s):
            try:
                self.flush()
            except Exception:
                pass

    def close(self, drain_timeout=5.0):
        """Stop the background thread, drain what's left, detach."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(drain_timeout, self._flush_s)
                              + 1.0)
            self._thread = None
        drained = self.drain(timeout=drain_timeout)
        if self._capture_spans:
            _trace.remove_sink(self._span_sink)
        if self._report_incidents:
            _fr.unregister_dump_listener(self._on_dump_trigger)
        if self._conn is not None:
            self._conn.close()
        return drained

    # ------------------------------------------------------------- shipping
    def _counter_cum(self, snap):
        """{counter name: cumulative value} from a registry snapshot."""
        values = snap.get("values", {})
        return {n: float(values.get(n, 0.0))
                for n, t in snap.get("types", {}).items()
                if t == "counter"}

    def _collect(self):
        """Build the next shipment from the current registry state."""
        snap = self._snapshot()
        values = snap.get("values", {})
        types = snap.get("types", {})
        cum = self._counter_cum(snap)
        counters = {}
        for name, cur in cum.items():
            delta = cur - self._last_acked.get(name, 0.0)
            if delta:
                counters[name] = delta
        gauges = {n: values.get(n) for n, t in types.items()
                  if t == "gauge"}
        spans = []
        while self._spans and len(spans) < MAX_SPANS_PER_SHIP:
            try:
                spans.append(_trace.span_dict(self._spans.popleft()))
            except IndexError:
                break
        if self._overflowed:
            self._overflowed = False
            _monitor.stat_add("telemetry.dropped_batches")
            # the drop counters themselves are counters and ship on the
            # NEXT flush's delta — nothing special needed here
        payload = {"member": self.member_id, "role": self.role,
                   "pid": os.getpid(), "counters": counters,
                   "gauges": gauges,
                   "hists": dict(snap.get("histograms", {})),
                   "spans": spans}
        return payload, cum

    def flush(self):
        """Ship one batch (or re-ship the pending un-acked one).
        Returns True when the hub acked, False when it is unreachable
        (state kept; next flush retries with the same replay key)."""
        with self._flush_lock:
            if self._pending is None:
                payload, cum = self._collect()
                self._seq += 1
                self._pending = (self._seq, payload, cum)
            key, payload, cum = self._pending
            try:
                reply = self._ensure_conn().call("telemetry_ship",
                                                 _mutating=True, _key=key,
                                                 **payload)
            except Exception:
                return False
            self._pending = None
            self._last_acked = cum
        incident = (reply or {}).get("incident")
        if incident:
            self._join_incident(incident["id"], incident["reason"])
        return True

    def drain(self, timeout=10.0):
        """Flush until nothing unshipped remains (pending acked, no
        counter delta, span buffer empty). Used for final accounting:
        after drain() the hub's per-member totals equal this process's
        stats() bitwise. Returns True on success."""
        deadline = self._clock() + timeout
        while True:
            ok = False
            try:
                ok = self.flush()
            except Exception:
                pass
            if ok and self._pending is None and not self._spans:
                cum = self._counter_cum(self._snapshot())
                if all(cum.get(n, 0.0) == self._last_acked.get(n, 0.0)
                       for n in cum):
                    return True
            if self._clock() >= deadline:
                return False
            time.sleep(min(0.05, self._flush_s))

    def shipped_totals(self):
        """Cumulative counter totals the hub has acked for this member."""
        with self._flush_lock:
            return dict(self._last_acked)

    # ------------------------------------------------------------ incidents
    def _on_dump_trigger(self, reason, exc, incident_id):
        """flight_recorder dump listener: a locally-originated failure
        (incident_id None) is reported to the hub off-thread — the
        failure path must not block on the network."""
        if incident_id is not None:
            return
        threading.Thread(target=self._report_trigger, args=(reason,),
                         daemon=True).start()

    def _report_trigger(self, reason):
        try:
            reply = self._ensure_conn().call("telemetry_incident",
                                             member=self.member_id,
                                             reason=reason, role=self.role,
                                             pid=os.getpid())
            iid = (reply or {}).get("incident_id")
            if iid:
                self._join_incident(iid, reason)
        except Exception:
            pass

    def _join_incident(self, incident_id, reason):
        """Dump locally under the incident id and ship the record into
        the merged incident file. Idempotent per incident."""
        if incident_id in self._seen_incidents:
            return
        self._seen_incidents.add(incident_id)
        try:
            _fr.dump(f"incident_{reason}".replace("/", "_"),
                     incident_id=incident_id)
            record = _fr.record(reason, incident_id=incident_id)
            self._ensure_conn().call("telemetry_incident_dump",
                                     member=self.member_id,
                                     incident_id=incident_id,
                                     record=record)
        except Exception:
            pass


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

def fetch_snapshot(endpoint=None, timeout=5.0):
    """One-shot aggregated hub snapshot (bench.py's fleet section).
    Raises on an unreachable hub — callers own their degrade policy."""
    rpc = _rpc()
    endpoint = endpoint or _flag("PADDLE_TELEMETRY_HUB")
    conn = rpc.Connection(endpoint, timeout=timeout, max_retries=0,
                          connect_retry_s=timeout, quiet=True)
    try:
        return conn.call("telemetry_snapshot")
    finally:
        conn.close()


def stitch_incident(incident):
    """Cross-process trace chains in a merged incident dump: for every
    trace id seen in >= 2 member records, the members it crossed (in
    first-span time order) and the span names involved. This is what
    proves a serve->primary->backup flow is ONE story."""
    by_trace = {}
    for member, record in (incident.get("members") or {}).items():
        role = (record or {}).get("role", "")
        pid = (record or {}).get("pid")
        for s in (record or {}).get("spans") or ():
            tid = s.get("trace_id")
            if not tid:
                continue
            ent = by_trace.setdefault(tid, {})
            cur = ent.get(member)
            if cur is None:
                cur = ent[member] = {
                    "member": member, "role": role, "pid": pid,
                    "first_ts_us": s.get("ts_us", 0), "spans": 0,
                    "names": set()}
            cur["first_ts_us"] = min(cur["first_ts_us"],
                                     s.get("ts_us", 0))
            cur["spans"] += 1
            cur["names"].add(s.get("name"))
    chains = []
    for tid, members in by_trace.items():
        if len(members) < 2:
            continue
        hops = sorted(members.values(),
                      key=lambda m: m["first_ts_us"])
        chains.append({
            "trace_id": tid,
            "members": [m["member"] for m in hops],
            "roles": [m["role"] for m in hops],
            "pids": [m["pid"] for m in hops],
            "span_names": sorted(set().union(*(m["names"]
                                               for m in hops))),
            "spans": sum(m["spans"] for m in hops)})
    chains.sort(key=lambda c: (-len(c["members"]), -c["spans"]))
    return chains
