from . import dtype, flags, rng, tape, tensor  # noqa: F401
from .tensor import Tensor, to_tensor  # noqa: F401
