"""Typed error machinery.

Analog of reference platform/enforce.h + platform/errors.h +
error_codes.proto: PADDLE_ENFORCE_* macros build typed errors with
actionable hints. Python tracebacks replace the demangled C++ stacks; the
typed taxonomy and the enforce_* checks carry over so framework errors are
catchable by kind (the reference's external_error_map equivalent for user
code)."""
from __future__ import annotations

__all__ = ["EnforceNotMet", "InvalidArgumentError", "NotFoundError",
           "OutOfRangeError", "AlreadyExistsError", "PermissionDeniedError",
           "UnimplementedError", "UnavailableError", "FatalError",
           "ExecutionTimeoutError", "enforce", "enforce_eq", "enforce_gt",
           "enforce_ge", "check_type", "check_shape"]


class EnforceNotMet(RuntimeError):
    """Base framework error (reference EnforceNotMet, enforce.h)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class FatalError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


def enforce(cond, message, error=InvalidArgumentError):
    """PADDLE_ENFORCE analog."""
    if not cond:
        raise error(message)


def enforce_eq(a, b, what="values", error=InvalidArgumentError):
    if a != b:
        raise error(f"expected {what} to be equal, got {a!r} vs {b!r}")


def enforce_gt(a, b, what="value", error=InvalidArgumentError):
    if not a > b:
        raise error(f"expected {what} > {b!r}, got {a!r}")


def enforce_ge(a, b, what="value", error=InvalidArgumentError):
    if not a >= b:
        raise error(f"expected {what} >= {b!r}, got {a!r}")


def check_type(value, name, expected, op_name=""):
    """reference fluid/data_feeder.py check_type."""
    if not isinstance(value, expected):
        exp = expected if isinstance(expected, tuple) else (expected,)
        names = "/".join(t.__name__ for t in exp)
        where = f" of op {op_name}" if op_name else ""
        raise InvalidArgumentError(
            f"argument {name!r}{where} must be {names}, got "
            f"{type(value).__name__}")


def check_shape(shape, name="shape"):
    if not all(isinstance(s, int) and (s > 0 or s in (-1,)) for s in shape):
        raise InvalidArgumentError(
            f"{name} must be positive ints (or -1 for deferred), got "
            f"{list(shape)}")
