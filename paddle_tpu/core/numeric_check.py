"""Numeric debugging: the FLAGS_check_nan_inf sweep.

TPU-native analog of the reference's per-op nan/inf validation
(reference: paddle/fluid/framework/details/nan_inf_utils_detail.cu:94 CUDA
sweep + nan_inf_utils_detail.cc:177 CPU path, enabled by
platform/flags.cc:44 FLAGS_check_nan_inf). Two tiers:

- eager ops: `check_op_outputs` runs right after each kernel in
  core/tape.record_op — concrete values only (tracers are covered by the
  post-step sweep), raising with the op name like the reference's
  EnforceNotMet does.
- compiled steps: `sweep` host-checks a pytree of step outputs (loss,
  fetches, updated scope/params) after the jitted call returns, naming every
  offending entry.
"""
from __future__ import annotations

import numpy as np

import jax

from . import flags as _flags


def enabled() -> bool:
    return bool(_flags.flag("FLAGS_check_nan_inf"))


def _is_concrete_float(v):
    if isinstance(v, jax.core.Tracer):
        return False
    import jax.numpy as jnp
    return hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating)


def check_op_outputs(op_name: str, out_val):
    """Raise if any concrete floating output of an eager op has nan/inf."""
    outs = out_val if isinstance(out_val, (tuple, list)) else [out_val]
    for i, v in enumerate(outs):
        if not _is_concrete_float(v):
            continue
        arr = np.asarray(v)
        if not np.isfinite(arr).all():
            n_nan = int(np.isnan(arr).sum())
            n_inf = int(np.isinf(arr).sum())
            raise RuntimeError(
                f"[FLAGS_check_nan_inf] op '{op_name}' output {i} contains "
                f"{n_nan} nan / {n_inf} inf values "
                f"(shape={tuple(arr.shape)}, dtype={arr.dtype})")


def sweep(tree, context: str):
    """Host-check every floating leaf of `tree`; raise naming the bad ones."""
    bad = []
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, v in flat:
        if not _is_concrete_float(v):
            continue
        arr = np.asarray(v)
        if not np.isfinite(arr).all():
            name = jax.tree_util.keystr(path)
            bad.append(f"{name}: {int(np.isnan(arr).sum())} nan / "
                       f"{int(np.isinf(arr).sum())} inf "
                       f"(shape={tuple(arr.shape)})")
    if bad:
        raise RuntimeError(
            f"[FLAGS_check_nan_inf] non-finite values after {context}:\n  " +
            "\n  ".join(bad))
