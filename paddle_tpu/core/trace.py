"""Cross-layer span tracer.

The runtime grew three opaque concurrent subsystems — the async pipelined
hot loop (static/pipeline_runner.py), the fault-tolerant PS transport
(distributed/ps/rpc.py), and guarded Pallas dispatch (ops/pallas) — whose
interesting moments happen on different threads (and, for the PS stack,
different processes). A flat counter dict can say THAT something happened;
it cannot say which step's retirement a stall belongs to, or which client
call a server-side replay correlates with. This module is the shared
substrate (TensorFlow's runtime made per-step timelines first-class for
the same reason — PAPERS.md):

- **Spans**: named intervals with ids, parent links, attributes, and the
  owning thread. `span("pipeline/dispatch", step=3)` nests under the
  ambient span of the current thread; `attach(ctx)` re-homes a worker
  thread (prefetch, RPC handler) under a context captured elsewhere, and
  the PS client ships its context inside the RPC frame so server-side
  apply/replay spans carry the SAME trace id as the originating call
  across processes.
- **Flow events**: `span.flow(fid, "s"|"t"|"f")` threads a logical object
  (a pipeline step) through the spans that touch it, so the Chrome trace
  draws arrows dispatch -> retire -> materialize across threads.
- **Two sinks**: a bounded always-on ring of finished spans (the flight
  recorder's feed — core/flight_recorder.py dumps it on failure), and a
  full capture buffer while `start()`ed, exported with
  `export_chrome_trace` (chrome://tracing / Perfetto).

This absorbs profiler.RecordEvent: RecordEvent is now a thin span wrapper
and finished spans are mirrored into the profiler's event table while the
host profiler is enabled, so `profiler.summary()` covers every span site
for free. Span overhead is two perf_counter calls and a deque append —
cheap enough to leave on at per-step granularity (NOT per-op; per-op
annotations stay behind FLAGS_enable_profiler, as before).
"""
from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque

from . import flags as _flags

__all__ = ["Span", "span", "begin", "end", "instant", "attach", "current",
           "new_trace_id", "start", "stop", "enabled", "get_spans",
           "recent", "reset", "set_ring_size", "export_chrome_trace",
           "to_chrome_events", "span_dict"]

_lock = threading.Lock()
_ids = itertools.count(1)
_enabled = False
_buffer: list = []                 # full capture while start()ed
_t_origin = time.perf_counter()
_tls = threading.local()

# Mirrors finished spans into paddle_tpu.profiler's event table while the
# host profiler is enabled; the profiler module installs this at import so
# core stays import-light (no upward dependency).
_profiler_sink = None

# Additional finished-span sinks (e.g. core/telemetry.py's shipper
# buffering spans for the hub). Called OUTSIDE _lock on the thread that
# finished the span — sinks must be non-blocking and never raise.
_sinks: list = []


def add_sink(fn):
    """Register fn(span) to be called for every finished span."""
    with _lock:
        if fn not in _sinks:
            _sinks.append(fn)


def remove_sink(fn):
    with _lock:
        try:
            _sinks.remove(fn)
        except ValueError:
            pass


def _ring_size():
    try:
        return max(0, int(_flags.flag("FLAGS_trace_ring_size")))
    except KeyError:  # flags not loaded yet (import order in tools)
        return 4096


_ring: deque = deque(maxlen=_ring_size() or None)


def set_ring_size(n: int):
    """Re-bound the always-on ring (flight-recorder depth). Existing
    entries are kept up to the new bound."""
    global _ring
    with _lock:
        _ring = deque(_ring, maxlen=max(0, int(n)) or None)


def _sync_ring_size():
    """Pick up a runtime FLAGS_trace_ring_size change. The flag is read
    at import to size the ring; re-reading on every append would tax the
    hot path, so set_flags takes effect at the next start()/reset()
    boundary (or immediately via set_ring_size())."""
    n = _ring_size() or None
    if _ring.maxlen != n:
        set_ring_size(n or 0)


def new_trace_id() -> str:
    """Process-unique trace id; the pid prefix keeps ids distinct across
    the PS server/worker processes whose spans later merge in one dump."""
    return f"{os.getpid():x}-{next(_ids):x}"


def _new_span_id() -> str:
    return f"{os.getpid():x}.{next(_ids):x}"


class Span:
    """One named interval. Created via begin()/span(); finished spans are
    immutable records in the ring (and the capture buffer while tracing).
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0", "t1",
                 "tid", "thread", "attrs", "flows")

    def __init__(self, name, trace_id, parent_id, attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else {}
        self.flows = None          # [(flow_id, phase)], lazily allocated
        th = threading.current_thread()
        self.tid = th.ident
        self.thread = th.name
        self.t0 = time.perf_counter()
        self.t1 = None

    def set_attr(self, key, value):
        self.attrs[key] = value
        return self

    def flow(self, flow_id: int, phase: str):
        """Bind a flow event to this span: phase 's' starts an arrow,
        't' continues it, 'f' terminates it (Chrome flow semantics)."""
        if self.flows is None:
            self.flows = []
        self.flows.append((int(flow_id), phase))
        return self

    @property
    def context(self):
        return (self.trace_id, self.span_id)

    @property
    def duration_ms(self):
        return ((self.t1 or time.perf_counter()) - self.t0) * 1e3

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"id={self.span_id}, parent={self.parent_id})")


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current():
    """Ambient (trace_id, span_id) of the calling thread, or None."""
    st = _stack()
    if not st:
        return None
    top = st[-1]
    return top.context if isinstance(top, Span) else top


def _resolve_parent(parent):
    if parent is None:
        ctx = current()
        if ctx is not None:
            return ctx
        return (new_trace_id(), None)
    if isinstance(parent, Span):
        return parent.context
    # remote context off the wire: (trace_id, span_id) tuple/list
    try:
        trace_id, span_id = parent
        return (str(trace_id), None if span_id is None else str(span_id))
    except (TypeError, ValueError):
        return (new_trace_id(), None)


def begin(name: str, parent=None, _attach=True, **attrs) -> Span:
    """Open a span (pushed as the thread's ambient parent). Pair with
    end(); prefer the `span()` context manager where control flow allows.

    `_attach=False` opens a DETACHED span: it still parents under the
    ambient span but is not pushed onto the stack — for legacy
    begin()/end() call sites (profiler.RecordEvent) whose callers may
    skip end() on exception; a missed end then loses one sample instead
    of leaving a dead span as every later span's ancestor."""
    trace_id, parent_id = _resolve_parent(parent)
    sp = Span(name, trace_id, parent_id, attrs)
    if _attach:
        _stack().append(sp)
    return sp


def end(sp: Span, discard: bool = False):
    """Close a span and record it (unless discarded). Idempotent (a
    second end is a no-op, so error paths can end eagerly and leave the
    `finally` as a backstop) and tolerant of out-of-order ends: removes
    `sp` wherever it sits on this thread's stack."""
    if sp is None or sp.t1 is not None:
        return
    sp.t1 = time.perf_counter()
    st = _stack()
    if st and st[-1] is sp:
        st.pop()
    elif sp in st:
        st.remove(sp)
    if discard:
        return
    _record(sp)


def _record(sp: Span):
    with _lock:
        _ring.append(sp)
        if _enabled:
            _buffer.append(sp)
    sink = _profiler_sink
    if sink is not None:
        sink(sp)
    for fn in _sinks:
        try:
            fn(sp)
        except Exception:
            pass


@contextlib.contextmanager
def span(name: str, parent=None, **attrs):
    """Scoped span. On an exception the span records the exception type
    in its attrs and re-raises."""
    sp = begin(name, parent=parent, **attrs)
    try:
        yield sp
    except BaseException as e:
        sp.attrs.setdefault("error", type(e).__name__)
        raise
    finally:
        end(sp)


def instant(name: str, **attrs) -> Span:
    """Zero-duration marker span (rendered as an instant event)."""
    sp = begin(name, **attrs)
    end(sp)
    return sp


@contextlib.contextmanager
def attach(ctx):
    """Adopt a context captured on another thread (or shipped across a
    process boundary) as this thread's ambient parent — the prefetch
    thread and the PS server's handler threads use this so their spans
    join the originating trace. `ctx` may be None (no-op)."""
    if ctx is None:
        yield
        return
    st = _stack()
    marker = (str(ctx[0]), None if ctx[1] is None else str(ctx[1]))
    st.append(marker)
    try:
        yield
    finally:
        if st and st[-1] == marker:
            st.pop()
        elif marker in st:
            st.remove(marker)


# -- capture control ---------------------------------------------------------

def start():
    """Begin full capture (the ring keeps running regardless)."""
    global _enabled
    _sync_ring_size()
    with _lock:
        _buffer.clear()
        _enabled = True


def stop():
    global _enabled
    with _lock:
        _enabled = False
    return get_spans()


def enabled() -> bool:
    return _enabled


def get_spans():
    with _lock:
        return list(_buffer)


def recent(n: int = None):
    """Most recent finished spans from the always-on ring (flight
    recorder feed); newest last."""
    with _lock:
        out = list(_ring)
    return out if n is None else out[-n:]


def open_spans():
    """Still-open spans of the CALLING thread, outermost first. The
    flight recorder includes these in a dump: the span enclosing the
    failure (e.g. the materialize that raised PipelineStepError) hasn't
    reached the ring yet — it IS the failure's location."""
    return [s for s in _stack() if isinstance(s, Span)]


def reset():
    _sync_ring_size()
    with _lock:
        _buffer.clear()
        _ring.clear()


# -- export ------------------------------------------------------------------

def span_dict(sp: Span) -> dict:
    """JSON-able record (flight-recorder dump schema)."""
    return {
        "name": sp.name, "trace_id": sp.trace_id, "span_id": sp.span_id,
        "parent_id": sp.parent_id, "ts_us": (sp.t0 - _t_origin) * 1e6,
        "dur_us": ((sp.t1 or sp.t0) - sp.t0) * 1e6, "tid": sp.tid,
        "thread": sp.thread, "attrs": sp.attrs, "flows": sp.flows or [],
    }


def to_chrome_events(spans=None, pid=None) -> list:
    """Chrome trace events: one "X" slice per span (args carry the span
    ids + attributes; zero-duration spans render as instants), flow
    events ("s"/"t"/"f") for every span-bound flow, and thread-name
    metadata. Flow timestamps sit at the slice midpoint so Chrome binds
    them to the right slice. Accepts live Span objects OR span_dict()
    records (the flight-recorder dump form, so tools/obs_report.py
    converts dumps with this same encoder); `pid` overrides the emitted
    process id (a dump's spans belong to the dumping process)."""
    spans = get_spans() if spans is None else spans
    pid = os.getpid() if pid is None else pid
    events, threads = [], {}
    for sp in spans:
        if isinstance(sp, dict):                 # span_dict record
            name, ts, dur = sp["name"], sp["ts_us"], sp["dur_us"]
            tid, thread = sp.get("tid", 0), sp.get("thread")
            trace_id, span_id = sp.get("trace_id"), sp.get("span_id")
            parent_id, attrs = sp.get("parent_id"), sp.get("attrs", {})
            flows = sp.get("flows") or ()
        else:
            t1 = sp.t1 if sp.t1 is not None else sp.t0
            ts = (sp.t0 - _t_origin) * 1e6
            dur = (t1 - sp.t0) * 1e6
            name, tid, thread = sp.name, sp.tid, sp.thread
            trace_id, span_id = sp.trace_id, sp.span_id
            parent_id, attrs = sp.parent_id, sp.attrs
            flows = sp.flows or ()
        threads.setdefault(tid, thread)
        args = {"trace_id": trace_id, "span_id": span_id}
        if parent_id:
            args["parent_id"] = parent_id
        args.update({k: v for k, v in attrs.items()
                     if isinstance(v, (str, int, float, bool))
                     or v is None})
        if dur <= 0:
            events.append({"name": name, "ph": "i", "pid": pid,
                           "tid": tid, "ts": ts, "s": "t", "args": args})
        else:
            events.append({"name": name, "ph": "X", "pid": pid,
                           "tid": tid, "ts": ts, "dur": dur,
                           "args": args})
        for fid, phase in flows:
            ev = {"name": "step-flow", "cat": "flow", "ph": phase,
                  "id": fid, "pid": pid, "tid": tid,
                  "ts": ts + max(dur / 2, 0.0)}
            if phase == "f":
                ev["bp"] = "e"
            events.append(ev)
    for tid, tname in threads.items():
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": tname or str(tid)}})
    return events


def export_chrome_trace(path: str, spans=None):
    """Write the capture buffer (or the given spans) as a Chrome trace."""
    with open(path, "w") as f:
        json.dump({"traceEvents": to_chrome_events(spans),
                   "displayTimeUnit": "ms"}, f)
    return path
