"""Tensor: the user-facing n-d array.

TPU-native analog of the reference's VarBase/LoDTensor pair
(reference: paddle/fluid/imperative/layer.h:65 VarBase;
paddle/fluid/framework/tensor.h:77 Tensor; lod_tensor.h LoDTensor).

Design deltas (SURVEY.md §7.1):
  - storage is a jax.Array (XLA-managed, device-resident) or a tracer while
    inside a jit trace — the same Tensor class flows through eager AND
    compiled paths, replacing the reference's dual VarBase/Variable split.
  - no LoD: ragged sequences are represented densely with masks/segment ids
    (see paddle_tpu.text utilities), which is the XLA-friendly layout.
  - autograd linkage is `_node/_out_index` into the tape (core/tape.py),
    replacing VarBase's GradVarBase + inplace version counter.
Tensor is registered as a jax pytree node so jit/grad/shard transforms can
cross Tensor boundaries transparently.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtypes
from . import tape as _tape

__all__ = ["Tensor", "to_tensor"]


def _coerce(value, dtype=None):
    if isinstance(value, Tensor):
        value = value._value
    jd = dtypes.to_jax_dtype(dtype) if dtype is not None else None
    if isinstance(value, jax.Array) or isinstance(value, jax.core.Tracer):
        return value.astype(jd) if jd is not None and value.dtype != jd else value
    arr = np.asarray(value)
    if jd is None:
        # paddle defaults: python floats -> float32, ints -> int64
        if arr.dtype == np.float64:
            jd = jnp.float32
        elif arr.dtype == np.int64 or arr.dtype == np.int32:
            jd = jnp.int64 if arr.dtype == np.int64 else arr.dtype
    return jnp.asarray(arr, dtype=jd)


class Tensor:
    __slots__ = ("_value", "stop_gradient", "grad", "name", "persistable",
                 "trainable", "_node", "_out_index", "_leaf_hooks",
                 "__weakref__")

    def __init__(self, value, dtype=None, stop_gradient=True, name=None,
                 persistable=False, _internal=False):
        if _internal:
            self._value = value
        else:
            self._value = _coerce(value, dtype)
        self.stop_gradient = stop_gradient
        self.grad = None
        self.name = name
        self.persistable = persistable
        self.trainable = not stop_gradient
        self._node = None
        self._out_index = 0

    # -- raw access ---------------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return tuple(int(s) for s in self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def is_leaf(self):
        return self._node is None

    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        return np.asarray(self._value).item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        return (f"Tensor(shape={list(self.shape)}, dtype={self.dtype.name}, "
                f"stop_gradient={self.stop_gradient},\n{self._value})")

    def __hash__(self):
        return id(self)

    def _concretize(self, caster, what):
        import jax
        try:
            return caster(np.asarray(self._value))
        except (jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerBoolConversionError) as e:
            raise TypeError(
                f"{what} of a traced Tensor inside a jitted/to_static "
                "function is data-dependent Python control flow, which "
                "would bake one branch into the compiled program. "
                "@paddle.jit.to_static converts if/while over tensors "
                "automatically when the function's source is available "
                "(jit/dy2static.py); otherwise use paddle.static.nn.cond "
                "/ while_loop, or keep the branch out of the traced "
                "region") from e

    def __bool__(self):
        return self._concretize(bool, "the truth value")

    def __float__(self):
        return self._concretize(float, "float()")

    def __int__(self):
        return self._concretize(int, "int()")

    def __index__(self):
        return self._concretize(int, "index()")

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        _tape.backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self):
        self.grad = None

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, _internal=True)
        t.name = self.name
        return t

    def clone(self):
        from .. import ops
        return ops.assign(self)

    def register_hook(self, hook):
        """Register a gradient hook (reference imperative/hooks.h via
        varbase_patch_methods register_hook): `hook(grad) -> new_grad |
        None`, fired when this tensor's gradient is computed during
        backward. Returns a RemovableHandle."""
        if self.stop_gradient:
            raise RuntimeError(
                "cannot register a gradient hook on a tensor with "
                "stop_gradient=True")
        if self._node is not None:
            hooks = self._node.out_hooks
            if hooks is None:
                hooks = self._node.out_hooks = {}
            lst = hooks.setdefault(self._out_index, [])
        else:
            if getattr(self, "_leaf_hooks", None) is None:
                self._leaf_hooks = []
            lst = self._leaf_hooks
        lst.append(hook)

        class _Handle:
            def remove(_self):
                try:
                    lst.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    # -- mutation (rebinds value; autograd-safe SSA rebind) -----------------
    def set_value(self, value):
        v = _coerce(value)
        if tuple(v.shape) != self.shape:
            raise ValueError(f"set_value shape mismatch {v.shape} vs {self.shape}")
        self._value = v.astype(self._value.dtype)
        self._node = None
        self._out_index = 0
        return self

    def copy_(self, other, *a):
        return self.set_value(other)

    def _rebind(self, new):
        """Adopt another Tensor's value and autograd linkage (in-place ops)."""
        self._value = new._value
        self._node = new._node
        self._out_index = new._out_index
        self.stop_gradient = new.stop_gradient
        return self

    def _alias(self):
        """Snapshot sharing value AND autograd linkage (unlike detach).

        Used by in-place ops: the op must consume the tensor's *pre-write*
        identity so the rebind cannot make the grad graph cyclic — the SSA
        discipline the reference enforces with inplace version counters
        (reference: paddle/fluid/framework/tensor.h:77-89).
        """
        t = Tensor(self._value, stop_gradient=self.stop_gradient,
                   _internal=True)
        t._node = self._node
        t._out_index = self._out_index
        t.name = self.name
        return t

    # -- conversion / shape sugar (defined via ops; populated lazily) ------
    def astype(self, dtype):
        from .. import ops
        return ops.cast(self, dtype)

    cast = astype

    def __getitem__(self, idx):
        from .. import ops
        return ops.getitem(self, idx)

    def __setitem__(self, idx, value):
        from .. import ops
        self._rebind(ops.setitem(self._alias(), idx, value))

    # arithmetic operators are attached by ops/_bind.py to avoid an import
    # cycle; see paddle_tpu/ops/_bind.py.


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor equivalent (place is accepted for parity; XLA owns
    placement — use paddle_tpu.distributed shardings for multi-device)."""
    if isinstance(data, Tensor):
        t = Tensor(data._value if dtype is None else _coerce(data._value, dtype),
                   stop_gradient=stop_gradient, _internal=dtype is None)
        return t
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


# -- pytree registration ----------------------------------------------------
def _flatten(t: Tensor):
    return (t._value,), (t.stop_gradient, t.name, t.persistable)


def _unflatten(aux, children):
    t = Tensor(children[0], stop_gradient=aux[0], name=aux[1],
               persistable=aux[2], _internal=True)
    return t


jax.tree_util.register_pytree_node(Tensor, _flatten, _unflatten)
