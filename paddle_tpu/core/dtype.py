"""Dtype registry.

TPU-native analog of the reference's `VarType` proto enum
(reference: paddle/fluid/framework/framework.proto:104) and the
float16/bfloat16 platform types (platform/float16.h, platform/bfloat16.h).
Here dtypes are plain jnp dtypes with paddle-style string names; bfloat16 is
the first-class reduced precision type (TPU MXU native), float16 is kept for
API parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_NAME_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

FLOATING = {"float16", "bfloat16", "float32", "float64"}
INTEGER = {"uint8", "int8", "int16", "int32", "int64"}


def convert_dtype(dtype):
    """Normalize any dtype spec (string / np / jnp dtype) to a canonical name."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype in _NAME_TO_DTYPE:
            return dtype
        raise TypeError(f"unsupported dtype string: {dtype!r}")
    name = np.dtype(dtype).name if not hasattr(dtype, "name") else np.dtype(dtype).name
    # np.dtype(bfloat16).name == 'bfloat16' via ml_dtypes
    if name in _NAME_TO_DTYPE:
        return name
    raise TypeError(f"unsupported dtype: {dtype!r}")


def to_jax_dtype(dtype):
    """Any dtype spec -> jnp dtype object."""
    if dtype is None:
        return None
    return _NAME_TO_DTYPE[convert_dtype(dtype)]


def is_floating(dtype) -> bool:
    return convert_dtype(dtype) in FLOATING


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in INTEGER
