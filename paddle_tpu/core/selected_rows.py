"""SelectedRows — sparse row-slice gradients.

Analog of reference framework/selected_rows.h: a {rows, value, height}
triple standing in for a mostly-zero dense tensor, produced by embedding
lookups' backward so huge-vocab tables never materialize dense gradients
(reference operators/lookup_table_v2_op.cc grad kernel emits SelectedRows;
optimizers like sgd_op.cc / adam_op.cc lazy_mode consume them row-wise).

TPU-native scoping: sparse grads are an EAGER-mode feature. Inside jitted
steps gradients are dense by construction (XLA fuses gather-transpose
scatter-adds efficiently, and dynamic row counts don't trace); in eager
mode — where the reference's PS/recsys workflows live — the tape's
embedding backward emits SelectedRows, `+` accumulates them without
densifying, and optimizers apply row-wise updates (SGD, Adam lazy_mode).
"""
from __future__ import annotations

import numpy as np

__all__ = ["SelectedRows"]


class SelectedRows:
    """rows: int array [n]; values: [n, ...] row payloads;
    dense_shape: the full tensor shape it abbreviates."""

    __slots__ = ("rows", "values", "dense_shape")

    def __init__(self, rows, values, dense_shape):
        import jax.numpy as jnp
        self.rows = jnp.asarray(rows).reshape(-1)
        self.values = jnp.asarray(values)
        self.dense_shape = tuple(dense_shape)
        if self.values.shape[0] != self.rows.shape[0]:
            raise ValueError(
                f"rows ({self.rows.shape[0]}) and values "
                f"({self.values.shape[0]}) disagree")

    # reference SelectedRows::height()
    @property
    def height(self):
        return self.dense_shape[0]

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def shape(self):
        return self.dense_shape

    @property
    def nbytes(self):
        return self.values.nbytes + self.rows.nbytes

    def __add__(self, other):
        if isinstance(other, SelectedRows):
            if other.dense_shape != self.dense_shape:
                raise ValueError("SelectedRows shape mismatch")
            import jax.numpy as jnp
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]),
                self.dense_shape)
        if other is None:
            return self
        # sparse + dense -> dense (mixed consumers densify)
        return self.to_dense() + other

    __radd__ = __add__

    def coalesce(self):
        """Merge duplicate rows (sum), sorted — the reference's
        MergeAdd functor (operators/math/selected_rows_functor.cc).
        Eager-only: row count is data-dependent."""
        import jax
        import jax.numpy as jnp
        rows = np.asarray(self.rows)
        uniq, inv = np.unique(rows, return_inverse=True)
        summed = jax.ops.segment_sum(self.values, jnp.asarray(inv),
                                     num_segments=len(uniq))
        return SelectedRows(jnp.asarray(uniq), summed, self.dense_shape)

    def to_dense(self):
        import jax.numpy as jnp
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.rows].add(self.values)

    def numpy(self):
        return np.asarray(self.to_dense())

    def __repr__(self):
        return (f"SelectedRows(rows={self.rows.shape[0]}, "
                f"dense_shape={self.dense_shape}, dtype={self.dtype})")
