"""SLO math: the single source for percentile/histogram estimation and
the multi-window burn-rate engine behind the cluster telemetry plane.

Three layers, bottom-up:

  - estimators: `percentile()` (exact, over raw samples — the one
    implementation every tool's p50/p99 goes through), and
    `hist_quantile()` / `good_count()` (approximate, over
    `monitor._Hist.summary()` dicts — what the hub has once samples
    have been folded into buckets).
  - `merge_hists()`: fold per-process histogram summaries into one
    cluster histogram. Merging is exact (bucket-wise sum) when the
    bucket bounds agree — which they do for any histogram observed with
    the same `buckets=` everywhere — and degrades to count/sum/min/max
    only (no buckets, no quantiles) when they don't.
  - `SLOSpec` + `SLOEngine`: declarative objectives ("99% of serve
    TTFTs under 250ms") evaluated with multi-window burn rates over
    cumulative (bad, total) series. A breach requires EVERY window's
    burn rate over threshold, so a single slow request cannot page but
    a sustained regression pages within the fast window. Breaches emit
    structured alert records; clearing is hysteretic on the fast
    window.

`RollingMedianDetector` (step-time anomaly / straggler detection) and
`latency_skew()` (per-shard PS latency spread) live here too: they are
the same "is this observation out of family" math the SLO engine runs,
applied point-wise.

Pure-python + numpy only (no jax); importable from servers, tools, and
the telemetry hub alike.
"""
import math
import threading
import time


def percentile(xs, p, ndigits=None):
    """The single-source percentile estimator (linear interpolation,
    matching numpy's default). Returns None for an empty sample set.

    `ndigits` rounds the result — tools that print pinned output pass
    ndigits=3 so their reports are byte-stable across refactors.
    """
    xs = list(xs)
    if not xs:
        return None
    import numpy as np
    v = float(np.percentile(np.asarray(xs, dtype=np.float64), p))
    return round(v, ndigits) if ndigits is not None else v


def good_count(summary, threshold):
    """How many observations in a histogram summary were <= threshold.

    Conservative: aligns threshold DOWN to the nearest bucket bound, so
    observations in a bucket straddling the threshold count as bad.
    Returns (good, total).
    """
    total = int(summary.get("count", 0))
    bounds = summary.get("bounds")
    buckets = summary.get("buckets")
    if not total or bounds is None or buckets is None:
        return (total if summary.get("max", math.inf) <= threshold
                else 0), total
    good = 0
    for i, b in enumerate(bounds):
        if b <= threshold:
            good += int(buckets[i])
        else:
            break
    return good, total


def hist_quantile(summary, q):
    """Estimate a quantile from a bucketed histogram summary (linear
    interpolation inside the target bucket, prometheus-style). Exact
    min/max are used to clamp the first/last bucket. Returns None for
    an empty histogram or one merged without buckets."""
    total = int(summary.get("count", 0))
    bounds = summary.get("bounds")
    buckets = summary.get("buckets")
    if not total or bounds is None or buckets is None:
        return None
    rank = q / 100.0 * total
    seen = 0.0
    lo = float(summary.get("min", 0.0))
    for i, n in enumerate(buckets):
        if not n:
            continue
        if seen + n >= rank:
            hi = bounds[i] if i < len(bounds) else float(
                summary.get("max", bounds[-1]))
            lo_b = bounds[i - 1] if i > 0 else lo
            frac = (rank - seen) / n
            return min(float(summary.get("max", hi)),
                       max(lo, lo_b + (hi - lo_b) * frac))
        seen += n
    return float(summary.get("max", bounds[-1]))


def merge_hists(summaries):
    """Fold histogram summaries (monitor._Hist.summary() dicts) into
    one. Bucket-exact when every summary shares the same bounds;
    otherwise the merged summary keeps count/sum/min/max but drops the
    buckets (quantile estimation unavailable, by design — a silently
    misaligned bucket merge would lie)."""
    summaries = [s for s in summaries if s and s.get("count")]
    if not summaries:
        return {"count": 0, "sum": 0.0, "min": None, "max": None,
                "avg": None, "bounds": None, "buckets": None}
    out = {
        "count": sum(int(s["count"]) for s in summaries),
        "sum": sum(float(s["sum"]) for s in summaries),
        "min": min(float(s["min"]) for s in summaries),
        "max": max(float(s["max"]) for s in summaries),
    }
    out["avg"] = out["sum"] / out["count"]
    bounds0 = summaries[0].get("bounds")
    if bounds0 is not None and all(
            list(s.get("bounds") or []) == list(bounds0)
            for s in summaries):
        merged = [0] * (len(bounds0) + 1)
        for s in summaries:
            for i, n in enumerate(s["buckets"]):
                merged[i] += int(n)
        out["bounds"] = list(bounds0)
        out["buckets"] = merged
    else:
        out["bounds"] = None
        out["buckets"] = None
    return out


class SLOSpec:
    """One declarative objective.

    kind="latency": `metric` names a histogram; an observation is good
      when <= `threshold_ms`; `objective` is the max allowed bad
      fraction (0.01 == "99% under threshold").
    kind="rate": `metric` names a counter of bad events; `denominator`
      names the total-events counter (objective = max bad/total
      fraction), or None for a per-second budget (objective = max bad
      events per second).
    """

    __slots__ = ("name", "kind", "metric", "threshold_ms", "objective",
                 "denominator", "description")

    def __init__(self, name, kind, metric, objective, threshold_ms=None,
                 denominator=None, description=""):
        if kind not in ("latency", "rate"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if kind == "latency" and threshold_ms is None:
            raise ValueError(f"latency SLO {name!r} needs threshold_ms")
        self.name = name
        self.kind = kind
        self.metric = metric
        self.threshold_ms = threshold_ms
        self.objective = float(objective)
        self.denominator = denominator
        self.description = description

    def to_dict(self):
        return {"name": self.name, "kind": self.kind,
                "metric": self.metric, "threshold_ms": self.threshold_ms,
                "objective": self.objective,
                "denominator": self.denominator,
                "description": self.description}


class SLOEngine:
    """Multi-window burn-rate evaluation over cumulative series.

    Feed it the CURRENT cumulative state (merged counters + histogram
    summaries) via `observe()`; it appends (ts, bad, total) points per
    spec and computes, for each window w,

        burn(w) = (bad fraction over the last w seconds) / objective

    A spec breaches when burn >= `burn_threshold` in EVERY window and
    at least one new bad event landed inside the fast window; it clears
    (hysteresis) when the fast-window burn drops back under threshold.
    `observe()` returns the NEW breach alerts from this evaluation.
    """

    def __init__(self, specs, fast_s=60.0, slow_s=300.0,
                 burn_threshold=1.0, now=time.time):
        self.specs = list(specs)
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.burn_threshold = float(burn_threshold)
        self._now = now
        self._series = {s.name: [] for s in self.specs}
        self._active = set()
        self.alerts = []
        self._lock = threading.Lock()

    def _bad_total(self, spec, counters, hists):
        if spec.kind == "latency":
            good, total = good_count(hists.get(spec.metric) or {},
                                     spec.threshold_ms)
            return float(total - good), float(total)
        bad = float(counters.get(spec.metric, 0.0))
        if spec.denominator is None:
            return bad, None
        return bad, float(counters.get(spec.denominator, 0.0))

    def _window_burn(self, pts, now, window_s, per_second, objective):
        """Burn rate over [now - window_s, now]; None if unevaluable."""
        cur = pts[-1]
        ref = pts[0]
        cutoff = now - window_s
        for p in pts:
            if p[0] <= cutoff:
                ref = p
            else:
                break
        d_bad = cur[1] - ref[1]
        if per_second:
            elapsed = max(cur[0] - ref[0], 1e-9)
            return (d_bad / elapsed) / objective, d_bad, elapsed
        d_total = (cur[2] or 0.0) - (ref[2] or 0.0)
        if d_total <= 0:
            return None, d_bad, 0.0
        return (d_bad / d_total) / objective, d_bad, d_total

    def observe(self, counters, hists, now=None):
        """Evaluate every spec against the current cumulative state;
        returns the list of NEW breach alert records."""
        now = self._now() if now is None else now
        new_alerts = []
        with self._lock:
            for spec in self.specs:
                bad, total = self._bad_total(spec, counters, hists)
                pts = self._series[spec.name]
                pts.append((now, bad, total))
                cutoff = now - self.slow_s * 2
                while len(pts) > 2 and pts[1][0] < cutoff:
                    pts.pop(0)
                per_second = (spec.kind == "rate"
                              and spec.denominator is None)
                burns = {}
                ok = True
                fast_bad = 0.0
                for label, w in (("fast", self.fast_s),
                                 ("slow", self.slow_s)):
                    burn, d_bad, _ = self._window_burn(
                        pts, now, w, per_second, spec.objective)
                    burns[label] = (None if burn is None
                                    else round(burn, 4))
                    if label == "fast":
                        fast_bad = d_bad
                    if burn is None or burn < self.burn_threshold:
                        ok = False
                breached = ok and fast_bad > 0
                if breached and spec.name not in self._active:
                    self._active.add(spec.name)
                    alert = {
                        "type": "slo_breach",
                        "slo": spec.name,
                        "time": now,
                        "burn": burns,
                        "bad": bad,
                        "total": total,
                        "objective": spec.objective,
                        "threshold_ms": spec.threshold_ms,
                        "metric": spec.metric,
                        "windows_s": [self.fast_s, self.slow_s],
                        "description": spec.description,
                    }
                    self.alerts.append(alert)
                    new_alerts.append(alert)
                elif not breached and spec.name in self._active:
                    fast = burns.get("fast")
                    if fast is not None and fast < self.burn_threshold:
                        self._active.discard(spec.name)
        return new_alerts

    def active(self):
        with self._lock:
            return sorted(self._active)


class RollingMedianDetector:
    """Point-wise anomaly detection against a rolling median: an
    observation is anomalous when it exceeds `k` times the median of
    the trailing window (after `min_samples` have been seen, so JIT
    warm-up steps train the baseline instead of paging on it).

    Used for `executor.step_anomalies` (straggler steps) and reusable
    for any strictly-positive latency-like series.
    """

    __slots__ = ("window", "k", "min_samples", "_ring", "anomalies")

    def __init__(self, window=32, k=3.0, min_samples=8):
        self.window = int(window)
        self.k = float(k)
        self.min_samples = int(min_samples)
        self._ring = []
        self.anomalies = 0

    def observe(self, v):
        """Feed one observation; True when it is out of family. The
        observation always joins the baseline (a sustained shift stops
        being anomalous once the median catches up — that is a level
        change, not a straggler)."""
        v = float(v)
        ring = self._ring
        anomalous = False
        if len(ring) >= self.min_samples:
            med = _median(ring)
            if med > 0 and v > self.k * med:
                anomalous = True
                self.anomalies += 1
        ring.append(v)
        if len(ring) > self.window:
            ring.pop(0)
        return anomalous

    def median(self):
        return _median(self._ring) if self._ring else None


def _median(xs):
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def latency_skew(per_shard_avg):
    """Per-shard latency spread: given {shard: avg_latency}, return
    (skew, worst_shard) where skew = worst avg / median avg — the
    straggler signal from the MLPerf pod-scale tuning work. None when
    fewer than two shards report."""
    items = [(k, float(v)) for k, v in per_shard_avg.items()
             if v is not None]
    if len(items) < 2:
        return None
    med = _median([v for _, v in items])
    worst, worst_v = max(items, key=lambda kv: kv[1])
    if med <= 0:
        return None
    return worst_v / med, worst
