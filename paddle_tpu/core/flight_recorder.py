"""Always-on flight recorder: dump recent spans + metrics on failure.

The span ring (core/trace.py) and the metric registry (core/monitor.py)
are always recording; this module turns them into a post-mortem artifact.
When `PADDLE_TPU_DUMP_DIR` is set, a failure writes one self-contained
JSON dump there:

- `PipelineStepError` (an in-flight async step failed —
  static/pipeline_runner.py raises at the materialization boundary),
- PS transport death (retry budget exhausted: DeadlineExceeded /
  ConnectionError out of distributed/ps/rpc.py, or the Communicator send
  thread dying),
- a fatal signal (SIGTERM by default; SIGUSR1 dumps on demand without
  killing the process) when `maybe_install()` ran at import.

Render a dump with `python tools/obs_report.py <dump.json>`: per-step
timeline, host-overhead breakdown, PS health, Pallas fallback rates.

With `PADDLE_TPU_DUMP_DIR` unset every hook is a no-op — the recorder
costs one env lookup on the failure path and nothing in steady state.
Dumps are rate-limited per reason so a failure storm (every handle of a
broken pipeline raising) cannot fill a disk.
"""
from __future__ import annotations

import contextlib
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import defaultdict

from . import monitor as _monitor
from . import trace as _trace

__all__ = ["dump", "dump_dir", "enabled", "suppressed", "maybe_install",
           "install_signal_handlers", "register_emergency_hook",
           "unregister_emergency_hook", "register_dump_listener",
           "unregister_dump_listener", "set_identity",
           "SCHEMA_VERSION", "SCHEMA_KEYS"]

SCHEMA_VERSION = 2
# tools/obs_report.py renders exactly these sections; its self_check()
# (registered in tools/framework_lint.py TOOL_CROSS_CHECKS) pins the two
# against each other so the dump format and the renderer cannot drift.
# v2 (cluster telemetry, core/telemetry.py) appended incident_id / role /
# peer_members; every consumer reads them with .get() so v1 dumps on
# disk keep rendering unchanged (regression-pinned in
# tests/test_flight_recorder.py against a committed v1 fixture).
SCHEMA_KEYS = ("schema", "reason", "time", "pid", "argv", "exception",
               "spans", "metrics", "flags", "env", "extra",
               "incident_id", "role", "peer_members")

_lock = threading.Lock()
_dumped = defaultdict(int)
_seq = 0
MAX_DUMPS_PER_REASON = 4

_prev_handlers: dict = {}


def dump_dir() -> str:
    return os.environ.get("PADDLE_TPU_DUMP_DIR", "")


def enabled() -> bool:
    return bool(dump_dir())


_suppress_tls = threading.local()


@contextlib.contextmanager
def suppressed(reason: str):
    """Suppress `reason` dumps on THIS thread for the scope — for outer
    retry layers whose inner layer would otherwise declare death
    prematurely (the Communicator rides out per-call retry exhaustion on
    all but its last send attempt)."""
    active = getattr(_suppress_tls, "reasons", None)
    if active is None:
        active = _suppress_tls.reasons = set()
    novel = reason not in active
    if novel:
        active.add(reason)
    try:
        yield
    finally:
        if novel:
            active.discard(reason)


def _is_suppressed(reason: str) -> bool:
    return reason in getattr(_suppress_tls, "reasons", ())


def _exception_record(exc):
    if exc is None:
        return None
    tb = None
    if getattr(exc, "__traceback__", None) is not None:
        tb = "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__))
    return {"type": type(exc).__name__, "message": str(exc),
            "traceback": tb}


def _flags_snapshot():
    try:
        from . import flags as _flags
        with _flags._LOCK:
            return dict(_flags._REGISTRY)
    except Exception:
        return {}


# Cluster identity (schema v2): a fleet member's role ("serve", "ps0",
# "trainer", ...) and its known peers, stamped into every dump so a
# merged incident can say WHO each record came from. Set once at member
# startup (core/telemetry.py's TelemetryShipper does it for its owner).
_role: str = ""
_peer_members: list = []


def set_identity(role=None, peers=None):
    """Declare this process's fleet identity for future dumps."""
    global _role, _peer_members
    if role is not None:
        _role = str(role)
    if peers is not None:
        _peer_members = [str(p) for p in peers]


def record(reason: str, exc=None, extra=None, incident_id=None) -> dict:
    """The dump payload (also used by obs_report --live). Key set is
    SCHEMA_KEYS, schema version SCHEMA_VERSION."""
    return {
        "schema": SCHEMA_VERSION,
        "reason": reason,
        "time": time.time(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "exception": _exception_record(exc),
        # ring (finished) + this thread's still-open spans — the span
        # enclosing the failure hasn't ended yet and would otherwise be
        # the one span missing from its own post-mortem
        "spans": [_trace.span_dict(s) for s in _trace.recent()]
                 + [dict(_trace.span_dict(s), open=True)
                    for s in _trace.open_spans()],
        "metrics": _monitor.snapshot(),
        "flags": _flags_snapshot(),
        "env": {k: v for k, v in os.environ.items()
                if k.startswith(("PADDLE_", "FLAGS_", "JAX_"))},
        "extra": extra or {},
        "incident_id": incident_id,
        "role": _role,
        "peer_members": list(_peer_members),
    }


# Emergency hooks: callables fired when a dump is requested for one of
# their reasons, INDEPENDENT of PADDLE_TPU_DUMP_DIR — the checkpoint
# tier's emergency synchronous save (incubate/checkpoint.py) rides the
# same trigger points as the recorder (PipelineStepError, SIGTERM)
# whether or not post-mortem dumps are configured. Each hook is
# (reasons, fn); fn(reason, exc) must never raise consequentially —
# failures are swallowed so a broken hook cannot mask the failure that
# fired it.
_emergency_hooks: list = []


def register_emergency_hook(fn, reasons=("pipeline_step_error",
                                         "signal_SIGTERM")):
    """Run `fn(reason, exc)` whenever a dump fires for one of `reasons`
    (even with the dump dir unset). Returns the hook handle for
    unregister_emergency_hook."""
    handle = (tuple(reasons), fn)
    with _lock:
        _emergency_hooks.append(handle)
    return handle


def unregister_emergency_hook(handle):
    with _lock:
        try:
            _emergency_hooks.remove(handle)
        except ValueError:
            pass


def _fire_emergency_hooks(reason, exc):
    with _lock:
        hooks = [fn for reasons, fn in _emergency_hooks
                 if reason in reasons]
    for fn in hooks:
        try:
            fn(reason, exc)
        except Exception:
            pass


# Dump listeners: fn(reason, exc, incident_id) fired for EVERY dump
# trigger regardless of reason and of PADDLE_TPU_DUMP_DIR — the cluster
# telemetry shipper uses this to report the trigger to the hub so the
# whole fleet dumps under one incident id. Listeners get the incident_id
# the dump was requested with (None for a locally-originated failure)
# so a hub-requested incident dump does not re-report itself.
_dump_listeners: list = []


def register_dump_listener(fn):
    with _lock:
        if fn not in _dump_listeners:
            _dump_listeners.append(fn)
    return fn


def unregister_dump_listener(fn):
    with _lock:
        try:
            _dump_listeners.remove(fn)
        except ValueError:
            pass


def _fire_dump_listeners(reason, exc, incident_id):
    with _lock:
        listeners = list(_dump_listeners)
    for fn in listeners:
        try:
            fn(reason, exc, incident_id)
        except Exception:
            pass


def dump(reason: str, exc=None, extra=None, incident_id=None,
         _fire_hooks=True):
    """Write a flight-recorder dump; returns the path, or None when
    disabled/rate-limited. NEVER raises — a recorder failure must not
    mask the failure being recorded."""
    try:
        if _fire_hooks and not _is_suppressed(reason):
            _fire_emergency_hooks(reason, exc)
        if not _is_suppressed(reason):
            _fire_dump_listeners(reason, exc, incident_id)
        d = dump_dir()
        if not d or _is_suppressed(reason):
            return None
        global _seq
        with _lock:
            if _dumped[reason] >= MAX_DUMPS_PER_REASON:
                return None
            _dumped[reason] += 1
            _seq += 1
            seq = _seq
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"obsdump_{reason}_{os.getpid()}_{seq:03d}.json")
        payload = record(reason, exc=exc, extra=extra,
                         incident_id=incident_id)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        return path
    except Exception:
        return None


# -- fatal-signal hook -------------------------------------------------------

def _handler(signum, frame):
    # Python delivers signals on the MAIN thread between bytecodes — the
    # interrupted code may be holding monitor/trace/flags locks (the hot
    # loop bumps counters constantly), and those are not reentrant. A
    # dump from the handler itself could deadlock on them; a side thread
    # either gets the locks when their holders release, or we give up at
    # the timeout and die dump-less. Best-effort by design.
    #
    # Emergency hooks (the checkpoint tier's synchronous grace save) run
    # FIRST, on the main thread, unbounded: the interrupted main thread
    # owns the model/optimizer state they capture, and a save that takes
    # longer than any fixed bound must complete rather than be killed
    # mid-write — delaying death is their entire purpose. Only the
    # metrics/trace dump rides the bounded side thread.
    reason = f"signal_{signal.Signals(signum).name}"
    if not _is_suppressed(reason):
        _fire_emergency_hooks(reason, None)
    th = threading.Thread(target=dump, args=(reason,),
                          kwargs={"_fire_hooks": False}, daemon=True)
    th.start()
    th.join(timeout=10.0)
    prev = _prev_handlers.get(signum)
    if callable(prev):
        prev(signum, frame)
    elif signum != signal.SIGUSR1 and prev != signal.SIG_IGN:
        # SIG_DFL — or None, i.e. a handler installed outside Python we
        # cannot call: restore the default disposition and re-raise so
        # the process still DIES on a fatal signal (a dump hook must
        # never make SIGTERM a no-op for the supervisor)
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def install_signal_handlers(signals=(signal.SIGTERM, signal.SIGUSR1)):
    """Chain a dump in front of the current handlers. SIGUSR1 becomes an
    on-demand dump (process keeps running); SIGTERM dumps then defers to
    whatever was installed (e.g. hapi's PreemptionGuard) or the default
    disposition. Main-thread only (CPython restriction) — silently
    no-ops elsewhere."""
    installed = []
    for sig in signals:
        try:
            prev = signal.signal(sig, _handler)
        except (ValueError, OSError):
            continue  # non-main thread or unsupported signal
        if prev is not _handler:
            _prev_handlers[sig] = prev
        installed.append(sig)
    return installed


def maybe_install():
    """Called from paddle_tpu import: arm the signal hook only when the
    dump dir is configured (and PADDLE_TPU_DUMP_ON_SIGNAL isn't 0)."""
    if not enabled():
        return []
    if os.environ.get("PADDLE_TPU_DUMP_ON_SIGNAL", "1") in ("0", "false"):
        return []
    return install_signal_handlers()
