"""Global flag registry.

TPU-native analog of the reference's gflags backbone
(reference: paddle/fluid/platform/flags.cc:33-565 and
pybind/global_value_getter_setter.cc): flags are declared once with a type
and default, may be seeded from `FLAGS_*` environment variables at import
time (matching fluid/__init__.py __bootstrap__), and are get/set-able at
runtime via `paddle_tpu.set_flags` / `get_flags`.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict

_LOCK = threading.Lock()
_REGISTRY: Dict[str, Any] = {}
_DEFS: Dict[str, tuple] = {}  # name -> (type, default, help)


def define_flag(name: str, default, help_str: str = ""):
    ftype = type(default)
    with _LOCK:
        _DEFS[name] = (ftype, default, help_str)
        env = os.environ.get(name)
        if env is not None:
            _REGISTRY[name] = _parse(ftype, env)
        else:
            _REGISTRY[name] = default


def _parse(ftype, text: str):
    if ftype is bool:
        return text.strip().lower() in ("1", "true", "yes", "on")
    return ftype(text)


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags equivalent."""
    with _LOCK:
        for name, value in flags.items():
            if name not in _DEFS:
                raise KeyError(f"unknown flag {name!r}")
            ftype = _DEFS[name][0]
            _REGISTRY[name] = _parse(ftype, value) if isinstance(value, str) and ftype is not str else ftype(value)


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    with _LOCK:
        return {name: _REGISTRY[name] for name in flags}


def flag(name: str):
    """Fast internal accessor."""
    return _REGISTRY[name]


# Core flag set (subset of reference platform/flags.cc relevant to TPU).
define_flag("FLAGS_check_nan_inf", False,
            "validate op outputs for nan/inf each step (reference platform/flags.cc:44)")
define_flag("FLAGS_benchmark", False, "sync and time each op")
define_flag("FLAGS_eager_delete_tensor_gb", 0.0, "GC threshold (no-op on XLA; kept for parity)")
define_flag("FLAGS_use_bf16_matmul", True, "prefer bfloat16 matmul accumulation on MXU")
define_flag("FLAGS_seed", 0, "global random seed")
define_flag("FLAGS_log_level", 0, "verbose log level (glog VLOG equivalent)")
define_flag("FLAGS_allocator_strategy", "xla", "kept for parity; XLA owns device memory")
define_flag("FLAGS_enable_profiler", False, "enable host event profiler")
define_flag("FLAGS_log_memory_estimate", False,
            "on each fresh Executor lowering, run the liveness-based "
            "peak-memory estimator (static/shape_infer.py analyze_memory) "
            "and publish executor/estimated_peak_bytes to the monitor")
define_flag("FLAGS_log_spmd_estimate", False,
            "on each fresh Executor lowering with a registered mesh, run "
            "the SPMD sharding analyzer (static/spmd_analyzer.py) and "
            "publish the spmd.{collective_bytes,hbm_estimate,"
            "resharding_count} monitor gauges (non-strict; set "
            "PADDLE_TPU_VERIFY_SPMD=1 to FAIL compilation on findings)")
define_flag("FLAGS_spmd_plan_beam", 4,
            "beam width of the auto-sharding planner's grouped search "
            "(static/spmd_planner.py). Must be wide enough to carry a "
            "chain-opening candidate (column-parallel qkv is illegal "
            "until the row-parallel out-proj closes the chain) past the "
            "always-legal replicated state")
define_flag("FLAGS_spmd_plan_sweeps", 1,
            "coordinate-descent polish passes the planner runs over the "
            "beam winner (feasible moves only; 0 disables)")
define_flag("FLAGS_spmd_plan_coll_weight", 1.0,
            "planner objective weight on predicted collective bytes/step "
            "(spmd_analyzer pricing)")
define_flag("FLAGS_spmd_plan_hbm_weight", 1.0,
            "planner objective weight on predicted peak per-device HBM "
            "bytes")
define_flag("FLAGS_spmd_plan_pp_micro", 8,
            "microbatch count the pipeline stage-cut planner prices a "
            "step with (static/spmd_planner.plan_pipeline): bubble "
            "fraction, ppermute wire bytes and per-tick hidden payload "
            "all scale with it")
define_flag("FLAGS_spmd_plan_pp_beam", 8,
            "beam width of the stage-cut search over legal cut "
            "boundaries (diagnostic-stratified, same machinery as the "
            "SPMD layout beam)")
define_flag("FLAGS_spmd_plan_pp_flops_weight", 1.0,
            "stage-cut objective weight on the pipeline-full compute "
            "proxy max(stage FLOPs) * num_micro (compute balance)")
define_flag("FLAGS_spmd_plan_pp_wire_weight", 1.0,
            "stage-cut objective weight on the ppermute wire bytes/step "
            "(pipeline.schedule_collectives of the cut frontier)")
define_flag("FLAGS_spmd_plan_pp_hbm_weight", 1.0,
            "stage-cut objective weight on max per-stage peak HBM "
            "(analyze_memory restricted to each stage's op range)")
define_flag("FLAGS_spmd_plan_pp_bubble_weight", 1.0,
            "stage-cut objective weight on the bubble cost "
            "bubble_fraction * total FLOPs (idle compute)")
define_flag("FLAGS_topology_ici_gbps", 90.0,
            "assumed per-device intra-pod (ICI) link bandwidth in GB/s "
            "for the two-tier topology cost model (mesh.axis_tiers / "
            "spmd_analyzer per-collective cost_us pricing)")
define_flag("FLAGS_topology_dcn_gbps", 6.25,
            "assumed per-device inter-pod (DCN) link bandwidth in GB/s — "
            "an order of magnitude below ICI, the cliff the hierarchical "
            "dp sync decomposition exists to avoid")
define_flag("FLAGS_topology_localsgd_k", 4,
            "k_steps the topology report prices the LocalSGD degraded "
            "sync mode with (one cross-replica average every k local "
            "steps amortizes the dp sync wire bytes by 1/k)")
define_flag("FLAGS_topology_localsgd_ratio", 8.0,
            "DCN-dominance threshold: when even the HIERARCHICAL dp "
            "sync's inter-pod cost_us exceeds its intra-pod cost_us by "
            "this factor, the topology report recommends the LocalSGD "
            "degraded mode instead (accuracy-for-bandwidth trade)")
define_flag("PADDLE_TRAFFIC_SEED", 0,
            "base seed for the traffic lab's named splitmix64 draw "
            "streams (traffic/workload.py); two runs of the same spec "
            "with the same seed are byte-identical — schedule AND "
            "per-request token draws")
define_flag("PADDLE_TRAFFIC_TIME_SCALE", 1.0,
            "wall-clock multiplier the harness paces a workload "
            "schedule with (traffic/harness.run_spec): 1.0 replays the "
            "spec in real time, 0.5 compresses it 2x (stress), 2.0 "
            "stretches it (debug)")
define_flag("PADDLE_TRAFFIC_CLIENTS", 4,
            "number of submitter threads the traffic harness partitions "
            "a schedule across (round-robin by event index)")
define_flag("FLAGS_capacity_p50_band_pct", 25.0,
            "capacity_plan --validate error band: hub-observed "
            "throughput and TTFT/token p50 must land within this "
            "percentage of the model's prediction")
define_flag("FLAGS_capacity_p99_band_pct", 40.0,
            "capacity_plan --validate error band for the tail: "
            "hub-observed TTFT/token p99 within this percentage of "
            "prediction (tails carry sampling noise the p50 band "
            "doesn't)")
define_flag("FLAGS_capacity_knee_rho", 0.85,
            "utilization the capacity report flags as the saturation "
            "knee: offered loads driving predicted slot utilization "
            "above this are marked over-knee (queueing delay diverges)")
define_flag("FLAGS_capacity_calib_beats", 32,
            "decode beats the CPU calibration measures per active-level "
            "when fitting the device profile's beat_ms base/slope "
            "(static/capacity.calibrate)")
define_flag("FLAGS_use_flash_attention", True,
            "route attention through the Pallas flash kernel on TPU "
            "(paddle_tpu.ops.pallas.flash_attention)")
define_flag("FLAGS_flash_min_seq", 1024,
            "dispatch threshold: the Pallas flash-attention kernel engages "
            "when s_k >= this (long-context regime where O(s^2) score "
            "materialization dominates); below it XLA's fused attention is "
            "faster on the MXU at these shapes. 0 forces the kernel on "
            "whenever shapes allow.")
define_flag("FLAGS_flash_block_q", 0,
            "flash attention q block size (0 = auto: 256 for s>=1024 else "
            "128)")
define_flag("FLAGS_flash_block_k", 0,
            "flash attention k block size (0 = auto)")
define_flag("FLAGS_fused_ce_block_n", 0,
            "fused CE token-block size (0 = auto 512)")
define_flag("FLAGS_fused_ce_block_v", 0,
            "fused CE vocab-block size (0 = auto 512)")
define_flag("FLAGS_flash_attention_interpret", False,
            "also use the flash kernel off-TPU via the Pallas interpreter "
            "(slow; for tests)")
define_flag("FLAGS_use_fused_ce", True,
            "route linear+cross-entropy loss heads through the Pallas "
            "fused kernel on TPU (paddle_tpu.ops.pallas.fused_ce)")
define_flag("FLAGS_pallas_interpret", False,
            "run all Pallas kernels off-TPU via the interpreter (slow; "
            "for tests)")
define_flag("FLAGS_use_decode_attention", True,
            "route StaticKVCache incremental-decode attention through the "
            "Pallas single-query flash kernel "
            "(paddle_tpu.ops.pallas.decode_attention): cache-length "
            "masking in-kernel, fully-masked KV blocks skipped via the "
            "grid instead of streaming the whole max_seq_len cache")
define_flag("FLAGS_decode_block_k", 0,
            "decode-attention KV block size (0 = auto: autotune table or "
            "the 128-column heuristic). Smaller blocks skip more of a "
            "mostly-empty cache; larger blocks amortize grid overhead")
define_flag("FLAGS_pallas_autotune", True,
            "block-size autotuning for Pallas kernels: measure candidate "
            "block configs at each new (kernel, shape-bucket, dtype, "
            "backend) key and cache the winner (in-process; on disk too "
            "when PADDLE_TPU_PALLAS_AUTOTUNE_CACHE names a json file). "
            "Off-TPU the heuristic defaults are used instead — interpret "
            "timings are meaningless. FLAGS_flash_block_* / "
            "FLAGS_fused_ce_block_* / FLAGS_decode_block_k overrides "
            "always win over the table")
define_flag("FLAGS_pallas_autotune_force", False,
            "measure autotune candidates even off-TPU (tests exercise the "
            "measuring path in interpreter mode; never useful in prod)")
define_flag("FLAGS_pallas_force_compile", False,
            "force compiled (Mosaic) lowering of Pallas kernels even "
            "off-TPU: tools/hlo_evidence.py uses this to AOT-lower bench "
            "graphs for a TPU target on a dev box. Such programs lower "
            "and cost-analyze fine but only *run* on real TPU hardware")
define_flag("FLAGS_pallas_strict", False,
            "re-raise Pallas kernel failures instead of demoting to the "
            "jnp fallback (kernel development; the default False keeps a "
            "kernel crash from ever aborting a training/bench run — each "
            "demotion bumps pallas.fallback.{kernel}.{reason} in "
            "core/monitor)")

# --- continuous-batching decode serving (inference/serving.py,
# --- nn/kv_pool.py, ops/pallas/decode_attention.py paged kernel) --------
define_flag("FLAGS_use_paged_attention", True,
            "route paged (block-table) decode attention through the "
            "Pallas kernel (ops/pallas/decode_attention."
            "paged_decode_attention): per-request block tables ride the "
            "scalar-prefetch path next to the ragged lengths, so a "
            "decode step's KV reads scale with each request's LIVE "
            "blocks, not max_seq_len. Off, the serve loop runs the jnp "
            "gather fallback (nn/kv_pool.paged_attention_ref)")
define_flag("FLAGS_serve_block_size", 0,
            "tokens per physical KV-pool block (nn/kv_pool.KVBlockPool); "
            "0 = auto: the paged-decode autotune table on TPU, else the "
            "128-column heuristic. Must be a multiple of the 8-row "
            "sublane tile. Smaller blocks waste less pool memory per "
            "short request; larger blocks amortize kernel grid overhead")
define_flag("FLAGS_serve_kv_blocks", 512,
            "physical blocks in the serving KV pool (per layer, k+v "
            "arenas); the pool is the admission currency — waiting "
            "requests stay queued until retiring streams free enough "
            "blocks (inference/serving.py backpressure)")
define_flag("FLAGS_serve_max_active", 64,
            "decode slots in the serving batch: the fused per-step "
            "decode processes this many concurrent streams (idle slots "
            "are masked to the trash block, costing no KV reads)")

define_flag("FLAGS_executor_max_inflight", 2,
            "async executor pipeline depth: how many dispatched-but-not-"
            "materialized steps the training hot loop keeps queued "
            "(static/pipeline_runner.py). jax dispatch is non-blocking, so "
            "N in-flight steps keep the device busy while the host "
            "converts/prefetches the next batches; fetches materialize "
            "only at print_period/callback/epoch boundaries. 0 restores "
            "the fully synchronous per-step loop")
define_flag("FLAGS_executor_scan_steps", 0,
            "scan-fused megasteps: when > 1 and the feed shapes are "
            "stable, the pipelined loop stacks K batches and runs ONE "
            "compiled lax.scan over the existing step — 1 dispatch per K "
            "steps instead of K, bitwise-equal to the serial loop (RNG "
            "keys, lr/t schedule threaded per iteration). Opt-in: "
            "dispatch-bound small programs win; large programs are "
            "already compute-bound. 0/1 disables fusion")
define_flag("FLAGS_executor_cache_size", 32,
            "LRU bound on the Executor's compiled-program cache (entries "
            "keyed on program.uid + feed/fetch signature); evictions bump "
            "executor/cache_evictions in core/monitor")

# --- observability (core/trace.py, core/monitor.py, flight recorder) ----
define_flag("FLAGS_trace_ring_size", 4096,
            "bounded ring of recent finished spans kept by the always-on "
            "tracer (core/trace.py) — the flight recorder's feed: on "
            "PipelineStepError / PS transport death / fatal signal the "
            "last N spans are dumped to PADDLE_TPU_DUMP_DIR. 0 disables "
            "the bound (unbounded ring; tests only). Runtime set_flags "
            "changes apply at the next trace.start()/reset() — call "
            "trace.set_ring_size() to resize immediately")
define_flag("FLAGS_monitor_series_len", 256,
            "per-metric bounded time-series ring in core/monitor: every "
            "stat_add/stat_set/observe appends (unix_ts, value) so dumps "
            "and dashboards see a trajectory, not just the final value")

# --- PS transport fault tolerance (distributed/ps/rpc.py) ---------------
# The reference's brpc channel exposes the same three knobs
# (connect_timeout_ms / timeout_ms / max_retry in brpc_ps_client.cc);
# flag names double as their env-var spelling, so a job script can export
# PADDLE_PS_CALL_TIMEOUT=5 without touching code.
define_flag("PADDLE_PS_CALL_TIMEOUT", 60.0,
            "per-RPC deadline in seconds; a call that stalls past it "
            "times out, retries, and finally raises DeadlineExceeded")
define_flag("PADDLE_PS_MAX_RETRIES", 5,
            "transport retry budget per call (attempts = retries + 1); "
            "mutating calls are made retry-safe by the server-side "
            "idempotent replay cache")
define_flag("PADDLE_PS_BACKOFF_BASE_S", 0.05,
            "first retry backoff in seconds; doubles per retry with "
            "jitter up to PADDLE_PS_BACKOFF_MAX_S")
define_flag("PADDLE_PS_BACKOFF_MAX_S", 2.0,
            "exponential backoff ceiling in seconds")
define_flag("PADDLE_PS_CONNECT_RETRY_S", 30.0,
            "initial-dial retry window: workers racing the server's bind "
            "at job start keep redialing this long before giving up")
define_flag("PADDLE_PS_MAX_FRAME", 1 << 30,
            "largest RPC frame either side will accept; a length prefix "
            "over this is rejected as a FrameError instead of an "
            "unbounded allocation from one garbled header")
define_flag("PADDLE_PS_REPLAY_CACHE", 512,
            "per-client entries in the server's idempotent-replay LRU; "
            "a retried mutating request inside this window replays the "
            "cached reply instead of re-applying the gradient")
define_flag("PADDLE_PS_SEND_RETRIES", 2,
            "extra Communicator send-thread attempts (with backoff) on "
            "top of the per-call transport retries before the thread "
            "declares itself dead")

# --- PS replicated storage tier (distributed/ps/{shard_map,replica}.py) --
define_flag("PADDLE_PS_REPLICA_BACKUPS", 0,
            "backups per shard when the fleet wiring builds the initial "
            "shard map (0 = replication off: the default map reproduces "
            "the legacy id%n_servers placement exactly). With k>0 every "
            "mutation is applied on the primary, forwarded to its "
            "backups under the SAME replay id, and acked only once "
            "durable on the write quorum")
define_flag("PADDLE_PS_REPLICA_QUORUM", 0,
            "replicas (primary included) that must ack a write before "
            "the client is acked; 0 = every LIVE replica (unreachable "
            "backups are evicted from the map rather than wedging "
            "writes)")
define_flag("PADDLE_PS_REPLICA_DELTA_LOG", 512,
            "per-table entries in the replay-keyed mutation log primaries "
            "keep for rejoin catch-up: a restarted server loads the "
            "snapshot, then replays the log suffix past its cursor; a "
            "cursor that fell off the bounded log restarts the fetch")
define_flag("PADDLE_PS_HEARTBEAT_S", 0.5,
            "replica heartbeat interval in seconds: every server beats "
            "replica_beat into its peers; beat replies gossip shard-map "
            "epochs so a behind server catches up")
define_flag("PADDLE_PS_HEARTBEAT_TIMEOUT_S", 3.0,
            "suspicion deadline: a primary whose beats stop for this "
            "long is declared dead and its first live backup promotes "
            "itself (shard-map epoch bump + broadcast)")
define_flag("PADDLE_PS_FAILOVER_RETRIES", 8,
            "extra client re-route attempts per logical call after a "
            "stale-map redirect or dead endpoint; paced by "
            "PADDLE_PS_FAILOVER_BACKOFF_S, the loop must outlast one "
            "heartbeat timeout + promotion")
define_flag("PADDLE_PS_FAILOVER_BACKOFF_S", 0.25,
            "base pause between client failover re-routes (grows "
            "linearly up to 4x)")

# --- sharded embedding engine (distributed/ps/{client,heter,embedding}.py) --
define_flag("PADDLE_PS_FANOUT_THREADS", 4,
            "per-shard fan-out concurrency of batched sparse lookups: a "
            "pull whose (deduped) ids span several shard primaries issues "
            "one RPC per shard from a pool of this many threads, so the "
            "batch costs max(shard latency), not the sum. 1 restores the "
            "serial per-shard loop (bitwise-identical results either way "
            "— shard slices are disjoint)")
define_flag("PADDLE_PS_PREFETCH_DEPTH", 2,
            "embedding-prefetch window depth (distributed/ps/embedding."
            "EmbeddingPrefetcher riding static/pipeline_runner."
            "InflightDriver): how many batches of sparse pulls may be in "
            "flight ahead of the training step. Results stay BITWISE "
            "equal to synchronous pulls: ids pushed after a batch's "
            "prefetch snapshot are re-pulled at materialization "
            "(conflict fix-up), so overlap never trades determinism")
define_flag("PADDLE_PS_HETER_CACHE_ROWS", 65536,
            "hot-id LRU bound on the HeterPS device-resident embedding "
            "cache (distributed/ps/heter.HeterPSCache): rows past the "
            "bound evict oldest-first into the host-RAM tier (see "
            "PADDLE_PS_HETER_HOST_ROWS), bumping ps.heter.evictions — "
            "device HBM holds the hot working set, not the vocab")
define_flag("PADDLE_PS_HETER_HOST_ROWS", 262144,
            "host-RAM second tier of the HeterPS cache: rows evicted "
            "from the device LRU park here (HeterPS lineage — tables "
            "larger than device memory tier through host DRAM before "
            "the PS); a host hit re-promotes without a PS RPC "
            "(ps.heter.host_hits). 0 disables the tier (evictions go "
            "straight back to the PS)")

# --- trainer-side fault tolerance (incubate/checkpoint.py,
# --- distributed/elastic.py Supervisor, distributed/launch.py --elastic) --
define_flag("PADDLE_CKPT_VERIFY", True,
            "verify checkpoint manifests (per-leaf sha256 + shape/dtype "
            "schema) on restore; a corrupt/partial/schema-mismatched "
            "step is quarantined and restore walks back to the newest "
            "VERIFIED checkpoint instead of loading garbage. Off, the "
            "manifest is still written but restore trusts the data")
define_flag("PADDLE_ELASTIC_MAX_RESTARTS", 3,
            "per-trainer restart budget of the elastic supervisor "
            "(distributed/elastic.py Supervisor / launch.py --elastic); "
            "a rank that dies or stalls more than this many times fails "
            "the whole job with the child's exit status")
define_flag("PADDLE_ELASTIC_RESTART_BACKOFF_S", 1.0,
            "base pause before an elastic trainer restart; grows "
            "linearly with that rank's restart count so a crash loop "
            "cannot hot-spin the supervisor")
define_flag("PADDLE_ELASTIC_STALL_TIMEOUT_S", 300.0,
            "supervisor-side stall deadline: a trainer whose heartbeat "
            "file keeps beating but whose step counter has not advanced "
            "for this long is flight-recorded, killed, and restarted "
            "(a hung collective or starved input pipeline looks exactly "
            "like this)")
define_flag("PADDLE_ELASTIC_HEARTBEAT_TIMEOUT_S", 60.0,
            "supervisor-side liveness deadline: a trainer whose "
            "heartbeat file is older than this (or unreadable) is "
            "declared dead and restarted")

# --- online learning (dataset/streaming.py, static/executor.py online
# --- mode, distributed/ps/publish.py, inference/serving.py hot-swap) ---
define_flag("PADDLE_STREAM_QUEUE_CAP", 1024,
            "bounded-queue capacity of dataset/streaming.StreamingDataset: "
            "producers (ServeLoop completion hooks) block in offer() once "
            "this many undelivered records are buffered — backpressure "
            "toward the serving tier instead of unbounded memory growth")
define_flag("PADDLE_STREAM_DEDUPE_WINDOW", 4096,
            "record-id dedupe window of StreamingDataset: the ids of the "
            "last N accepted records are remembered and re-offers of any "
            "of them are rejected (at-least-once transport in, exactly-"
            "once training batches out). The window rides checkpoints "
            "(state_dict), so a restarted trainer keeps rejecting "
            "records it already trained on")
define_flag("PADDLE_ONLINE_SYNC_EVERY", 1,
            "flush cadence of the online (continuous Downpour) trainer "
            "mode in static/executor.py: accumulated sparse deltas are "
            "pushed to the PS via push_sparse_delta every this many "
            "batches — one replay-id-protected RPC per touched shard "
            "per flush")
define_flag("PADDLE_ONLINE_STALENESS_BATCHES", 4,
            "bounded-staleness knob of the online trainer: the hard "
            "bound on batches trained past the last SUCCESSFUL delta "
            "flush. A transiently failing flush (PS chaos, failover in "
            "progress) is retried next cadence until this bound, then "
            "the flush error propagates (fail-stop) rather than letting "
            "the served model fall arbitrarily behind")

# --- cluster telemetry plane (core/telemetry.py, core/slo.py,
# --- tools/cluster_obs_drill.py) ---
define_flag("PADDLE_TELEMETRY_HUB", "",
            "host:port of a TelemetryHub. When set, processes that opt "
            "in (drills, bench.py snapshot emitters, anything that "
            "starts a TelemetryShipper) ship metric deltas / span "
            "batches there; empty (the default) means fully local "
            "observability, no network")
define_flag("PADDLE_TELEMETRY_FLUSH_S", 0.5,
            "TelemetryShipper flush cadence: every this many seconds "
            "the background thread snapshots the monitor registry and "
            "ships one replay-keyed delta batch to the hub. The hot "
            "path only ever appends to an in-memory buffer — a slow or "
            "dead hub can delay shipping, never a decode beat")
define_flag("PADDLE_TELEMETRY_SPAN_BUFFER", 2048,
            "bound on the shipper's finished-span buffer. When the hub "
            "falls behind and the buffer is full, new spans are dropped "
            "on the floor and counted in telemetry.dropped_spans / "
            "telemetry.dropped_batches (backpressure by shedding, "
            "never by blocking the thread that finished the span)")
define_flag("PADDLE_TELEMETRY_INCIDENT_WINDOW_S", 10.0,
            "incident coalescing window of the TelemetryHub: flight-"
            "recorder triggers and SLO breaches arriving within this "
            "many seconds of an open incident JOIN it (one incident id, "
            "one merged dump) instead of opening a new one")
define_flag("PADDLE_SLO_EVAL_S", 1.0,
            "cadence of the hub's SLO engine: every this many seconds "
            "the merged counters/histograms are appended to the burn-"
            "rate series and every SLOSpec is re-evaluated")
define_flag("PADDLE_SLO_FAST_WINDOW_S", 60.0,
            "fast burn-rate window: a breach requires the bad fraction "
            "over BOTH this window and the slow window to exceed the "
            "objective — the fast window bounds time-to-detect, the "
            "slow window filters blips")
define_flag("PADDLE_SLO_SLOW_WINDOW_S", 300.0,
            "slow burn-rate window (see PADDLE_SLO_FAST_WINDOW_S); "
            "also bounds how much burn-rate history the engine retains "
            "per SLO spec (2x this window)")
