"""Static graph Program.

Analog of the reference's graph-building layer (reference:
python/paddle/fluid/framework.py — Program/Block/Operator/Variable around
:976 and :2900; serialized as framework/framework.proto ProgramDesc).

Design delta (SURVEY.md §7.1 "One IR, compiler-executed"): the Program is a
flat SSA op list over symbolic Variables. There is no op-by-op interpreter —
the Executor lowers the whole Program to ONE jitted function (the
"Executor hot loop" executor.cc:473 becomes a single XLA execution), so
ChooseKernel/PrepareData/InferShape-at-runtime all disappear into the
compiler. Parameters and other persistables live in a name→array Scope
(framework/scope.h analog) threaded through the compiled step and written
back after each run.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..core.tensor import Tensor
from ..core.dtype import to_jax_dtype

__all__ = ["Variable", "OpNode", "Program", "Scope", "global_scope",
           "program_guard", "default_main_program", "default_startup_program",
           "name_scope"]


class Scope:
    """name -> device array store (reference framework/scope.h)."""

    def __init__(self):
        self._vars: Dict[str, Any] = {}

    def set(self, name, value):
        self._vars[name] = value

    def get(self, name):
        return self._vars[name]

    def has(self, name):
        return name in self._vars

    def find_var(self, name):
        return _ScopeVarView(self, name) if name in self._vars else None

    def var_names(self):
        return list(self._vars)

    def drop_kids(self):
        pass  # parity no-op: no kid scopes needed without per-run var churn


class _ScopeVarView:
    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def get_tensor(self):
        return self._scope.get(self._name)

    def set(self, value, place=None):
        self._scope.set(self._name, value)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


class Variable(Tensor):
    """Symbolic SSA value (reference framework.py:976 Variable).

    `_value` stays None; shape/dtype come from the recorded aval. A Variable
    may be scope-backed (persistable parameters/buffers), fed (data), or an
    intermediate op output.
    """

    __slots__ = ("aval", "var_id", "is_data", "scope_name", "program")

    _counter = [0]
    _lock = threading.Lock()

    def __init__(self, shape, dtype, name=None, is_data=False,
                 scope_name=None, program=None):
        Tensor.__init__(self, None, stop_gradient=True, _internal=True)
        self.aval = jax.ShapeDtypeStruct(tuple(shape), to_jax_dtype(dtype))
        with Variable._lock:
            Variable._counter[0] += 1
            self.var_id = Variable._counter[0]
        self.name = name or f"_var_{self.var_id}"
        self.is_data = is_data
        self.scope_name = scope_name
        self.program = program

    # Tensor surface backed by the aval
    @property
    def shape(self):
        return tuple(int(s) for s in self.aval.shape)

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    @property
    def dtype(self):
        return self.aval.dtype

    def numpy(self):
        # persistables are readable from the scope between runs
        if self.scope_name is not None and global_scope().has(self.scope_name):
            return np.asarray(global_scope().get(self.scope_name))
        raise RuntimeError(
            f"Variable {self.name} has no materialized value; fetch it via "
            "Executor.run(fetch_list=[...])")

    def set_value(self, value):
        if self.scope_name is None:
            raise RuntimeError("only persistable variables can set_value")
        import jax.numpy as jnp
        global_scope().set(self.scope_name,
                           jnp.asarray(np.asarray(value), self.aval.dtype))
        return self

    def detach(self):
        # no tape in static mode; symbolic identity is the detachment
        return self

    def _no_concrete(self, what):
        raise TypeError(
            f"{what} of symbolic Variable {self.name!r} is undefined at "
            "graph-build time — Python control flow cannot branch on graph "
            "values (the reference raises the same way, framework.py "
            "Variable.__bool__). Use paddle.static.nn.cond / "
            "paddle.static.nn.while_loop, or decorate the function with "
            "@paddle.jit.to_static so the branch converts automatically "
            "(jit/dy2static.py)")

    def __bool__(self):
        self._no_concrete("the truth value")

    def __float__(self):
        self._no_concrete("float()")

    def __int__(self):
        self._no_concrete("int()")

    def __index__(self):
        self._no_concrete("index()")

    def clone(self):
        from .. import ops
        return ops.assign(self)

    def __repr__(self):
        kind = "data" if self.is_data else (
            "persist" if self.scope_name else "tmp")
        return (f"Variable(name={self.name}, shape={list(self.shape)}, "
                f"dtype={self.dtype}, kind={kind})")

    def _rebind(self, new):
        """In-place write in static mode: later reads see the new SSA value;
        if scope-backed, the program records a state write-back (how BN
        running stats persist across runs)."""
        if isinstance(new, Variable):
            if self.scope_name is not None and self.program is not None:
                self.program.state_writes[self.scope_name] = new.var_id
            # adopt the new SSA identity for subsequent reads
            self.aval = new.aval
            self.var_id = new.var_id
            return self
        return Tensor._rebind(self, new)


class _Ref:
    """Snapshot of a Variable's SSA id at record time (ids on scope-backed
    Variables mutate when layers rebind them, e.g. BN running stats)."""

    __slots__ = ("var_id", "name")

    def __init__(self, var: "Variable"):
        self.var_id = var.var_id
        self.name = var.name


class OpNode:
    """One recorded op: raw_fn over a flat (args + kwargs-leaves) list;
    the kwargs pytree is rebuilt at execution time."""

    __slots__ = ("fn", "name", "flat", "n_args", "kw_tree", "out_vars",
                 "out_ids")

    def __init__(self, fn, name, flat, n_args, kw_tree, out_vars):
        self.fn = fn
        self.name = name
        # snapshot symbolic args as _Refs NOW (ids mutate on rebind)
        self.flat = [(_Ref(a) if isinstance(a, Variable) else a)
                     for a in flat]
        self.n_args = n_args
        self.kw_tree = kw_tree
        self.out_vars = out_vars
        self.out_ids = [o.var_id for o in out_vars]

    # -- pickling: ops serialize by registry name; array literals as numpy --
    def __getstate__(self):
        import numpy as _np
        fn = self.fn
        fn_ref = ("opreg", fn.op_name) if hasattr(fn, "op_name") else fn
        flat = [(_np.asarray(a) if hasattr(a, "dtype") and hasattr(a, "shape")
                 and not isinstance(a, (_Ref, _np.ndarray)) else a)
                for a in self.flat]
        return {"fn": fn_ref, "name": self.name, "flat": flat,
                "n_args": self.n_args, "kw_tree": self.kw_tree,
                "out_vars": self.out_vars, "out_ids": self.out_ids}

    def __setstate__(self, state):
        fn = state["fn"]
        if isinstance(fn, tuple) and fn[0] == "opreg":
            from ..ops import OP_REGISTRY
            fn = OP_REGISTRY[fn[1]].raw
        self.fn = fn
        self.name = state["name"]
        self.flat = state["flat"]
        self.n_args = state["n_args"]
        self.kw_tree = state["kw_tree"]
        self.out_vars = state["out_vars"]
        self.out_ids = state["out_ids"]


class Program:
    """Recorded op list + feed/persistable registry
    (reference framework.py Program; ProgramDesc proto)."""

    _uid_counter = [0]

    def __init__(self, name="main"):
        self.name = name
        with Variable._lock:
            Program._uid_counter[0] += 1
            self.uid = Program._uid_counter[0]
        self.ops: List[OpNode] = []
        self.data_vars: Dict[str, Variable] = {}
        self.persistable_vars: Dict[str, Variable] = {}
        self.persist_ids: Dict[str, int] = {}
        self.state_writes: Dict[str, int] = {}  # scope_name -> var_id
        self.backward_section = None   # (loss_var, [(param_var, grad_var)])
        self.optimizer_section = None  # (optimizer, [(param_var, grad_var)])
        self.random_seed = None
        self._version = 0

    # -- recording ----------------------------------------------------------
    def append_op(self, fn, name, flat, n_args, kw_tree, out_avals):
        outs = []
        for aval in out_avals:
            v = Variable(aval.shape, aval.dtype, program=self)
            outs.append(v)
        self.ops.append(OpNode(fn, name, flat, n_args, kw_tree, outs))
        self._version += 1
        return outs

    def add_data_var(self, var: Variable):
        self.data_vars[var.name] = var

    def add_persistable(self, var: Variable):
        self.persistable_vars[var.scope_name] = var
        # reads recorded before any rebind resolve against this seed id
        self.persist_ids[var.scope_name] = var.var_id

    # -- introspection (parity with Program.to_string / list_vars) ----------
    def list_vars(self):
        seen = {}
        for v in list(self.data_vars.values()) + list(self.persistable_vars.values()):
            seen[v.var_id] = v
        for op in self.ops:
            for v in op.out_vars:
                seen[v.var_id] = v
        return list(seen.values())

    def global_block(self):
        return self

    def all_parameters(self):
        return [v for v in self.persistable_vars.values()]

    @property
    def num_blocks(self):
        return 1

    def to_string(self, throw_on_error=False, with_details=False):
        lines = [f"Program<{self.name}> ({len(self.ops)} ops)"]
        for v in self.data_vars.values():
            lines.append(f"  data  {v.name}: {list(v.shape)} {v.dtype}")
        for v in self.persistable_vars.values():
            lines.append(f"  persist {v.scope_name}: {list(v.shape)} {v.dtype}")
        for op in self.ops:
            ins = ", ".join(a.name if isinstance(a, _Ref)
                            else (f"const{list(a.shape)}" if hasattr(a, "shape")
                                  else repr(a))
                            for a in op.flat[:op.n_args])
            outs = ", ".join(o.name for o in op.out_vars)
            lines.append(f"  {op.name}({ins}) -> {outs}")
        if self.backward_section:
            loss, pairs = self.backward_section
            lines.append(f"  [backward] d{loss.name} -> "
                         f"{[p.name for p, _ in pairs]}")
        if self.optimizer_section:
            opt, pairs = self.optimizer_section
            lines.append(f"  [optimize] {type(opt).__name__} on "
                         f"{len(pairs)} params")
        return "\n".join(lines)

    __str__ = to_string

    def clone(self, for_test=False):
        p = Program(self.name + ("_test" if for_test else "_clone"))
        p.ops = ([self._op_for_test(op) for op in self.ops] if for_test
                 else list(self.ops))
        p.data_vars = dict(self.data_vars)
        p.persistable_vars = dict(self.persistable_vars)
        p.persist_ids = dict(self.persist_ids)
        # test programs must not advance running statistics
        p.state_writes = {} if for_test else dict(self.state_writes)
        if not for_test:
            p.backward_section = self.backward_section
            p.optimizer_section = self.optimizer_section
        return p

    @staticmethod
    def _op_for_test(op: "OpNode") -> "OpNode":
        """Rewrite train-mode ops for inference (the reference's
        clone-for-test op flipping, framework.py Program.clone)."""
        import jax.tree_util as jtu
        if op.name == "batch_norm":
            kw = jtu.tree_unflatten(op.kw_tree, op.flat[op.n_args:])
            if kw.get("training", False):
                kw = dict(kw, training=False)
                leaves, tree = jtu.tree_flatten(kw)
                new = OpNode.__new__(OpNode)
                new.fn, new.name = op.fn, op.name
                new.flat = op.flat[:op.n_args] + leaves
                new.n_args, new.kw_tree = op.n_args, tree
                new.out_vars, new.out_ids = op.out_vars, op.out_ids
                return new
        if op.name in ("dropout_op", "alpha_dropout"):
            new = OpNode.__new__(OpNode)
            new.fn = lambda x, *a, **k: x  # identity at inference
            new.name = f"{op.name}_identity"
            new.flat, new.n_args = op.flat, op.n_args
            new.kw_tree = op.kw_tree
            new.out_vars, new.out_ids = op.out_vars, op.out_ids
            return new
        return op


class StaticParam(Variable):
    """Scope-backed trainable parameter in static mode
    (reference framework.py Parameter under static graph)."""

    __slots__ = ("optimize_attr", "regularizer", "need_clip", "is_parameter")

    def __init__(self, shape, dtype, name, program, trainable=True,
                 regularizer=None, learning_rate=1.0, need_clip=True):
        super().__init__(shape, dtype, name=name, scope_name=name,
                         program=program)
        self.persistable = True
        self.trainable = trainable
        self.stop_gradient = not trainable
        self.optimize_attr = {"learning_rate": learning_rate}
        self.regularizer = regularizer
        self.need_clip = need_clip
        self.is_parameter = True


# -- default program stack ---------------------------------------------------

class _StaticState(threading.local):
    def __init__(self):
        self.enabled = False
        self.main = Program("main")
        self.startup = Program("startup")
        self.forced = None  # sub-block tracing override (control_flow.py)
        self.cf_parents = []  # enclosing sub-block traces (control_flow.py)


_state = _StaticState()


def forced_program():
    """The program a control-flow sub-block trace pins (overrides the
    per-arg program inference in tape._record_static — an op mixing outer
    Variables with sub-block placeholders must record into the
    sub-block)."""
    return _state.forced


class force_program:
    def __init__(self, program):
        self.program = program

    def __enter__(self):
        self._old = _state.forced
        _state.forced = self.program
        return self

    def __exit__(self, *exc):
        _state.forced = self._old
        return False


def in_static_mode() -> bool:
    return _state.enabled


def enable_static_():
    _state.enabled = True


def disable_static_():
    _state.enabled = False


def default_main_program() -> Program:
    return _state.main


def default_startup_program() -> Program:
    return _state.startup


def switch_main_program(program):
    old = _state.main
    _state.main = program
    return old


class program_guard:
    """with program_guard(main, startup): ... (reference framework.py)."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program or Program("startup")

    def __enter__(self):
        self._old_main = _state.main
        self._old_startup = _state.startup
        _state.main = self.main
        _state.startup = self.startup
        return self

    def __exit__(self, *exc):
        _state.main = self._old_main
        _state.startup = self._old_startup
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
