"""Abstract shape/dtype propagation over static Programs.

The compile-time InferShape analog (reference framework/op_desc.cc
InferShape + operators/*_op.cc InferShape methods, run while building the
ProgramDesc): walk the op list propagating `jax.ShapeDtypeStruct` avals,
using per-op rules registered alongside `@defop`
(paddle_tpu/ops/_dispatch.py SHAPE_INFER_REGISTRY) and falling back to
`jax.eval_shape` on the op's kernel. Mismatches (a rewritten matmul whose
contraction dims no longer agree, a dtype-promotion surprise, an AMP
fp16/fp32 boundary violation) surface at build/verify time as
`ShapeInferError` naming the op and variable — not as an XLA trace error
at Executor.run time.

The propagated avals also feed `analyze_memory(program)`: a liveness-
based peak-memory estimator (reference memory_optimize_pass liveness
analysis, ir/memory_optimize_pass/memory_optimization_var_info.h) used by
the Executor (FLAGS_log_memory_estimate) and tools/pp_schedule_report.py.
"""
from __future__ import annotations

import numpy as np
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops._dispatch import SHAPE_INFER_REGISTRY
from .program import Program, _Ref

__all__ = ["ShapeInferError", "register_infer_rule", "infer_program",
           "analyze_memory", "SHAPE_INFER_REGISTRY"]


class ShapeInferError(RuntimeError):
    """Shape/dtype propagation found an inconsistency.

    `op_name`/`op_index` name the offending op, `var` the output variable
    (when the failure is a recorded-vs-inferred mismatch).
    """

    def __init__(self, message, *, op_name=None, op_index=None, var=None):
        self.op_name = op_name
        self.op_index = op_index
        self.var = var
        where = ""
        if op_name is not None:
            where = f" [op #{op_index} '{op_name}']" \
                if op_index is not None else f" [op '{op_name}']"
        super().__init__(f"shape-infer{where}: {message}")


def register_infer_rule(*names):
    """Register an abstract rule for the named ops (the decorator form of
    `@defop(infer=...)` for rules shared across an op family)."""
    def deco(fn):
        for n in names:
            SHAPE_INFER_REGISTRY[n] = fn
        return fn
    return deco


def _aval_of(x):
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    return x


def _is_aval(x):
    return isinstance(x, jax.ShapeDtypeStruct)


def _nbytes(aval) -> int:
    return int(np.prod(aval.shape, dtype=np.int64)) \
        * np.dtype(aval.dtype).itemsize if aval.shape \
        else np.dtype(aval.dtype).itemsize


# ---------------------------------------------------------------------------
# propagation
# ---------------------------------------------------------------------------

def _seed_env(program: Program) -> Dict[int, jax.ShapeDtypeStruct]:
    env = {}
    for v in program.data_vars.values():
        env[v.var_id] = v.aval
    for scope_name, vid in program.persist_ids.items():
        pv = program.persistable_vars.get(scope_name)
        if pv is not None:
            env[vid] = pv.aval
    return env


def _fallback_eval_shape(op, in_vals, kw_tree, n_args):
    """Record-time inference replayed: jax.eval_shape over the kernel with
    the PRNG chain sandboxed (tape._record_static does the same)."""
    import jax.tree_util as jtu
    from ..core import rng as _rng

    dyn_idx = [i for i, v in enumerate(in_vals) if _is_aval(v)]

    def call(*dyn):
        vals = list(in_vals)
        for i, v in zip(dyn_idx, dyn):
            vals[i] = v
        kw = jtu.tree_unflatten(kw_tree, vals[n_args:])
        return op.fn(*vals[:n_args], **kw)

    with _rng.rng_state(jax.random.PRNGKey(0)):
        return jax.eval_shape(call, *[in_vals[i] for i in dyn_idx])


def _apply_rule(rule, op, in_vals, kw_tree, n_args):
    import jax.tree_util as jtu
    kw = jtu.tree_unflatten(kw_tree, in_vals[n_args:])
    return rule(*in_vals[:n_args], **kw)


def _amp_cast(program, op_name, in_vals):
    """Mirror the Executor's program-level AMP cast (executor.py
    cast_vals) on avals, and report gray-zone mixed-precision inputs —
    the fp16/fp32 boundary mismatches AMP O1 silently promotes."""
    from .. import amp as amp_mod
    level = program.amp_level
    dtype = getattr(program, "amp_dtype", jnp.bfloat16)
    white, black = getattr(program, "amp_lists", (None, None))
    dt = amp_mod.policy_dtype(op_name, level, dtype, white, black)
    float_dtypes = {np.dtype(v.dtype) for v in in_vals if _is_aval(v)
                    and jnp.issubdtype(v.dtype, jnp.floating)}
    mixed = len(float_dtypes) > 1
    if dt is None:
        return in_vals, mixed, float_dtypes
    out = [jax.ShapeDtypeStruct(v.shape, dt)
           if _is_aval(v) and jnp.issubdtype(v.dtype, jnp.floating)
           and np.dtype(v.dtype) != np.dtype(dt) else v
           for v in in_vals]
    return out, False, float_dtypes


def infer_program(program: Program, check: bool = True,
                  amp_check: bool = True) -> Dict[int, jax.ShapeDtypeStruct]:
    """Propagate avals through the program; returns {var_id: aval}.

    check=True compares each op's inferred output avals against the
    recorded ones (shape always; dtype unless program-level AMP rewrites
    dtypes at lowering time) and raises `ShapeInferError` on mismatch.
    amp_check=True additionally flags fp16/fp32 boundary violations for
    AMP-tagged programs: a gray-list op receiving mixed float dtypes
    would silently promote — exactly the surprise AMP O1 is supposed to
    make deliberate.
    """
    env = _seed_env(program)
    amp_on = bool(getattr(program, "amp_level", None))
    violations: List[str] = []
    for i, op in enumerate(program.ops):
        in_vals = []
        for x in op.flat:
            if isinstance(x, _Ref):
                if x.var_id not in env:
                    raise ShapeInferError(
                        f"input '{x.name}' (id {x.var_id}) has no known "
                        "aval — the program is structurally broken (run "
                        "verify_program for the structural diagnosis)",
                        op_name=op.name, op_index=i, var=x.name)
                in_vals.append(env[x.var_id])
            else:
                in_vals.append(_aval_of(x))
        if amp_on:
            in_vals, mixed, float_dtypes = _amp_cast(program, op.name,
                                                     in_vals)
            if mixed and amp_check:
                violations.append(
                    f"op #{i} '{op.name}' mixes float dtypes "
                    f"{sorted(str(d) for d in float_dtypes)} in the AMP "
                    "gray zone — the promotion is silent; add the op to a "
                    "white/black list or cast explicitly")
        rule = SHAPE_INFER_REGISTRY.get(op.name)
        try:
            if rule is not None:
                out = _apply_rule(rule, op, in_vals, op.kw_tree, op.n_args)
            else:
                out = _fallback_eval_shape(op, in_vals, op.kw_tree,
                                           op.n_args)
        except ShapeInferError:
            raise
        except Exception as e:
            raise ShapeInferError(str(e), op_name=op.name,
                                  op_index=i) from e
        avals = list(out) if isinstance(out, (tuple, list)) else [out]
        avals = [_aval_of(a) for a in avals]
        if len(avals) != len(op.out_ids):
            raise ShapeInferError(
                f"kernel yields {len(avals)} outputs but the op records "
                f"{len(op.out_ids)}", op_name=op.name, op_index=i)
        for aval, oid, ovar in zip(avals, op.out_ids, op.out_vars):
            if check:
                rec = ovar.aval
                if tuple(aval.shape) != tuple(rec.shape):
                    raise ShapeInferError(
                        f"output '{ovar.name}' records shape "
                        f"{tuple(rec.shape)} but propagation infers "
                        f"{tuple(aval.shape)}", op_name=op.name,
                        op_index=i, var=ovar.name)
                if not amp_on and np.dtype(aval.dtype) != np.dtype(rec.dtype):
                    raise ShapeInferError(
                        f"output '{ovar.name}' records dtype {rec.dtype} "
                        f"but propagation infers {aval.dtype}",
                        op_name=op.name, op_index=i, var=ovar.name)
            env[oid] = aval
    if violations:
        raise ShapeInferError("AMP boundary check failed:\n  "
                              + "\n  ".join(violations))
    return env


# ---------------------------------------------------------------------------
# liveness-based peak-memory estimate
# ---------------------------------------------------------------------------

def analyze_memory(program: Program,
                   env: Optional[dict] = None,
                   shard_divisors: Optional[Dict[int, int]] = None,
                   op_range: Optional[Tuple[int, int]] = None) -> dict:
    """Estimate the lowered step's peak residency from inferred avals.

    Liveness at the Program level (the reference's
    memory_optimize_pass var lifetime analysis): an intermediate is live
    from the op that defines it until its last reader — or to the end of
    the program when it is fetched, state-written, or feeds the backward
    section. Persistables (params) and feeds are resident throughout.

    shard_divisors ({var_id: divisor}) turns the estimate PER-DEVICE
    under SPMD partitioning: each var's bytes are divided by the product
    of its sharded dims' mesh-axis sizes (supplied by
    static/spmd_analyzer.py from the propagated PartitionSpecs).

    op_range=(lo, hi) restricts the estimate to the op slice [lo, hi) —
    the per-STAGE residency a pipeline-stage cut would give that slice
    (static/spmd_planner.plan_pipeline prices candidate cuts with it):
    only persistables/feeds the slice actually reads count as resident,
    a var defined before `lo` but read inside is a stage input (resident
    throughout the slice), and a var defined inside but read after `hi`
    is the stage's outbound frontier (pinned to the end of the slice).

    Returns {"peak_bytes", "param_bytes", "feed_bytes",
    "activation_peak_bytes", "timeline": [(op_name, live_bytes)],
    "peak_op"}; a pure estimate — XLA's buffer assignment (fusion,
    rematerialization, donation) can only shrink it.
    """
    if env is None:
        env = infer_program(program, check=False, amp_check=False)
    divs = shard_divisors or {}

    def _nb(vid, aval):
        return _nbytes(aval) // max(int(divs.get(vid, 1)), 1)

    n = len(program.ops)
    lo, hi = (0, n) if op_range is None else op_range
    lo, hi = max(0, int(lo)), min(n, int(hi))

    last_use: Dict[int, int] = {}
    defined_at: Dict[int, int] = {}
    for i, op in enumerate(program.ops):
        for x in op.flat:
            if isinstance(x, _Ref):
                last_use[x.var_id] = i
        for oid in op.out_ids:
            defined_at[oid] = i

    used_in_range = None
    if op_range is not None:
        used_in_range = set()
        for op in program.ops[lo:hi]:
            for x in op.flat:
                if isinstance(x, _Ref):
                    used_in_range.add(x.var_id)

    param_bytes = 0
    param_ids = set()
    for scope_name, vid in program.persist_ids.items():
        pv = program.persistable_vars.get(scope_name)
        if pv is not None and (used_in_range is None
                               or vid in used_in_range):
            param_bytes += _nb(vid, pv.aval)
            param_ids.add(vid)
    feed_bytes = 0
    feed_ids = set()
    for v in program.data_vars.values():
        if used_in_range is None or v.var_id in used_in_range:
            feed_bytes += _nb(v.var_id, v.aval)
            feed_ids.add(v.var_id)
    if used_in_range is not None:
        # inbound frontier: defined before the slice, read inside —
        # resident for the whole stage like a feed
        for vid in used_in_range:
            if vid in param_ids or vid in feed_ids:
                continue
            if defined_at.get(vid, lo) < lo and vid in env:
                feed_bytes += _nb(vid, env[vid])
                feed_ids.add(vid)

    roots = set(program.state_writes.values())
    if program.backward_section is not None:
        loss, pairs = program.backward_section
        roots.add(loss.var_id)
    for v in getattr(program, "_jit_fetch_vars", []) or []:
        roots.add(v.var_id)
    for vid in roots:
        last_use[vid] = n  # pinned to the end of the step
    if op_range is not None:
        roots = {vid for vid in roots if lo <= defined_at.get(vid, -1) < hi}

    timeline = []
    peak = param_bytes + feed_bytes
    peak_op = None
    live_bytes = 0
    live_now: Dict[int, int] = {}
    for i in range(lo, hi):
        op = program.ops[i]
        for oid in op.out_ids:
            if oid in env and last_use.get(oid, -1) >= i:
                b = _nb(oid, env[oid])
                live_now[oid] = b
                live_bytes += b
        total = param_bytes + feed_bytes + live_bytes
        timeline.append((op.name, total))
        if total > peak:
            peak, peak_op = total, (i, op.name)
        # free vars whose last reader this op was (outputs freed above
        # only after their own last use passes); under op_range, a var
        # still read past `hi` is the outbound frontier and stays live
        # to the end of the slice
        for vid in [v for v, last in list(live_now.items())
                    if last_use.get(v, -1) <= i and v not in roots]:
            live_bytes -= live_now.pop(vid)
    return {"peak_bytes": int(peak),
            "param_bytes": int(param_bytes),
            "feed_bytes": int(feed_bytes),
            "activation_peak_bytes": int(peak - param_bytes - feed_bytes),
            "timeline": timeline,
            "peak_op": peak_op}


# ---------------------------------------------------------------------------
# the built-in rule library (>= 25 ops). Rules are deliberately closed
# forms — no tracing — so a rewritten program can be re-checked in
# microseconds, and their error strings name the contract that broke.
# ---------------------------------------------------------------------------

def _result_dtype(*vals):
    """jnp-style promotion over avals + python literals."""
    parts = [v.dtype if _is_aval(v) else v for v in vals]
    return jnp.result_type(*parts)


def _default_float():
    # respects the live jax_enable_x64 config (paddle_tpu turns it on)
    return jnp.result_type(float)


def _default_int():
    return jnp.result_type(int)


def _float_dtype(v):
    """Unary float-math output dtype: floats pass through, ints promote
    to the configured default float."""
    dt = v.dtype if _is_aval(v) else jnp.result_type(v)
    if jnp.issubdtype(dt, jnp.inexact):
        return dt
    return _default_float()


@register_infer_rule("add", "subtract", "multiply", "maximum", "minimum")
def _ew_binary(x, y, **kw):
    xs = x.shape if _is_aval(x) else ()
    ys = y.shape if _is_aval(y) else ()
    try:
        shape = np.broadcast_shapes(tuple(xs), tuple(ys))
    except ValueError:
        raise ValueError(
            f"elementwise operands do not broadcast: {tuple(xs)} vs "
            f"{tuple(ys)}") from None
    return jax.ShapeDtypeStruct(shape, _result_dtype(x, y))


@register_infer_rule("relu", "relu6", "leaky_relu", "silu", "gelu",
                     "hardswish", "softplus")
def _ew_unary_float(x, **kw):
    shape = x.shape if _is_aval(x) else ()
    return jax.ShapeDtypeStruct(tuple(shape), _float_dtype(x))


@register_infer_rule("exp", "log", "sqrt", "sigmoid", "tanh")
def _ew_unary_math(x, **kw):
    shape = x.shape if _is_aval(x) else ()
    return jax.ShapeDtypeStruct(tuple(shape), _float_dtype(x))


@register_infer_rule("softmax", "log_softmax")
def _softmax_rule(x, axis=-1, **kw):
    nd = len(x.shape)
    if not -nd <= axis < nd:
        raise ValueError(f"softmax axis {axis} out of range for rank {nd}")
    return jax.ShapeDtypeStruct(tuple(x.shape), _float_dtype(x))


def _norm_axes(axis, nd):
    if axis is None:
        return tuple(range(nd))
    axes = axis if isinstance(axis, (tuple, list)) else [axis]
    out = []
    for a in axes:
        a = int(a)
        if not -nd <= a < nd:
            raise ValueError(f"reduce axis {a} out of range for rank {nd}")
        out.append(a % nd if nd else 0)
    return tuple(out)


def _reduce_shape(x, axis, keepdim):
    nd = len(x.shape)
    axes = set(_norm_axes(axis, nd))
    if keepdim:
        return tuple(1 if i in axes else s for i, s in enumerate(x.shape))
    return tuple(s for i, s in enumerate(x.shape) if i not in axes)


@register_infer_rule("sum")
def _sum_rule(x, axis=None, dtype=None, keepdim=False, **kw):
    dt = jnp.dtype(dtype) if dtype is not None else (
        _default_int() if jnp.issubdtype(x.dtype, jnp.bool_) else x.dtype)
    return jax.ShapeDtypeStruct(_reduce_shape(x, axis, keepdim), dt)


@register_infer_rule("prod")
def _prod_rule(x, axis=None, keepdim=False, **kw):
    dt = _default_int() if jnp.issubdtype(x.dtype, jnp.bool_) else x.dtype
    return jax.ShapeDtypeStruct(_reduce_shape(x, axis, keepdim), dt)


@register_infer_rule("mean")
def _mean_rule(x, axis=None, keepdim=False, **kw):
    return jax.ShapeDtypeStruct(_reduce_shape(x, axis, keepdim),
                                _float_dtype(x))


@register_infer_rule("max", "min")
def _minmax_rule(x, axis=None, keepdim=False, **kw):
    return jax.ShapeDtypeStruct(_reduce_shape(x, axis, keepdim), x.dtype)


@register_infer_rule("all", "any")
def _bool_reduce_rule(x, axis=None, keepdim=False, **kw):
    return jax.ShapeDtypeStruct(_reduce_shape(x, axis, keepdim),
                                jnp.dtype(jnp.bool_))


@register_infer_rule("reshape")
def _reshape_rule(x, shape, **kw):
    size = int(np.prod(x.shape, dtype=np.int64))
    shape = [int(s) for s in shape]
    if shape.count(-1) > 1:
        raise ValueError(f"reshape shape {shape} has more than one -1")
    if -1 in shape:
        rest = int(np.prod([s for s in shape if s != -1], dtype=np.int64))
        if rest == 0 or size % rest:
            raise ValueError(
                f"cannot infer -1 in reshape {tuple(x.shape)} -> {shape}")
        shape[shape.index(-1)] = size // rest
    if int(np.prod(shape, dtype=np.int64)) != size:
        raise ValueError(
            f"reshape size mismatch: {tuple(x.shape)} ({size} elements) "
            f"-> {tuple(shape)}")
    return jax.ShapeDtypeStruct(tuple(shape), x.dtype)


@register_infer_rule("transpose")
def _transpose_rule(x, perm=None, **kw):
    nd = len(x.shape)
    if perm is None:
        perm = list(range(nd))[::-1]
    if sorted(int(p) % nd if nd else 0 for p in perm) != list(range(nd)):
        raise ValueError(
            f"transpose perm {list(perm)} is not a permutation of rank "
            f"{nd}")
    return jax.ShapeDtypeStruct(tuple(x.shape[int(p)] for p in perm),
                                x.dtype)


@register_infer_rule("concat")
def _concat_rule(*xs, axis=0, **kw):
    # recorded as concat(*xs, axis=...) through _concat's star args
    avals = [v for v in xs if _is_aval(v)]
    if not avals:
        raise ValueError("concat needs at least one tensor input")
    nd = len(avals[0].shape)
    ax = int(axis) % nd if nd else 0
    base = list(avals[0].shape)
    total = 0
    for v in avals:
        if len(v.shape) != nd:
            raise ValueError(
                f"concat rank mismatch: {tuple(avals[0].shape)} vs "
                f"{tuple(v.shape)}")
        for i, (a, b) in enumerate(zip(base, v.shape)):
            if i != ax and a != b:
                raise ValueError(
                    f"concat dim {i} mismatch: {tuple(avals[0].shape)} vs "
                    f"{tuple(v.shape)} (axis={ax})")
        total += v.shape[ax]
    base[ax] = total
    return jax.ShapeDtypeStruct(tuple(base), _result_dtype(*avals))


@register_infer_rule("cast")
def _cast_rule(x, dtype, **kw):
    from ..core.dtype import to_jax_dtype
    return jax.ShapeDtypeStruct(tuple(x.shape), to_jax_dtype(dtype))


@register_infer_rule("one_hot")
def _one_hot_rule(x, num_classes, **kw):
    return jax.ShapeDtypeStruct(tuple(x.shape) + (int(num_classes),),
                                _default_float())


@register_infer_rule("embedding")
def _embedding_rule(weight, ids, padding_idx=None, sparse=False, **kw):
    if len(weight.shape) != 2:
        raise ValueError(
            f"embedding weight must be [vocab, dim], got "
            f"{tuple(weight.shape)}")
    return jax.ShapeDtypeStruct(tuple(ids.shape) + (weight.shape[1],),
                                weight.dtype)


@register_infer_rule("conv2d")
def _conv2d_rule(x, weight, bias=None, stride=1, padding=0, dilation=1,
                 groups=1, data_format="NCHW", **kw):
    if len(x.shape) != 4 or len(weight.shape) != 4:
        raise ValueError(
            f"conv2d wants 4-D input and weight, got {tuple(x.shape)} and "
            f"{tuple(weight.shape)}")
    if np.dtype(x.dtype) != np.dtype(weight.dtype):
        raise ValueError(
            f"conv2d input dtype {x.dtype} != weight dtype {weight.dtype}")
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    dil = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    if data_format == "NCHW":
        n, cin, h, w = x.shape
    else:
        n, h, w, cin = x.shape
    cout, cin_w, kh, kw_ = weight.shape
    if cin_w * int(groups) != cin:
        raise ValueError(
            f"conv2d channel mismatch: input has {cin} channels but "
            f"weight expects {cin_w} x groups={groups}")
    if isinstance(padding, str):
        if padding.upper() == "SAME":
            oh = -(-h // st[0])
            ow = -(-w // st[1])
        else:  # VALID
            oh = (h - dil[0] * (kh - 1) - 1) // st[0] + 1
            ow = (w - dil[1] * (kw_ - 1) - 1) // st[1] + 1
    else:
        ph, pw = (padding, padding) if isinstance(padding, int) \
            else tuple(padding)[:2]
        oh = (h + 2 * ph - dil[0] * (kh - 1) - 1) // st[0] + 1
        ow = (w + 2 * pw - dil[1] * (kw_ - 1) - 1) // st[1] + 1
    if oh <= 0 or ow <= 0:
        raise ValueError(
            f"conv2d output collapses to {oh}x{ow} for input {h}x{w}, "
            f"kernel {kh}x{kw_}, stride {st}, padding {padding}")
    shape = (n, cout, oh, ow) if data_format == "NCHW" \
        else (n, oh, ow, cout)
    return jax.ShapeDtypeStruct(shape, x.dtype)
