"""Auto-sharding planner — search PartitionSpec plans against the SPMD
analyzer's cost model.

PR 3's analyzer (`spmd_analyzer.analyze_program`) can price any candidate
layout on any `{axis: size}` mesh without devices: the implied collective
set with per-device payload bytes, a per-device peak-HBM estimate, and a
hard diagnostic catalogue. This module INVERTS it — instead of asking
users to hand-write `COLUMN_PARALLEL`/`ROW_PARALLEL` regexes ("Scale
MLPerf-0.6 models on Google TPU-v3 Pods" describes exactly the layout
search engineers do by hand today), it derives the plan:

  * **Candidate generation** comes from the analyzer's per-op rules: a
    matmul contraction dim admits row-parallel, a matmul output dim
    admits column-parallel, an embedding/vocab-head weight admits
    vocab-parallel on dim 0, elementwise partners (biases) admit the
    matching 1-D sharding, data feeds admit batch-`dp` (and seq-`sp`)
    sharding, and — opt-in — every remaining param admits ZeRO-style
    `dp` on dim 0. Candidates that cannot divide their dim are never
    generated.
  * **Template grouping**: parameters sharing a name template (digit
    runs collapsed to `\\d+`, e.g. `blocks\\.\\d+\\.fc2\\.weight`) are
    planned as ONE group, so the search space is per-template, not
    per-tensor, and the emitted plan is a compact, human-auditable rule
    list (SNIPPETS `match_partition_rules` idiom, produced instead of
    consumed).
  * **Search**: grouped beam search in dataflow order with analyzer
    re-pricing per candidate. States are ranked by
    `(diagnostic_count, objective)` — intermediate states MAY carry
    diagnostics (column-parallel qkv is illegal until the row-parallel
    out-proj closes the Megatron chain two groups later), but only
    zero-diagnostic final states can win; all-replicated is the always-
    legal fallback. A bounded coordinate-descent sweep then polishes the
    winner. Objective = `coll_weight * collective_bytes/step +
    hbm_weight * peak_per_device_HBM` (flag-tunable).
  * **Emission**, three ways: `plan.param_specs` for
    `Program.spmd_param_specs` / `analyze_program`; `plan.rules` as
    `(template, ndim, PartitionSpec)` records installable via
    `sharding.add_tp_rule` (`plan.install_rules()`); and
    `plan.as_strategy()` — a `fleet.DistributedStrategy` with
    `auto_shard = True` that `fleet.distributed_optimizer` tags onto the
    Program so the Executor resolves the plan at compile
    (`resolve_auto_shard`).

CLI: `python tools/spmd_plan.py --tp 4 [--dp 2 --sp 2] [--json]` plans
the GPT workload and prints the plan next to the hand-written preset and
the replicated baseline. `docs/spmd_planner.md` has the full story.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
from jax.sharding import PartitionSpec as P

from .program import Program, _Ref
from .spmd_analyzer import (SpmdReport, _entries as _spec_entries,
                            _mesh_axes, _nbytes, analyze_program)

__all__ = ["ShardingPlan", "PlanRule", "plan_program", "resolve_auto_shard",
           "name_template"]


# how many diagnostic-count strata the beam carries (lowest first): a
# chain opener sits one stratum per still-open block above the legal
# states, so this bounds how deep an opener→closer chain may nest
_DIAG_STRATA = 4


def name_template(name: str) -> str:
    """Anchored regex template for a parameter name: all-digit dotted
    components (LayerList indices) collapse to `\\d+`, so
    `blocks.3.fc2.weight` and `blocks.11.fc2.weight` share one rule
    (`^blocks\\.\\d+\\.fc2\\.weight$`). Digits embedded in an identifier
    (`fc1` vs `fc2` — different modules) stay literal."""
    body = r"\.".join(r"\d+" if comp.isdigit() else re.escape(comp)
                      for comp in name.split("."))
    return "^" + body + "$"


def _to_p(entries) -> P:
    return P(*[None if not e else (e[0] if len(e) == 1 else tuple(e))
               for e in entries])


def _spec_key(entries) -> tuple:
    return tuple(tuple(e) for e in entries)


@dataclass
class PlanRule:
    """One emitted rule: params matching `template` (of rank `ndim`)
    take `spec`. The human-auditable unit of the plan."""
    template: str
    ndim: int
    spec: P

    def matches(self, name: str, ndim: int) -> bool:
        return ndim == self.ndim and re.search(self.template, name) \
            is not None


@dataclass(eq=False)  # identity hash: groups key search assignments
class PlanGroup:
    """One search unit: all params (or one data feed) sharing a name
    template, rank and shape; `candidates` are the normalized spec
    tuples the role scan admits (index 0 is always replicated)."""
    template: str
    kind: str                    # "param" | "data"
    members: List[str]           # scope names (program keys)
    display: List[str]           # display (dotted) names for the rules
    ndim: int = 0
    shape: tuple = ()
    nbytes: int = 0
    first_use: int = 1 << 30
    roles: set = field(default_factory=set)
    candidates: List[tuple] = field(default_factory=list)


@dataclass
class ShardingPlan:
    """A searched layout plus its predicted costs, consumable three ways
    (specs dict, rule list, fleet strategy) — see module docstring."""
    mesh_axes: Dict[str, int]
    param_specs: Dict[str, P]          # scope_name -> spec
    data_specs: Dict[str, P]           # data var name -> spec
    rules: List[PlanRule]
    names: Dict[str, str]              # scope_name -> display name
    report: Optional[SpmdReport] = None
    objective: float = 0.0
    predicted: Dict[str, Any] = field(default_factory=dict)
    baseline: Dict[str, Any] = field(default_factory=dict)  # replicated
    evaluations: int = 0

    # -- consumption ---------------------------------------------------------
    def spec_for(self, name: str, ndim: int) -> P:
        """Spec for a (display) param name by the emitted rule list —
        the planner-made analog of `sharding.param_spec_for`. Most
        specific rule wins (fewest `\\d+` wildcards first), so an
        exact-name rule beats a template it also matches."""
        for rule in sorted(self.rules,
                           key=lambda r: r.template.count(r"\d+")):
            if rule.matches(name, ndim):
                return rule.spec
        return P()

    def apply(self, program: Program) -> "ShardingPlan":
        """Pin the plan on a Program for `analyze_program` / the
        PADDLE_TPU_VERIFY_SPMD hook / `FLAGS_log_spmd_estimate`."""
        program.spmd_param_specs = dict(self.param_specs)
        program.spmd_data_specs = dict(self.data_specs)
        return self

    def install_rules(self):
        """Register every rule via `sharding.add_tp_rule` (callable
        builders, so a template only fires for its rank); returns the
        installed patterns for later `sharding.remove_tp_rule`."""
        from ..distributed import sharding as sharding_mod
        patterns = []
        for rule in self.rules:
            def build(ndim, _r=rule):
                return _r.spec if ndim == _r.ndim else P()
            sharding_mod.add_tp_rule(rule.template, build)
            patterns.append(rule.template)
        return patterns

    def as_strategy(self, strategy=None):
        """A `fleet.DistributedStrategy` carrying this plan:
        `fleet.distributed_optimizer(opt, plan.as_strategy())` makes
        `minimize` tag the Program and the Executor resolve the plan at
        compile (`auto_shard = True`)."""
        if strategy is None:
            from ..distributed.fleet import DistributedStrategy
            strategy = DistributedStrategy()
        strategy.auto_shard = True
        strategy.auto_shard_configs = {"plan": self}
        return strategy

    def build_param_shardings(self, params: Dict[str, Any], mesh):
        """`{name: NamedSharding}` for a (dotted-name) param tree — the
        jit `in_shardings` form the MULTICHIP dryrun consumes."""
        from jax.sharding import NamedSharding
        return {name: NamedSharding(mesh, self.spec_for(
            name, len(getattr(v, "shape", ())))) for name, v in
            params.items()}

    # -- reporting -----------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        """Stable (sorted, primitive-typed) form for CI consumption."""
        return {
            "mesh": dict(sorted(self.mesh_axes.items())),
            "rules": [{"template": r.template, "ndim": r.ndim,
                       "spec": [None if e is None else list(e)
                                if isinstance(e, tuple) else e
                                for e in tuple(r.spec)]}
                      for r in sorted(self.rules,
                                      key=lambda r: (r.template, r.ndim))],
            "data_specs": {k: [None if e is None else e
                               for e in tuple(v)]
                           for k, v in sorted(self.data_specs.items())},
            "predicted": dict(sorted(self.predicted.items())),
            "baseline_replicated": dict(sorted(self.baseline.items())),
            "objective": self.objective,
            "evaluations": self.evaluations,
        }

    def render(self) -> str:
        lines = ["spmd plan: mesh {" + ", ".join(
            f"{a}:{s}" for a, s in self.mesh_axes.items()) + "}"]
        lines.append("rules:")
        for r in sorted(self.rules, key=lambda r: (r.template, r.ndim)):
            lines.append(f"  {r.template:<44} -> {r.spec}")
        if not self.rules:
            lines.append("  (everything replicated)")
        for name, spec in sorted(self.data_specs.items()):
            lines.append(f"  data {name:<39} -> {spec}")
        p, b = self.predicted, self.baseline
        lines.append(
            f"predicted: collective {p.get('collective_bytes', 0)} B/step, "
            f"peak HBM/device {p.get('hbm_peak', 0)} B "
            f"(replicated baseline: {b.get('collective_bytes', 0)} B, "
            f"{b.get('hbm_peak', 0)} B)")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# role scan — which shardings does each persistable/feed admit?
# ---------------------------------------------------------------------------

def _kw_of(op) -> dict:
    import jax.tree_util as jtu
    try:
        kw = jtu.tree_unflatten(op.kw_tree, op.flat[op.n_args:])
    except Exception:
        return {}
    return kw if isinstance(kw, dict) else {}


_EW_OPS = ("add", "subtract", "multiply", "divide", "maximum", "minimum",
           "where")


def _scan_roles(program: Program):
    """Walk the op list (and control-flow sub-blocks): for every
    persistable, record how it is consumed — the role set drives
    candidate generation. Also records each var's first-use op index so
    the search runs in dataflow order (Megatron chains close as soon as
    possible after they open)."""
    from .control_flow import _CondFn, _WhileFn

    id2scope = {vid: scope for scope, vid in program.persist_ids.items()}
    roles: Dict[str, set] = {s: set() for s in program.persist_ids}
    first: Dict[str, int] = {}

    def note(ref, idx):
        scope = id2scope.get(ref.var_id) if isinstance(ref, _Ref) else None
        if scope is not None:
            first.setdefault(scope, idx)
        return scope

    def walk(ops, base):
        for i, op in enumerate(ops):
            idx = base + i
            if isinstance(op.fn, (_CondFn, _WhileFn)):
                blocks = [op.fn.true_block, op.fn.false_block] \
                    if isinstance(op.fn, _CondFn) else [op.fn.body_block]
                for blk in blocks:
                    walk(blk.ops, idx)
                for x in op.flat:
                    note(x, idx)
                continue
            args = op.flat[:op.n_args]
            kw = _kw_of(op)
            for x in op.flat:
                note(x, idx)
            if op.name == "matmul" and len(args) >= 2:
                ty = bool(kw.get("transpose_y", False))
                tx = bool(kw.get("transpose_x", False))
                lhs, rhs = note(args[0], idx), note(args[1], idx)
                if rhs is not None:
                    roles[rhs].add(("matmul_rhs", ty))
                if lhs is not None:
                    roles[lhs].add(("matmul_lhs", tx))
            elif op.name == "embedding" and args:
                w = note(args[0], idx)
                if w is not None:
                    roles[w].add(("vocab", None))
            elif op.name in ("fused_ce_op", "ce_head_fallback") \
                    and len(args) >= 2:
                w = note(args[1], idx)
                if w is not None:
                    roles[w].add(("vocab", None))
            elif op.name in _EW_OPS:
                for x in args:
                    s = note(x, idx)
                    if s is not None:
                        roles[s].add(("elementwise", None))

    walk(program.ops, 0)
    return roles, first


def _param_candidates(g: PlanGroup, axes: Dict[str, int],
                      zero_dp: bool) -> List[tuple]:
    nd, shape = g.ndim, g.shape
    cands: List[tuple] = [((),) * nd]

    def add(dim, ax):
        if 0 <= dim < nd and shape[dim] % axes[ax] == 0:
            spec = [()] * nd
            spec[dim] = (ax,)
            if tuple(spec) not in cands:
                cands.append(tuple(spec))

    for role, flag in g.roles:
        if role == "matmul_rhs" and nd >= 2:
            cdim = nd - 1 if flag else nd - 2   # contraction: row-parallel
            odim = nd - 2 if flag else nd - 1   # output: column-parallel
            for ax in axes:
                add(cdim, ax)
                add(odim, ax)
        elif role == "vocab":
            for ax in axes:
                add(0, ax)
        elif role == "elementwise" and nd == 1:
            # a bias/scale riding an elementwise op can mirror its
            # partner's output sharding
            for ax in axes:
                add(0, ax)
    if zero_dp and "dp" in axes:
        add(0, "dp")
    return cands


def _data_candidates(g: PlanGroup, axes: Dict[str, int]) -> List[tuple]:
    """Feeds admit batch-dp (dim 0) and sequence-sp (dim 1) sharding —
    the repo's mesh-axis conventions (fleet hybrid degrees)."""
    nd, shape = g.ndim, g.shape
    cands: List[tuple] = [((),) * nd]
    combos = []
    dp_ok = "dp" in axes and nd >= 1 and shape[0] % axes["dp"] == 0
    sp_ok = "sp" in axes and nd >= 2 and shape[1] % axes["sp"] == 0
    if dp_ok:
        combos.append({0: ("dp",)})
    if sp_ok:
        combos.append({1: ("sp",)})
    if dp_ok and sp_ok:
        combos.append({0: ("dp",), 1: ("sp",)})
    for combo in combos:
        spec = [combo.get(d, ()) for d in range(nd)]
        if tuple(spec) not in cands:
            cands.append(tuple(spec))
    return cands


# ---------------------------------------------------------------------------
# the search
# ---------------------------------------------------------------------------

class _Oracle:
    """Memoized analyzer pricing of a full assignment."""

    def __init__(self, program, axes, coll_w, hbm_w):
        self.program = program
        self.axes = axes
        self.coll_w = coll_w
        self.hbm_w = hbm_w
        self.cache: Dict[tuple, tuple] = {}
        self.evaluations = 0

    def price(self, param_assign: Dict[str, tuple],
              data_assign: Dict[str, tuple]):
        """-> (n_diags, score, optimistic_score, report). The optimistic
        score drops the all-gather bytes: a zero-diagnostic plan implies
        none (every gather the analyzer emits rides a diagnostic), so it
        is the value an open Megatron chain would have once its closer
        removes the reshard — the ranking that keeps chain-opening
        states alive inside the infeasible beam strata."""
        key = (tuple(sorted((k, _spec_key(v))
                            for k, v in param_assign.items())),
               tuple(sorted((k, _spec_key(v))
                            for k, v in data_assign.items())))
        hit = self.cache.get(key)
        if hit is not None:
            return hit
        self.evaluations += 1
        report = analyze_program(
            self.program, mesh=self.axes,
            param_specs={k: _to_p(v) for k, v in param_assign.items()},
            data_specs={k: _to_p(v) for k, v in data_assign.items()})
        hbm = report.hbm["peak_bytes"] if report.hbm else \
            sum(_nbytes(pv.aval)
                for pv in self.program.persistable_vars.values())
        score = self.coll_w * report.collective_bytes() + self.hbm_w * hbm
        ar_bytes = sum(c.bytes for c in report.collectives
                       if c.kind == "all_reduce")
        opt = self.coll_w * ar_bytes + self.hbm_w * hbm
        out = (len(report.diagnostics), float(score), float(opt), report)
        self.cache[key] = out
        return out


def _build_groups(program: Program, axes, names, zero_dp,
                  fixed_data_specs) -> List[PlanGroup]:
    roles, first = _scan_roles(program)
    names = dict(names or {})
    by_tmpl: Dict[tuple, PlanGroup] = {}

    for scope, pv in program.persistable_vars.items():
        display = names.get(scope, scope)
        shape = tuple(pv.aval.shape)
        # same-template params with different shapes/roles cannot share
        # one rule — the shape in the key splits them apart (their
        # templates then collide; _emit falls back to exact names)
        key = (name_template(display), shape,
               frozenset(roles.get(scope, ())))
        g = by_tmpl.get(key)
        if g is None:
            g = by_tmpl[key] = PlanGroup(
                template=key[0], kind="param", members=[], display=[],
                ndim=len(shape), shape=shape, roles=set(roles.get(scope,
                                                                  ())))
        g.members.append(scope)
        g.display.append(display)
        g.nbytes += _nbytes(pv.aval)
        g.first_use = min(g.first_use, first.get(scope, 1 << 30))

    groups = list(by_tmpl.values())
    for g in groups:
        g.candidates = _param_candidates(g, axes, zero_dp)

    if fixed_data_specs is None:
        for name, v in program.data_vars.items():
            g = PlanGroup(template=name_template(name), kind="data",
                          members=[name], display=[name],
                          ndim=len(v.aval.shape),
                          shape=tuple(v.aval.shape),
                          nbytes=_nbytes(v.aval), first_use=-1)
            g.candidates = _data_candidates(g, axes)
            groups.append(g)

    # dataflow order: feeds first (they enter at op 0), then params by
    # first use — a Megatron chain's opener and closer sit adjacently,
    # so the infeasible intermediate survives at most a few beam steps
    groups.sort(key=lambda g: (g.first_use, -g.nbytes, g.template))
    return [g for g in groups if len(g.candidates) > 1 or g.kind == "param"]


def plan_program(program: Program, mesh=None, *, layer=None, names=None,
                 data_specs=None, coll_weight=None, hbm_weight=None,
                 beam=None, sweeps=None, zero_dp=False) -> ShardingPlan:
    """Search a PartitionSpec plan for `program` on `mesh`.

    mesh: a Mesh or `{axis: size}` dict (device-free), or None for the
    registered default. `layer`/`names` supply display (dotted) names
    for the rule templates (`names` = {scope_name: dotted_name}; a
    `layer` is walked via `named_parameters()`); without them the rules
    fall back to scope-name templates. `data_specs` pins the feed specs
    instead of searching them. `zero_dp=True` adds ZeRO-style dim-0 `dp`
    candidates for every param the oracle will accept. Weights/beam
    default from `FLAGS_spmd_plan_*`.
    """
    from ..core import monitor
    from ..core.flags import flag as _flag

    axes = _mesh_axes(mesh)
    coll_w = float(_flag("FLAGS_spmd_plan_coll_weight")
                   if coll_weight is None else coll_weight)
    hbm_w = float(_flag("FLAGS_spmd_plan_hbm_weight")
                  if hbm_weight is None else hbm_weight)
    beam_w = max(1, int(_flag("FLAGS_spmd_plan_beam")
                        if beam is None else beam))
    n_sweeps = max(0, int(_flag("FLAGS_spmd_plan_sweeps")
                          if sweeps is None else sweeps))

    if layer is not None and names is None:
        names = {}
        for dotted, p in layer.named_parameters():
            scope = getattr(p, "scope_name", None) or getattr(
                p, "name", dotted)
            names[scope] = dotted
    names = dict(names or {})

    fixed_data = None if data_specs is None else \
        {k: _spec_entries(v) for k, v in data_specs.items()}
    oracle = _Oracle(program, axes, coll_w, hbm_w)

    repl_param = {s: ((),) * len(pv.aval.shape)
                  for s, pv in program.persistable_vars.items()}
    repl_data = dict(fixed_data) if fixed_data is not None else \
        {n: ((),) * len(v.aval.shape)
         for n, v in program.data_vars.items()}

    def price(assign):
        pa = dict(repl_param)
        da = dict(repl_data)
        for g, cand in assign.items():
            tgt = pa if g.kind == "param" else da
            for m in g.members:
                tgt[m] = cand
        return oracle.price(pa, da)

    if not axes:
        # no mesh axes — the trivial (replicated) plan, no search
        groups: List[PlanGroup] = []
        best_assign: Dict[PlanGroup, tuple] = {}
        n_d, best_score, _opt, best_rep = price(best_assign)
        base_score, base_rep = best_score, best_rep
    else:
        groups = _build_groups(program, axes, names, zero_dp, fixed_data)
        _, base_score, _opt, base_rep = price({})

        # beam over groups in dataflow order, STRATIFIED by diagnostic
        # count: the top `beam` states of each of the lowest diag levels
        # survive. A flat (diags, score) ranking would evict every
        # chain-opening state (column-parallel qkv carries a reshard
        # diagnostic per block until the row-parallel out-proj closes
        # the chain) as soon as `beam` fully-legal states exist; keeping
        # a few diag>0 strata carries the opener to its closer.
        states: List[tuple] = [(0, base_score, base_score, {})]
        for g in groups:
            nxt: List[tuple] = []
            for st in states:
                for cand in g.candidates:
                    a2 = dict(st[3])
                    a2[g] = cand
                    d2, s2, o2, _ = price(a2)
                    nxt.append((d2, s2, o2, a2))
            buckets: Dict[int, list] = {}
            for t in nxt:
                buckets.setdefault(t[0], []).append(t)
            states = []
            for lvl in sorted(buckets)[:_DIAG_STRATA]:
                # legal states rank by the real objective; open-chain
                # states by the optimistic one (gathers assumed closed)
                rank = (lambda t: t[1]) if lvl == 0 else (lambda t: t[2])
                states.extend(sorted(buckets[lvl], key=rank)[:beam_w])

        feasible = [(s, a) for d, s, _o, a in states if d == 0]
        if feasible:
            best_score, best_assign = min(feasible, key=lambda t: t[0])
        else:
            best_score, best_assign = base_score, {}

        # coordinate-descent polish: re-try every candidate of every
        # group against the current winner (feasible moves only)
        for _ in range(n_sweeps):
            improved = False
            for g in groups:
                for cand in g.candidates:
                    if best_assign.get(g, g.candidates[0]) == cand:
                        continue
                    a2 = dict(best_assign)
                    a2[g] = cand
                    d2, s2, _o2, _ = price(a2)
                    if d2 == 0 and s2 < best_score:
                        best_score, best_assign = s2, a2
                        improved = True
            if not improved:
                break

        n_d, best_score, _opt, best_rep = price(best_assign)

    # -- emit ----------------------------------------------------------------
    def chosen(g):
        return best_assign.get(g, g.candidates[0] if g.candidates
                               else ((),) * g.ndim)

    # (template, ndim) -> distinct chosen specs, REPLICATED INCLUDED: a
    # replicated group must veto its template too, or a sibling group's
    # template rule would claim its members through spec_for /
    # install_rules and shard what the search left replicated
    tmpl_specs: Dict[tuple, set] = {}
    for g in groups:
        if g.kind == "param":
            tmpl_specs.setdefault((g.template, g.ndim), set()).add(
                _spec_key(chosen(g)))

    param_specs: Dict[str, P] = {}
    data_plan: Dict[str, P] = {}
    rules: List[PlanRule] = []
    emitted: set = set()
    for g in groups:
        cand = chosen(g)
        if g.kind == "data":
            if any(cand):
                data_plan[g.members[0]] = _to_p(cand)
            continue
        for m in g.members:
            param_specs[m] = _to_p(cand)
        if not any(cand):
            continue  # replicated members need no rule (spec_for -> P())
        if len(tmpl_specs[(g.template, g.ndim)]) > 1:
            # template collision (same name shape, different tensor
            # shape/role): exact-name rules disambiguate; colliding
            # replicated members stay ruleless and default to P()
            for disp in g.display:
                rules.append(PlanRule("^" + re.escape(disp) + "$",
                                      g.ndim, _to_p(cand)))
            continue
        if (g.template, g.ndim) not in emitted:
            emitted.add((g.template, g.ndim))
            rules.append(PlanRule(g.template, g.ndim, _to_p(cand)))
    if fixed_data is not None:
        data_plan = {k: _to_p(v) for k, v in fixed_data.items()}

    plan = ShardingPlan(
        mesh_axes=dict(axes), param_specs=param_specs,
        data_specs=data_plan, rules=rules, names=names, report=best_rep,
        objective=float(best_score), evaluations=oracle.evaluations,
        predicted={
            "collective_bytes": best_rep.collective_bytes(),
            "hbm_peak": best_rep.hbm["peak_bytes"] if best_rep.hbm else 0,
            "diagnostics": len(best_rep.diagnostics),
        },
        baseline={
            "collective_bytes": base_rep.collective_bytes(),
            "hbm_peak": base_rep.hbm["peak_bytes"] if base_rep.hbm else 0,
            "objective": float(base_score),
        })
    monitor.stat_add("spmd.plans_resolved")
    monitor.stat_set_many({
        "spmd.plan_objective": plan.objective,
        "spmd.plan_collective_bytes": plan.predicted["collective_bytes"],
        "spmd.plan_hbm": plan.predicted["hbm_peak"],
        "spmd.plan_evaluations": oracle.evaluations,
    })
    return plan


# ---------------------------------------------------------------------------
# the strategy.auto_shard seam (fleet.distributed_optimizer -> Executor)
# ---------------------------------------------------------------------------

def resolve_auto_shard(program: Program, cfg=None) -> Optional[ShardingPlan]:
    """Resolve a Program tagged `auto_shard` (by
    `fleet.DistributedOptimizer.minimize` under a strategy with
    `auto_shard = True`) into concrete `spmd_param_specs` /
    `spmd_data_specs`. Called from the Executor's compile path; a
    no-mesh environment resolves to None (nothing to shard)."""
    cfg = dict(cfg if cfg is not None
               else getattr(program, "_auto_shard", None) or {})
    plan = cfg.get("plan")
    if plan is None:
        mesh = cfg.get("mesh")
        if mesh is None:
            from ..distributed import mesh as mesh_mod
            mesh = mesh_mod.get_mesh()
        if not _mesh_axes(mesh):
            return None
        plan = plan_program(
            program, mesh=mesh, names=cfg.get("names"),
            data_specs=cfg.get("data_specs"),
            zero_dp=bool(cfg.get("zero_dp", False)),
            coll_weight=cfg.get("coll_weight"),
            hbm_weight=cfg.get("hbm_weight"), beam=cfg.get("beam"))
        cfg["plan"] = plan
        program._auto_shard = cfg  # memoize: compile may re-enter
    plan.apply(program)
    return plan
