"""Auto-sharding planner — search PartitionSpec plans against the SPMD
analyzer's cost model.

PR 3's analyzer (`spmd_analyzer.analyze_program`) can price any candidate
layout on any `{axis: size}` mesh without devices: the implied collective
set with per-device payload bytes, a per-device peak-HBM estimate, and a
hard diagnostic catalogue. This module INVERTS it — instead of asking
users to hand-write `COLUMN_PARALLEL`/`ROW_PARALLEL` regexes ("Scale
MLPerf-0.6 models on Google TPU-v3 Pods" describes exactly the layout
search engineers do by hand today), it derives the plan:

  * **Candidate generation** comes from the analyzer's per-op rules: a
    matmul contraction dim admits row-parallel, a matmul output dim
    admits column-parallel, an embedding/vocab-head weight admits
    vocab-parallel on dim 0, elementwise partners (biases) admit the
    matching 1-D sharding, data feeds admit batch-`dp` (and seq-`sp`)
    sharding, and — opt-in — every remaining param admits ZeRO-style
    `dp` on dim 0. Candidates that cannot divide their dim are never
    generated.
  * **Template grouping**: parameters sharing a name template (digit
    runs collapsed to `\\d+`, e.g. `blocks\\.\\d+\\.fc2\\.weight`) are
    planned as ONE group, so the search space is per-template, not
    per-tensor, and the emitted plan is a compact, human-auditable rule
    list (SNIPPETS `match_partition_rules` idiom, produced instead of
    consumed).
  * **Search**: grouped beam search in dataflow order with analyzer
    re-pricing per candidate. States are ranked by
    `(diagnostic_count, objective)` — intermediate states MAY carry
    diagnostics (column-parallel qkv is illegal until the row-parallel
    out-proj closes the Megatron chain two groups later), but only
    zero-diagnostic final states can win; all-replicated is the always-
    legal fallback. A bounded coordinate-descent sweep then polishes the
    winner. Objective = `coll_weight * collective_bytes/step +
    hbm_weight * peak_per_device_HBM` (flag-tunable).
  * **Emission**, three ways: `plan.param_specs` for
    `Program.spmd_param_specs` / `analyze_program`; `plan.rules` as
    `(template, ndim, PartitionSpec)` records installable via
    `sharding.add_tp_rule` (`plan.install_rules()`); and
    `plan.as_strategy()` — a `fleet.DistributedStrategy` with
    `auto_shard = True` that `fleet.distributed_optimizer` tags onto the
    Program so the Executor resolves the plan at compile
    (`resolve_auto_shard`).

CLI: `python tools/spmd_plan.py --tp 4 [--dp 2 --sp 2] [--json]` plans
the GPT workload and prints the plan next to the hand-written preset and
the replicated baseline. `docs/spmd_planner.md` has the full story.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
from jax.sharding import PartitionSpec as P

from .program import Program, _Ref
from .spmd_analyzer import (SpmdReport, _entries as _spec_entries,
                            _mesh_axes, _mesh_topology, _nbytes,
                            analyze_program)

__all__ = ["ShardingPlan", "PlanRule", "plan_program", "resolve_auto_shard",
           "name_template", "PipelinePlan", "StageCost", "plan_pipeline",
           "legal_cut_points"]


# how many diagnostic-count strata the beam carries (lowest first): a
# chain opener sits one stratum per still-open block above the legal
# states, so this bounds how deep an opener→closer chain may nest
_DIAG_STRATA = 4


def name_template(name: str) -> str:
    """Anchored regex template for a parameter name: all-digit dotted
    components (LayerList indices) collapse to `\\d+`, so
    `blocks.3.fc2.weight` and `blocks.11.fc2.weight` share one rule
    (`^blocks\\.\\d+\\.fc2\\.weight$`). Digits embedded in an identifier
    (`fc1` vs `fc2` — different modules) stay literal."""
    body = r"\.".join(r"\d+" if comp.isdigit() else re.escape(comp)
                      for comp in name.split("."))
    return "^" + body + "$"


def _to_p(entries) -> P:
    return P(*[None if not e else (e[0] if len(e) == 1 else tuple(e))
               for e in entries])


def _spec_key(entries) -> tuple:
    return tuple(tuple(e) for e in entries)


@dataclass
class PlanRule:
    """One emitted rule: params matching `template` (of rank `ndim`)
    take `spec`. The human-auditable unit of the plan."""
    template: str
    ndim: int
    spec: P

    def matches(self, name: str, ndim: int) -> bool:
        return ndim == self.ndim and re.search(self.template, name) \
            is not None


@dataclass(eq=False)  # identity hash: groups key search assignments
class PlanGroup:
    """One search unit: all params (or one data feed) sharing a name
    template, rank and shape; `candidates` are the normalized spec
    tuples the role scan admits (index 0 is always replicated)."""
    template: str
    kind: str                    # "param" | "data"
    members: List[str]           # scope names (program keys)
    display: List[str]           # display (dotted) names for the rules
    ndim: int = 0
    shape: tuple = ()
    nbytes: int = 0
    first_use: int = 1 << 30
    roles: set = field(default_factory=set)
    candidates: List[tuple] = field(default_factory=list)


@dataclass
class ShardingPlan:
    """A searched layout plus its predicted costs, consumable three ways
    (specs dict, rule list, fleet strategy) — see module docstring."""
    mesh_axes: Dict[str, int]
    param_specs: Dict[str, P]          # scope_name -> spec
    data_specs: Dict[str, P]           # data var name -> spec
    rules: List[PlanRule]
    names: Dict[str, str]              # scope_name -> display name
    report: Optional[SpmdReport] = None
    objective: float = 0.0
    predicted: Dict[str, Any] = field(default_factory=dict)
    baseline: Dict[str, Any] = field(default_factory=dict)  # replicated
    evaluations: int = 0
    pipeline: Optional["PipelinePlan"] = None  # stage cuts (plan_pipeline)
    mesh_tiers: Dict[str, dict] = field(default_factory=dict)
    # ^ per-axis link metadata; empty on a flat (single-tier) mesh
    grad_sync: Optional[dict] = None
    # ^ SpmdReport.hierarchical_sync() of the winning layout: the priced
    #   flat/hierarchical/localsgd dp sync schemes + recommendation

    # -- consumption ---------------------------------------------------------
    def spec_for(self, name: str, ndim: int) -> P:
        """Spec for a (display) param name by the emitted rule list —
        the planner-made analog of `sharding.param_spec_for`. Most
        specific rule wins (fewest `\\d+` wildcards first), so an
        exact-name rule beats a template it also matches."""
        for rule in sorted(self.rules,
                           key=lambda r: r.template.count(r"\d+")):
            if rule.matches(name, ndim):
                return rule.spec
        return P()

    def apply(self, program: Program) -> "ShardingPlan":
        """Pin the plan on a Program for `analyze_program` / the
        PADDLE_TPU_VERIFY_SPMD hook / `FLAGS_log_spmd_estimate`."""
        program.spmd_param_specs = dict(self.param_specs)
        program.spmd_data_specs = dict(self.data_specs)
        return self

    def install_rules(self):
        """Register every rule via `sharding.add_tp_rule` (callable
        builders, so a template only fires for its rank); returns the
        installed patterns for later `sharding.remove_tp_rule`."""
        from ..distributed import sharding as sharding_mod
        patterns = []
        for rule in self.rules:
            def build(ndim, _r=rule):
                return _r.spec if ndim == _r.ndim else P()
            sharding_mod.add_tp_rule(rule.template, build)
            patterns.append(rule.template)
        return patterns

    def as_strategy(self, strategy=None):
        """A `fleet.DistributedStrategy` carrying this plan:
        `fleet.distributed_optimizer(opt, plan.as_strategy())` makes
        `minimize` tag the Program and the Executor resolve the plan at
        compile (`auto_shard = True`). A plan carrying pipeline stage
        cuts (`plan_pipeline`) additionally flips `strategy.pipeline` on
        and writes the planned stage assignment into the existing
        `pipeline_configs` knob surface (`schedule_mode: "1F1B"`,
        `accumulate_steps` = the priced microbatch count, plus the
        planner-owned `num_virtual`/`stage_op_ranges` keys)."""
        if strategy is None:
            from ..distributed.fleet import DistributedStrategy
            strategy = DistributedStrategy()
        strategy.auto_shard = True
        strategy.auto_shard_configs = {"plan": self}
        pp = self.pipeline
        if pp is not None:
            strategy.pipeline = True
            strategy.pipeline_configs.update({
                "accumulate_steps": pp.num_micro,
                "schedule_mode": "1F1B",
                "num_virtual": pp.num_virtual,
                "pp_degree": pp.num_stages,
                "stage_op_ranges": [tuple(s.op_range) for s in pp.stages],
            })
        gs = self.grad_sync
        if gs and gs.get("outer", {}).get("size", 1) > 1:
            # two-tier mesh: pick the dp sync mode the cost model chose —
            # the three-phase decomposition by default, LocalSGD when
            # even the decomposed DCN leg dominates
            if gs.get("recommendation") == "localsgd":
                strategy.localsgd = True
                strategy.localsgd_configs = dict(
                    strategy.localsgd_configs or {},
                    k_steps=int(gs.get("localsgd_k", 4)))
            elif gs.get("recommendation") == "hierarchical":
                strategy.hierarchical_allreduce = True
                strategy.hierarchical_allreduce_configs = {
                    "inner_axes": list(gs["inner"]["axes"]),
                    "outer_axes": list(gs["outer"]["axes"])}
        return strategy

    def build_param_shardings(self, params: Dict[str, Any], mesh):
        """`{name: NamedSharding}` for a (dotted-name) param tree — the
        jit `in_shardings` form the MULTICHIP dryrun consumes."""
        from jax.sharding import NamedSharding
        return {name: NamedSharding(mesh, self.spec_for(
            name, len(getattr(v, "shape", ())))) for name, v in
            params.items()}

    # -- reporting -----------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        """Stable (sorted, primitive-typed) form for CI consumption.
        Flat-mesh plans keep the pre-topology key set; the `topology`
        block appears only when the mesh declares link tiers."""
        out = self._base_json()
        if self.mesh_tiers:
            out["topology"] = {
                "tiers": {ax: {"tier": str(m["tier"]),
                               "gbps": float(m["gbps"])}
                          for ax, m in sorted(self.mesh_tiers.items())},
                "grad_sync": self.grad_sync,
            }
        return out

    def _base_json(self) -> Dict[str, Any]:
        return {
            "mesh": dict(sorted(self.mesh_axes.items())),
            "rules": [{"template": r.template, "ndim": r.ndim,
                       "spec": [None if e is None else list(e)
                                if isinstance(e, tuple) else e
                                for e in tuple(r.spec)]}
                      for r in sorted(self.rules,
                                      key=lambda r: (r.template, r.ndim))],
            "data_specs": {k: [None if e is None else e
                               for e in tuple(v)]
                           for k, v in sorted(self.data_specs.items())},
            "predicted": dict(sorted(self.predicted.items())),
            "baseline_replicated": dict(sorted(self.baseline.items())),
            "objective": self.objective,
            "evaluations": self.evaluations,
        }

    def render(self) -> str:
        lines = ["spmd plan: mesh {" + ", ".join(
            f"{a}:{s}" for a, s in self.mesh_axes.items()) + "}"]
        if self.mesh_tiers:
            by_tier: Dict[tuple, List[str]] = {}
            for ax, m in self.mesh_tiers.items():
                by_tier.setdefault(
                    (str(m["tier"]), float(m["gbps"])), []).append(ax)
            lines.append("link tiers: " + "; ".join(
                f"{','.join(axs)}={t}@{g:g}GB/s"
                for (t, g), axs in sorted(by_tier.items())))
        lines.append("rules:")
        for r in sorted(self.rules, key=lambda r: (r.template, r.ndim)):
            lines.append(f"  {r.template:<44} -> {r.spec}")
        if not self.rules:
            lines.append("  (everything replicated)")
        for name, spec in sorted(self.data_specs.items()):
            lines.append(f"  data {name:<39} -> {spec}")
        p, b = self.predicted, self.baseline
        lines.append(
            f"predicted: collective {p.get('collective_bytes', 0)} B/step, "
            f"peak HBM/device {p.get('hbm_peak', 0)} B "
            f"(replicated baseline: {b.get('collective_bytes', 0)} B, "
            f"{b.get('hbm_peak', 0)} B)")
        gs = self.grad_sync
        if gs:
            red = gs.get("inter_pod_reduction_x", 1)
            lines.append(
                f"dp grad sync: {gs.get('recommendation')} "
                f"(inner {'x'.join(map(str, gs['inner']['axes'])) or '-'}"
                f":{gs['inner']['size']}, outer "
                f"{'x'.join(map(str, gs['outer']['axes'])) or '-'}"
                f":{gs['outer']['size']}, hierarchical cuts inter-pod "
                f"bytes {red:.1f}x)")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# role scan — which shardings does each persistable/feed admit?
# ---------------------------------------------------------------------------

def _kw_of(op) -> dict:
    import jax.tree_util as jtu
    try:
        kw = jtu.tree_unflatten(op.kw_tree, op.flat[op.n_args:])
    except Exception:
        return {}
    return kw if isinstance(kw, dict) else {}


_EW_OPS = ("add", "subtract", "multiply", "divide", "maximum", "minimum",
           "where")


def _scan_roles(program: Program):
    """Walk the op list (and control-flow sub-blocks): for every
    persistable, record how it is consumed — the role set drives
    candidate generation. Also records each var's first-use op index so
    the search runs in dataflow order (Megatron chains close as soon as
    possible after they open)."""
    from .control_flow import _CondFn, _WhileFn

    id2scope = {vid: scope for scope, vid in program.persist_ids.items()}
    roles: Dict[str, set] = {s: set() for s in program.persist_ids}
    first: Dict[str, int] = {}

    def note(ref, idx):
        scope = id2scope.get(ref.var_id) if isinstance(ref, _Ref) else None
        if scope is not None:
            first.setdefault(scope, idx)
        return scope

    def walk(ops, base):
        for i, op in enumerate(ops):
            idx = base + i
            if isinstance(op.fn, (_CondFn, _WhileFn)):
                blocks = [op.fn.true_block, op.fn.false_block] \
                    if isinstance(op.fn, _CondFn) else [op.fn.body_block]
                for blk in blocks:
                    walk(blk.ops, idx)
                for x in op.flat:
                    note(x, idx)
                continue
            args = op.flat[:op.n_args]
            kw = _kw_of(op)
            for x in op.flat:
                note(x, idx)
            if op.name == "matmul" and len(args) >= 2:
                ty = bool(kw.get("transpose_y", False))
                tx = bool(kw.get("transpose_x", False))
                lhs, rhs = note(args[0], idx), note(args[1], idx)
                if rhs is not None:
                    roles[rhs].add(("matmul_rhs", ty))
                if lhs is not None:
                    roles[lhs].add(("matmul_lhs", tx))
            elif op.name == "embedding" and args:
                w = note(args[0], idx)
                if w is not None:
                    roles[w].add(("vocab", None))
            elif op.name in ("fused_ce_op", "ce_head_fallback") \
                    and len(args) >= 2:
                w = note(args[1], idx)
                if w is not None:
                    roles[w].add(("vocab", None))
            elif op.name == "moe_layer" and len(args) >= 6:
                # stacked expert weights (w_up, b_up, w_down, b_down):
                # dim 0 is the expert dim, shardable over the layer's
                # `axis` kwarg (conventionally 'ep')
                ax = kw.get("axis", "ep")
                ax = ax if isinstance(ax, str) else "ep"
                for x in args[2:6]:
                    s = note(x, idx)
                    if s is not None:
                        roles[s].add(("expert", ax))
            elif op.name in _EW_OPS:
                for x in args:
                    s = note(x, idx)
                    if s is not None:
                        roles[s].add(("elementwise", None))

    walk(program.ops, 0)
    return roles, first


def _param_candidates(g: PlanGroup, axes: Dict[str, int],
                      zero_dp: bool) -> List[tuple]:
    nd, shape = g.ndim, g.shape
    cands: List[tuple] = [((),) * nd]

    def add(dim, ax):
        if 0 <= dim < nd and shape[dim] % axes[ax] == 0:
            spec = [()] * nd
            spec[dim] = (ax,)
            if tuple(spec) not in cands:
                cands.append(tuple(spec))

    for role, flag in g.roles:
        if role == "matmul_rhs" and nd >= 2:
            cdim = nd - 1 if flag else nd - 2   # contraction: row-parallel
            odim = nd - 2 if flag else nd - 1   # output: column-parallel
            for ax in axes:
                add(cdim, ax)
                add(odim, ax)
        elif role == "vocab":
            for ax in axes:
                add(0, ax)
        elif role == "expert":
            # expert placement: the stacked expert dim shards over the
            # MoE layer's own axis only (all-to-all dispatch/combine is
            # priced by the analyzer's moe_layer rule)
            if flag in axes:
                add(0, flag)
        elif role == "elementwise" and nd == 1:
            # a bias/scale riding an elementwise op can mirror its
            # partner's output sharding
            for ax in axes:
                add(0, ax)
    if zero_dp and "dp" in axes:
        add(0, "dp")
    return cands


def _data_candidates(g: PlanGroup, axes: Dict[str, int],
                     tiers: Optional[Dict[str, dict]] = None) -> List[tuple]:
    """Feeds admit batch-dp (dim 0) and sequence-sp (dim 1) sharding —
    the repo's mesh-axis conventions (fleet hybrid degrees). On a
    two-tier mesh the slow-tier axes also join the batch dim (alone or
    outside `dp`, DCN-major), so the beam can push pure data
    parallelism — and only that — across the pod boundary."""
    nd, shape = g.ndim, g.shape
    cands: List[tuple] = [((),) * nd]
    top = max((float(m.get("gbps", 0.0))
               for m in (tiers or {}).values()), default=0.0)
    slow = [ax for ax, m in (tiers or {}).items()
            if ax in axes and 0 < float(m.get("gbps", 0.0)) < top]

    batch_entries: List[tuple] = []
    if "dp" in axes:
        batch_entries.append(("dp",))
    for ax in sorted(slow):
        batch_entries.append((ax,))
        if "dp" in axes:
            batch_entries.append((ax, "dp"))

    combos = []
    for ent in batch_entries:
        size = 1
        for ax in ent:
            size *= axes[ax]
        if nd >= 1 and shape[0] % size == 0:
            combos.append({0: ent})
    sp_ok = "sp" in axes and nd >= 2 and shape[1] % axes["sp"] == 0
    base = list(combos)
    if sp_ok:
        combos.append({1: ("sp",)})
        for c in base:
            combos.append({**c, 1: ("sp",)})
    for combo in combos:
        spec = [combo.get(d, ()) for d in range(nd)]
        if tuple(spec) not in cands:
            cands.append(tuple(spec))
    return cands


# ---------------------------------------------------------------------------
# the search
# ---------------------------------------------------------------------------

class _Oracle:
    """Memoized analyzer pricing of a full assignment.

    `mesh_desc` preserves the full topology grammar (per-axis link
    tiers) so the analyzer prices slow-tier traffic at its real weight;
    it defaults to the bare axes, which is the flat single-tier case."""

    def __init__(self, program, axes, coll_w, hbm_w, mesh_desc=None):
        self.program = program
        self.axes = axes
        self.mesh_desc = mesh_desc if mesh_desc is not None else axes
        self.coll_w = coll_w
        self.hbm_w = hbm_w
        self.cache: Dict[tuple, tuple] = {}
        self.evaluations = 0

    def price(self, param_assign: Dict[str, tuple],
              data_assign: Dict[str, tuple]):
        """-> (n_diags, score, optimistic_score, report). The optimistic
        score drops the all-gather bytes: a zero-diagnostic plan implies
        none (every gather the analyzer emits rides a diagnostic), so it
        is the value an open Megatron chain would have once its closer
        removes the reshard — the ranking that keeps chain-opening
        states alive inside the infeasible beam strata."""
        key = (tuple(sorted((k, _spec_key(v))
                            for k, v in param_assign.items())),
               tuple(sorted((k, _spec_key(v))
                            for k, v in data_assign.items())))
        hit = self.cache.get(key)
        if hit is not None:
            return hit
        self.evaluations += 1
        report = analyze_program(
            self.program, mesh=self.mesh_desc,
            param_specs={k: _to_p(v) for k, v in param_assign.items()},
            data_specs={k: _to_p(v) for k, v in data_assign.items()})
        hbm = report.hbm["peak_bytes"] if report.hbm else \
            sum(_nbytes(pv.aval)
                for pv in self.program.persistable_vars.values())
        # tier-weighted bytes == plain bytes on a flat mesh, so the
        # single-tier goldens price (and rank) exactly as before
        score = self.coll_w * report.weighted_collective_bytes() \
            + self.hbm_w * hbm
        ar_bytes = report.weighted_collective_bytes("all_reduce")
        opt = self.coll_w * ar_bytes + self.hbm_w * hbm
        out = (len(report.diagnostics), float(score), float(opt), report)
        self.cache[key] = out
        return out


def _build_groups(program: Program, axes, names, zero_dp,
                  fixed_data_specs, tiers=None) -> List[PlanGroup]:
    roles, first = _scan_roles(program)
    names = dict(names or {})
    by_tmpl: Dict[tuple, PlanGroup] = {}

    for scope, pv in program.persistable_vars.items():
        display = names.get(scope, scope)
        shape = tuple(pv.aval.shape)
        # same-template params with different shapes/roles cannot share
        # one rule — the shape in the key splits them apart (their
        # templates then collide; _emit falls back to exact names)
        key = (name_template(display), shape,
               frozenset(roles.get(scope, ())))
        g = by_tmpl.get(key)
        if g is None:
            g = by_tmpl[key] = PlanGroup(
                template=key[0], kind="param", members=[], display=[],
                ndim=len(shape), shape=shape, roles=set(roles.get(scope,
                                                                  ())))
        g.members.append(scope)
        g.display.append(display)
        g.nbytes += _nbytes(pv.aval)
        g.first_use = min(g.first_use, first.get(scope, 1 << 30))

    groups = list(by_tmpl.values())
    for g in groups:
        g.candidates = _param_candidates(g, axes, zero_dp)

    if fixed_data_specs is None:
        for name, v in program.data_vars.items():
            g = PlanGroup(template=name_template(name), kind="data",
                          members=[name], display=[name],
                          ndim=len(v.aval.shape),
                          shape=tuple(v.aval.shape),
                          nbytes=_nbytes(v.aval), first_use=-1)
            g.candidates = _data_candidates(g, axes, tiers)
            groups.append(g)

    # dataflow order: feeds first (they enter at op 0), then params by
    # first use — a Megatron chain's opener and closer sit adjacently,
    # so the infeasible intermediate survives at most a few beam steps
    groups.sort(key=lambda g: (g.first_use, -g.nbytes, g.template))
    return [g for g in groups if len(g.candidates) > 1 or g.kind == "param"]


def plan_program(program: Program, mesh=None, *, layer=None, names=None,
                 data_specs=None, coll_weight=None, hbm_weight=None,
                 beam=None, sweeps=None, zero_dp=False) -> ShardingPlan:
    """Search a PartitionSpec plan for `program` on `mesh`.

    mesh: a Mesh or `{axis: size}` dict (device-free), or None for the
    registered default. `layer`/`names` supply display (dotted) names
    for the rule templates (`names` = {scope_name: dotted_name}; a
    `layer` is walked via `named_parameters()`); without them the rules
    fall back to scope-name templates. `data_specs` pins the feed specs
    instead of searching them. `zero_dp=True` adds ZeRO-style dim-0 `dp`
    candidates for every param the oracle will accept. Weights/beam
    default from `FLAGS_spmd_plan_*`.
    """
    from ..core import monitor
    from ..core.flags import flag as _flag

    axes, tiers = _mesh_topology(mesh)
    # rebuild the device-free grammar form so every oracle pricing run
    # carries the per-axis tiers (and the search stays Mesh-object-free)
    mesh_desc = {ax: ({"size": n, **tiers[ax]} if ax in tiers else n)
                 for ax, n in axes.items()} if tiers else dict(axes)
    coll_w = float(_flag("FLAGS_spmd_plan_coll_weight")
                   if coll_weight is None else coll_weight)
    hbm_w = float(_flag("FLAGS_spmd_plan_hbm_weight")
                  if hbm_weight is None else hbm_weight)
    beam_w = max(1, int(_flag("FLAGS_spmd_plan_beam")
                        if beam is None else beam))
    n_sweeps = max(0, int(_flag("FLAGS_spmd_plan_sweeps")
                          if sweeps is None else sweeps))

    if layer is not None and names is None:
        names = {}
        for dotted, p in layer.named_parameters():
            scope = getattr(p, "scope_name", None) or getattr(
                p, "name", dotted)
            names[scope] = dotted
    names = dict(names or {})

    fixed_data = None if data_specs is None else \
        {k: _spec_entries(v) for k, v in data_specs.items()}
    oracle = _Oracle(program, axes, coll_w, hbm_w, mesh_desc=mesh_desc)

    repl_param = {s: ((),) * len(pv.aval.shape)
                  for s, pv in program.persistable_vars.items()}
    repl_data = dict(fixed_data) if fixed_data is not None else \
        {n: ((),) * len(v.aval.shape)
         for n, v in program.data_vars.items()}

    def price(assign):
        pa = dict(repl_param)
        da = dict(repl_data)
        for g, cand in assign.items():
            tgt = pa if g.kind == "param" else da
            for m in g.members:
                tgt[m] = cand
        return oracle.price(pa, da)

    if not axes:
        # no mesh axes — the trivial (replicated) plan, no search
        groups: List[PlanGroup] = []
        best_assign: Dict[PlanGroup, tuple] = {}
        n_d, best_score, _opt, best_rep = price(best_assign)
        base_score, base_rep = best_score, best_rep
    else:
        groups = _build_groups(program, axes, names, zero_dp, fixed_data,
                               tiers=tiers)
        _, base_score, _opt, base_rep = price({})

        # beam over groups in dataflow order, STRATIFIED by diagnostic
        # count: the top `beam` states of each of the lowest diag levels
        # survive. A flat (diags, score) ranking would evict every
        # chain-opening state (column-parallel qkv carries a reshard
        # diagnostic per block until the row-parallel out-proj closes
        # the chain) as soon as `beam` fully-legal states exist; keeping
        # a few diag>0 strata carries the opener to its closer.
        states: List[tuple] = [(0, base_score, base_score, {})]
        for g in groups:
            nxt: List[tuple] = []
            for st in states:
                for cand in g.candidates:
                    a2 = dict(st[3])
                    a2[g] = cand
                    d2, s2, o2, _ = price(a2)
                    nxt.append((d2, s2, o2, a2))
            buckets: Dict[int, list] = {}
            for t in nxt:
                buckets.setdefault(t[0], []).append(t)
            states = []
            for lvl in sorted(buckets)[:_DIAG_STRATA]:
                # legal states rank by the real objective; open-chain
                # states by the optimistic one (gathers assumed closed)
                rank = (lambda t: t[1]) if lvl == 0 else (lambda t: t[2])
                states.extend(sorted(buckets[lvl], key=rank)[:beam_w])

        feasible = [(s, a) for d, s, _o, a in states if d == 0]
        if feasible:
            best_score, best_assign = min(feasible, key=lambda t: t[0])
        else:
            best_score, best_assign = base_score, {}

        # coordinate-descent polish: re-try every candidate of every
        # group against the current winner (feasible moves only)
        for _ in range(n_sweeps):
            improved = False
            for g in groups:
                for cand in g.candidates:
                    if best_assign.get(g, g.candidates[0]) == cand:
                        continue
                    a2 = dict(best_assign)
                    a2[g] = cand
                    d2, s2, _o2, _ = price(a2)
                    if d2 == 0 and s2 < best_score:
                        best_score, best_assign = s2, a2
                        improved = True
            if not improved:
                break

        n_d, best_score, _opt, best_rep = price(best_assign)

    # -- emit ----------------------------------------------------------------
    def chosen(g):
        return best_assign.get(g, g.candidates[0] if g.candidates
                               else ((),) * g.ndim)

    # (template, ndim) -> distinct chosen specs, REPLICATED INCLUDED: a
    # replicated group must veto its template too, or a sibling group's
    # template rule would claim its members through spec_for /
    # install_rules and shard what the search left replicated
    tmpl_specs: Dict[tuple, set] = {}
    for g in groups:
        if g.kind == "param":
            tmpl_specs.setdefault((g.template, g.ndim), set()).add(
                _spec_key(chosen(g)))

    param_specs: Dict[str, P] = {}
    data_plan: Dict[str, P] = {}
    rules: List[PlanRule] = []
    emitted: set = set()
    for g in groups:
        cand = chosen(g)
        if g.kind == "data":
            if any(cand):
                data_plan[g.members[0]] = _to_p(cand)
            continue
        for m in g.members:
            param_specs[m] = _to_p(cand)
        if not any(cand):
            continue  # replicated members need no rule (spec_for -> P())
        if len(tmpl_specs[(g.template, g.ndim)]) > 1:
            # template collision (same name shape, different tensor
            # shape/role): exact-name rules disambiguate; colliding
            # replicated members stay ruleless and default to P()
            for disp in g.display:
                rules.append(PlanRule("^" + re.escape(disp) + "$",
                                      g.ndim, _to_p(cand)))
            continue
        if (g.template, g.ndim) not in emitted:
            emitted.add((g.template, g.ndim))
            rules.append(PlanRule(g.template, g.ndim, _to_p(cand)))
    if fixed_data is not None:
        data_plan = {k: _to_p(v) for k, v in fixed_data.items()}

    predicted = {
        "collective_bytes": best_rep.collective_bytes(),
        "hbm_peak": best_rep.hbm["peak_bytes"] if best_rep.hbm else 0,
        "diagnostics": len(best_rep.diagnostics),
    }
    if tiers:
        predicted["weighted_collective_bytes"] = \
            best_rep.weighted_collective_bytes()
        predicted["tier_bytes"] = dict(sorted(
            best_rep.tier_bytes().items()))
    plan = ShardingPlan(
        mesh_axes=dict(axes), param_specs=param_specs,
        data_specs=data_plan, rules=rules, names=names, report=best_rep,
        objective=float(best_score), evaluations=oracle.evaluations,
        mesh_tiers=dict(tiers), grad_sync=best_rep.hierarchical_sync(),
        predicted=predicted,
        baseline={
            "collective_bytes": base_rep.collective_bytes(),
            "hbm_peak": base_rep.hbm["peak_bytes"] if base_rep.hbm else 0,
            "objective": float(base_score),
        })
    monitor.stat_add("spmd.plans_resolved")
    monitor.stat_set_many({
        "spmd.plan_objective": plan.objective,
        "spmd.plan_collective_bytes": plan.predicted["collective_bytes"],
        "spmd.plan_hbm": plan.predicted["hbm_peak"],
        "spmd.plan_evaluations": oracle.evaluations,
    })
    return plan


# ---------------------------------------------------------------------------
# pipeline stage-cut + expert-placement planner. The search space is the
# program ITSELF: where to cut the dataflow into pipeline stages (and,
# through the inner SPMD plan, where to place MoE experts on the 'ep'
# axis). Every pricing ingredient is the static analysis the repo
# already trusts: analyze_flops for compute balance, analyze_memory
# restricted to each stage's op range for per-stage HBM,
# pipeline.schedule_collectives for the ppermute wire, and
# bubble_fraction for schedule idle cost.
# ---------------------------------------------------------------------------

@dataclass
class CutPoint:
    """A legal stage boundary: the op index the cut falls BEFORE, and
    the single activation var crossing it (the def-use live set at the
    boundary, persistables and feeds excluded, must be exactly one
    tensor — the pipeline forwards ONE hidden per tick)."""
    boundary: int
    frontier_id: int
    frontier_name: str
    aval: Any


@dataclass
class StageCost:
    """One global pipeline stage's predicted costs."""
    index: int
    op_range: Tuple[int, int]
    flops: float
    hbm_peak: int
    param_bytes: int
    diagnostics: int = 0

    def to_json(self):
        return {"stage": self.index, "op_range": list(self.op_range),
                "flops": self.flops, "hbm_peak": self.hbm_peak,
                "param_bytes": self.param_bytes,
                "diagnostics": self.diagnostics}


def legal_cut_points(program: Program) -> List[CutPoint]:
    """Enumerate the op boundaries where the crossing live set is a
    single activation (the verifier's def-use chains, inverted into cut
    legality): a var is live across boundary `b` when it is defined
    before `b` and read at-or-after `b`. Persistables never cross (each
    stage holds its own params) and feeds enter at stage 0 by
    convention; what remains must be exactly ONE tensor — the narrow
    activation frontier a ppermute can carry."""
    ops = program.ops
    persist = set(program.persist_ids.values())
    data_ids = {v.var_id for v in program.data_vars.values()}
    defined_at: Dict[int, int] = {}
    last_use: Dict[int, int] = {}
    avals: Dict[int, Any] = {}
    names: Dict[int, str] = {}
    for i, op in enumerate(ops):
        for x in op.flat:
            if isinstance(x, _Ref):
                last_use[x.var_id] = i
        for oid, ov in zip(op.out_ids, op.out_vars):
            defined_at[oid] = i
            avals[oid] = ov.aval
            names[oid] = ov.name
    for v in getattr(program, "_jit_fetch_vars", []) or []:
        last_use[v.var_id] = len(ops)
    # state-write values and the backward loss survive to step end
    # exactly as analyze_memory pins them: a mid-program state update
    # CROSSES every later boundary (the stage must forward it), so a
    # cut there is not a single-tensor frontier
    for vid in program.state_writes.values():
        last_use[vid] = len(ops)
    if program.backward_section is not None:
        bw_loss, _pairs = program.backward_section
        last_use[bw_loss.var_id] = len(ops)

    # sweep the boundary left to right, maintaining the live set
    live: set = set()
    cuts: List[CutPoint] = []
    for b in range(1, len(ops)):
        op = ops[b - 1]
        for oid in op.out_ids:
            if oid not in persist and oid not in data_ids \
                    and last_use.get(oid, -1) >= b:
                live.add(oid)
        live = {vid for vid in live if last_use.get(vid, -1) >= b}
        if len(live) == 1:
            (vid,) = live
            cuts.append(CutPoint(b, vid, names.get(vid, str(vid)),
                                 avals.get(vid)))
    return cuts


@dataclass
class PipelinePlan:
    """A searched pipeline partition: `num_stages * num_virtual` global
    stages over the program's op list (stage g runs ops
    `stages[g].op_range`; under interleaved 1F1B, global stage g lives
    on rank `g % num_stages` as chunk `g // num_stages`), priced by the
    per-stage objective and carrying the inner (non-pp) SPMD plan —
    expert placement included — as `inner`."""
    mesh_axes: Dict[str, int]
    axis: str
    num_stages: int
    num_virtual: int
    num_micro: int
    schedule: str
    cuts: List[int]
    stages: List[StageCost]
    frontier_bytes_per_tick: int
    wire: Dict[str, Any]
    bubble: float
    objective: float
    diagnostics: List[str] = field(default_factory=list)
    inner: Optional[ShardingPlan] = None
    cut_points: List[CutPoint] = field(default_factory=list)
    hand: Dict[str, Any] = field(default_factory=dict)
    expert: Dict[str, Any] = field(default_factory=dict)
    evaluations: int = 0

    # -- consumption ---------------------------------------------------------
    @property
    def n_segments(self) -> int:
        """Atomic segments between consecutive legal boundaries (the
        unit a cut vector partitions)."""
        return len(self.cut_points) + 1

    def stage_of_op(self, op_index: int) -> int:
        for s in self.stages:
            if s.op_range[0] <= op_index < s.op_range[1]:
                return s.index
        return 0 if op_index < self.stages[0].op_range[0] \
            else len(self.stages) - 1

    def stage_segments(self) -> List[List[int]]:
        """Segment indices per global stage: segment k spans
        [boundary k-1, boundary k) over the LEGAL boundary list — the
        execution-side unit (StagedPipelineRunner maps one chunk
        parameter pytree per segment)."""
        bounds = [0] + [c.boundary for c in self.cut_points] \
            + [1 << 30]
        out: List[List[int]] = [[] for _ in self.stages]
        for k in range(len(bounds) - 1):
            mid = bounds[k]
            out[self.stage_of_op(mid)].append(k)
        return out

    def param_stages(self, program: Program) -> Dict[str, int]:
        """{scope_name: global stage} by each persistable's first use —
        the stage that must HOLD the param (resolved onto the Program by
        `resolve_auto_shard` before the VERIFY_SPMD hook runs)."""
        _roles, first = _scan_roles(program)
        return {scope: self.stage_of_op(first.get(scope, 0))
                for scope in program.persist_ids}

    # -- reporting -----------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        out = {
            "mesh": dict(sorted(self.mesh_axes.items())),
            "axis": self.axis,
            "num_stages": self.num_stages,
            "num_virtual": self.num_virtual,
            "num_micro": self.num_micro,
            "schedule": self.schedule,
            "cuts": list(self.cuts),
            "stages": [s.to_json() for s in self.stages],
            "frontier_bytes_per_tick": self.frontier_bytes_per_tick,
            "wire": dict(self.wire),
            "bubble": self.bubble,
            "objective": self.objective,
            "diagnostics": list(self.diagnostics),
            "hand": dict(self.hand),
            "expert": dict(self.expert),
            "evaluations": self.evaluations,
        }
        if self.inner is not None:
            out["inner"] = self.inner.to_json()
        return out

    def stage_table(self) -> str:
        """Human-readable per-stage table (tools/spmd_plan.py
        --pipeline)."""
        lines = [
            "pipeline plan: mesh {" + ", ".join(
                f"{a}:{s}" for a, s in self.mesh_axes.items())
            + f"}} axis={self.axis} stages={self.num_stages}"
              f" v={self.num_virtual} micro={self.num_micro}"
              f" schedule={self.schedule}",
            f"  {'stage':<7}{'ops':<12}{'flops':>14}{'peak HBM':>12}"
            f"{'params':>12}{'diags':>7}"]
        for s in self.stages:
            lines.append(
                f"  {s.index:<7}{f'[{s.op_range[0]},{s.op_range[1]})':<12}"
                f"{s.flops:>14.0f}{s.hbm_peak:>12}{s.param_bytes:>12}"
                f"{s.diagnostics:>7}")
        lines.append(
            f"wire: {self.wire.get('count', 0)} ppermute x "
            f"{self.frontier_bytes_per_tick} B = "
            f"{self.wire.get('total_bytes', 0)} B/step; bubble "
            f"{self.bubble:.3f}; objective {self.objective:.0f}")
        if self.expert.get("all_to_all_count"):
            lines.append(
                f"experts: {self.expert.get('rules')} over axis "
                f"'{self.expert.get('axis')}' — "
                f"{self.expert['all_to_all_count']} all-to-all, "
                f"{self.expert.get('all_to_all_bytes', 0)} B/step")
        if self.hand:
            lines.append(
                f"hand (equal-segments) cut: objective "
                f"{self.hand.get('objective', 0):.0f} at cuts "
                f"{self.hand.get('cuts')}")
        if self.diagnostics:
            lines.append(f"diagnostics ({len(self.diagnostics)}):")
            lines += [f"  {d}" for d in self.diagnostics]
        return "\n".join(lines)


def plan_pipeline(program: Program, mesh=None, *, axis="pp",
                  num_micro=None, num_virtual=1, schedule=None,
                  layer=None, names=None, data_specs=None, cuts=None,
                  boundaries=None, beam=None, flops_weight=None,
                  wire_weight=None, hbm_weight=None, bubble_weight=None,
                  zero_dp=False, inner_beam=None, coll_weight=None,
                  inner_hbm_weight=None) -> PipelinePlan:
    """Search pipeline stage cuts (and, through the inner SPMD plan,
    MoE expert placement) for `program` on `mesh`.

    The `axis` (default 'pp') mesh dimension is the pipeline; all OTHER
    axes go to the inner per-stage SPMD plan (`plan_program` — dp/tp/sp
    layouts plus 'ep' expert placement), so a dp/pp/ep mesh is planned
    as one joint objective. `cuts=[op_index, ...]` prices a GIVEN cut
    vector instead of searching (the hand-baseline seam);
    `boundaries=[op_index, ...]` restricts the CANDIDATE boundaries to
    a subset of the legal ones (e.g. layer boundaries, so plan segments
    align 1:1 with the units `StagedPipelineRunner` executes).
    `schedule` defaults to "1f1b" for `num_virtual == 1`, "interleaved"
    otherwise.

    Objective per candidate partition (flags `FLAGS_spmd_plan_pp_*`):

        flops_w  * max(stage FLOPs) * num_micro     # pipeline-full compute
      + bubble_w * bubble_fraction * total FLOPs    # schedule idle cost
      + wire_w   * ppermute wire bytes/step         # schedule_collectives
      + hbm_w    * max(stage peak HBM)              # analyze_memory slice

    Only partitions whose per-stage SPMD sub-plans are zero-diagnostic
    can win (the stage's slice of the inner analyzer report must be
    clean) — the same hard-constraint discipline as the layout search.
    """
    from ..core import monitor
    from ..core.flags import flag as _flag
    from ..distributed.pipeline import (bubble_fraction,
                                        schedule_collectives)
    from .shape_infer import analyze_memory
    from .spmd_analyzer import analyze_flops

    axes, tiers = _mesh_topology(mesh)
    pp = int(axes.get(axis, 1))
    v = max(1, int(num_virtual))
    n_global = pp * v
    if schedule is None:
        schedule = "interleaved" if v > 1 else "1f1b"
    M = int(_flag("FLAGS_spmd_plan_pp_micro")
            if num_micro is None else num_micro)
    beam_w = max(1, int(_flag("FLAGS_spmd_plan_pp_beam")
                        if beam is None else beam))
    fl_w = float(_flag("FLAGS_spmd_plan_pp_flops_weight")
                 if flops_weight is None else flops_weight)
    wi_w = float(_flag("FLAGS_spmd_plan_pp_wire_weight")
                 if wire_weight is None else wire_weight)
    hb_w = float(_flag("FLAGS_spmd_plan_pp_hbm_weight")
                 if hbm_weight is None else hbm_weight)
    bu_w = float(_flag("FLAGS_spmd_plan_pp_bubble_weight")
                 if bubble_weight is None else bubble_weight)

    # inner SPMD plan over everything that is NOT the pipeline axis —
    # dp/tp/sp layouts AND 'ep' expert placement ride the same search
    # (inner_beam/coll_weight/inner_hbm_weight tune that inner search;
    # `beam`/`hbm_weight` above are the STAGE-CUT search's knobs)
    inner_axes = {a: ({"size": s, **tiers[a]} if a in tiers else s)
                  for a, s in axes.items() if a != axis}
    inner = plan_program(program, inner_axes, layer=layer, names=names,
                         data_specs=data_specs, zero_dp=zero_dp,
                         beam=inner_beam, coll_weight=coll_weight,
                         hbm_weight=inner_hbm_weight)
    inner_rep = inner.report
    diagnostics: List[str] = [str(d) for d in inner_rep.diagnostics] \
        if inner_rep is not None else []

    # shared pricing state: per-op flops, avals, per-var shard divisors
    flops = analyze_flops(program)["per_op"]
    total_flops = float(sum(flops))
    env_aval: Dict[int, Any] = {}
    for dv in program.data_vars.values():
        env_aval[dv.var_id] = dv.aval
    for scope, vid in program.persist_ids.items():
        pv = program.persistable_vars.get(scope)
        if pv is not None:
            env_aval[vid] = pv.aval
    for op in program.ops:
        for oid, ov in zip(op.out_ids, op.out_vars):
            env_aval[oid] = ov.aval
    divs: Dict[int, int] = {}
    if inner_rep is not None:
        for vid, spec in inner_rep.specs.items():
            d = 1
            for e in spec:
                for ax in e:
                    d *= axes.get(ax, 1)
            divs[vid] = d
    diag_ops = sorted(d.op_index for d in (inner_rep.diagnostics
                                           if inner_rep else [])
                      if d.op_index is not None)

    # legal boundaries, filtered to the dominant frontier aval so the
    # chosen stages stay homogeneous (hidden -> hidden, the
    # pipeline.py contract)
    all_cuts = legal_cut_points(program)
    shape_votes: Dict[tuple, int] = {}
    for c in all_cuts:
        if c.aval is not None:
            key = (tuple(c.aval.shape), str(c.aval.dtype))
            shape_votes[key] = shape_votes.get(key, 0) + 1
    frontier_key = max(shape_votes, key=shape_votes.get) \
        if shape_votes else None
    if boundaries is not None:
        # the caller defines the unit grid: validate against the FULL
        # legal set (a requested boundary may carry a non-dominant
        # frontier shape — the caller owns that homogeneity choice)
        allowed = {int(b) for b in boundaries}
        illegal = allowed - {c.boundary for c in all_cuts
                             if c.aval is not None}
        if illegal:
            diagnostics.append(
                "pipeline-cut: requested candidate boundaries "
                f"{sorted(illegal)} are not legal single-tensor cut "
                "points")
        cand = [c for c in all_cuts
                if c.aval is not None and c.boundary in allowed]
    else:
        cand = [c for c in all_cuts
                if c.aval is not None
                and (tuple(c.aval.shape),
                     str(c.aval.dtype)) == frontier_key]
    bmap = {c.boundary: c for c in cand}
    bset = [c.boundary for c in cand]
    n_ops = len(program.ops)

    if schedule == "interleaved" and M % max(pp, 1) != 0:
        diagnostics.append(
            f"pipeline-cut: interleaved schedule needs num_micro ({M}) "
            f"divisible by the pp size ({pp})")
    if len(bset) < n_global - 1:
        diagnostics.append(
            f"pipeline-cut: only {len(bset)} legal single-tensor cut "
            f"boundaries for {n_global} stages — the program cannot be "
            f"partitioned this deep")

    evaluations = 0
    stage_cache: Dict[Tuple[int, int], StageCost] = {}

    def _bisect(lst, x):
        import bisect
        return bisect.bisect_left(lst, x)

    def stage_cost(lo: int, hi: int, idx: int = 0) -> StageCost:
        nonlocal evaluations
        hit = stage_cache.get((lo, hi))
        if hit is not None:
            return StageCost(idx, (lo, hi), hit.flops, hit.hbm_peak,
                             hit.param_bytes, hit.diagnostics)
        evaluations += 1
        est = analyze_memory(program, env=env_aval, shard_divisors=divs,
                             op_range=(lo, hi))
        n_diag = _bisect(diag_ops, hi) - _bisect(diag_ops, lo)
        sc = StageCost(idx, (lo, hi), float(sum(flops[lo:hi])),
                       int(est["peak_bytes"]), int(est["param_bytes"]),
                       n_diag)
        stage_cache[(lo, hi)] = sc
        return sc

    def build_stages(cut_vec: List[int]) -> List[StageCost]:
        bounds = [0] + list(cut_vec) + [n_ops]
        return [stage_cost(bounds[k], bounds[k + 1], k)
                for k in range(len(bounds) - 1)]

    def frontier_tick_bytes(cut_vec: List[int]) -> int:
        """Per-tick ppermute payload: one MICROBATCH of the (possibly
        dp/sp-sharded) hidden frontier."""
        if not cut_vec:
            return 0
        per = []
        for b in cut_vec:
            c = bmap.get(b)
            if c is None or c.aval is None:
                continue
            per.append(_nbytes(c.aval)
                       // max(divs.get(c.frontier_id, 1), 1))
        if not per:
            return 0
        return max(per) // max(M, 1)

    def objective_of(stages: List[StageCost], cut_vec: List[int]):
        max_fl = max((s.flops for s in stages), default=0.0)
        max_hbm = max((s.hbm_peak for s in stages), default=0)
        bub = bubble_fraction(M, pp, schedule, v)
        tick_b = frontier_tick_bytes(cut_vec)
        wire = schedule_collectives(M, pp, tick_b, schedule, v,
                                    axis=axis, tiers=tiers or None)
        obj = (fl_w * max_fl * M + bu_w * bub * total_flops
               + wi_w * wire["total_bytes"] + hb_w * max_hbm)
        return obj, bub, wire, tick_b

    need = n_global - 1
    if cuts is not None:
        best_cuts = sorted(int(c) for c in cuts)
        for b in best_cuts:
            if b not in bmap:
                diagnostics.append(
                    f"pipeline-cut: requested cut at op {b} is not a "
                    "legal single-tensor boundary")
    elif need <= 0 or len(bset) < need:
        best_cuts = bset[:max(need, 0)]
    else:
        # diagnostic-stratified beam over boundaries in dataflow order
        # (the PR 10 machinery, re-aimed at cut vectors): a state is a
        # partial cut prefix; closing a stage prices it; states bucket
        # by the diagnostics their CLOSED stages carry and the top
        # `beam` of each of the lowest strata survive. Ranking inside a
        # stratum is the closed-stage imbalance against the ideal
        # flops/n_global split — the optimistic completion score.
        ideal = total_flops / n_global
        # states: (diags, score, n_cuts, cuts_tuple)
        states: List[tuple] = [(0, 0.0, 0, ())]
        for pos, b in enumerate(bset):
            remaining = len(bset) - pos - 1
            nxt: List[tuple] = []
            for dg, sc, k, cv in states:
                if k + remaining >= need:   # skipping b can still finish
                    nxt.append((dg, sc, k, cv))
                if k < need:                # cut at b: close a stage
                    lo = cv[-1] if cv else 0
                    st = stage_cost(lo, b)
                    nxt.append((dg + st.diagnostics,
                                sc + abs(st.flops - ideal), k + 1,
                                cv + (b,)))
            buckets: Dict[int, list] = {}
            for t in nxt:
                buckets.setdefault(t[0], []).append(t)
            states = []
            for lvl in sorted(buckets)[:_DIAG_STRATA]:
                states.extend(sorted(buckets[lvl],
                                     key=lambda t: t[1])[:beam_w])
        finals = [t for t in states if t[2] == need]
        scored = []
        for dg, _sc, _k, cv in finals:
            stages = build_stages(list(cv))
            dg_full = sum(s.diagnostics for s in stages)
            obj, _b, _w, _t = objective_of(stages, list(cv))
            scored.append((dg_full, obj, list(cv)))
        scored.sort(key=lambda t: (t[0], t[1]))
        best_cuts = scored[0][2] if scored else bset[:need]
        if scored and scored[0][0] > 0:
            diagnostics.append(
                f"pipeline-cut: every {n_global}-stage partition "
                "carries per-stage SPMD diagnostics — no clean cut "
                "exists for this layout")

    stages = build_stages(best_cuts)
    obj, bub, wire, tick_b = objective_of(stages, best_cuts)

    # hand baseline: the equal-segments cut (what an engineer writes by
    # hand — `layers // pp` per stage), priced with the SAME objective
    hand: Dict[str, Any] = {}
    n_seg = len(bset) + 1
    if need > 0 and len(bset) >= need:
        hand_cuts = sorted({bset[min(len(bset) - 1,
                                     (k * n_seg) // n_global - 1)]
                            for k in range(1, n_global)})
        if len(hand_cuts) == need:
            h_stages = build_stages(hand_cuts)
            h_obj, _hb, _hw, _ht = objective_of(h_stages, hand_cuts)
            hand = {"cuts": hand_cuts, "objective": float(h_obj),
                    "max_stage_flops": max(s.flops for s in h_stages),
                    "diagnostics": sum(s.diagnostics
                                       for s in h_stages)}

    # expert placement summary (the inner plan's 'ep' work)
    expert: Dict[str, Any] = {}
    if inner_rep is not None:
        a2a = [c for c in inner_rep.collectives if c.kind == "all_to_all"]
        if a2a:
            ep_axes = sorted({c.axis for c in a2a})
            expert = {
                "axis": ",".join(ep_axes),
                "all_to_all_count": len(a2a),
                "all_to_all_bytes": int(sum(c.bytes for c in a2a)),
                "rules": sorted(r.template for r in inner.rules
                                if any(ax in ep_axes
                                       for e in _spec_entries(r.spec)
                                       for ax in e)),
            }

    plan = PipelinePlan(
        mesh_axes=dict(axes), axis=axis, num_stages=pp, num_virtual=v,
        num_micro=M, schedule=schedule, cuts=list(best_cuts),
        stages=stages, frontier_bytes_per_tick=int(tick_b),
        wire=dict(wire), bubble=float(bub), objective=float(obj),
        diagnostics=diagnostics, inner=inner,
        # the FULL candidate list, not just the chosen cuts: segments
        # (the execution-side unit grid) are defined between candidate
        # boundaries, so stage_segments() needs them all
        cut_points=cand,
        hand=hand, expert=expert, evaluations=evaluations)
    inner.pipeline = plan
    monitor.stat_add("spmd.pipeline_plans")
    monitor.stat_set_many({
        "spmd.pipeline_objective": plan.objective,
        "spmd.pipeline_stages": n_global,
        "spmd.pipeline_wire_bytes": wire["total_bytes"],
    })
    return plan


# ---------------------------------------------------------------------------
# the strategy.auto_shard seam (fleet.distributed_optimizer -> Executor)
# ---------------------------------------------------------------------------

def resolve_auto_shard(program: Program, cfg=None) -> Optional[ShardingPlan]:
    """Resolve a Program tagged `auto_shard` (by
    `fleet.DistributedOptimizer.minimize` under a strategy with
    `auto_shard = True`) into concrete `spmd_param_specs` /
    `spmd_data_specs`. Called from the Executor's compile path; a
    no-mesh environment resolves to None (nothing to shard).

    A mesh with a pipeline axis ('pp' by default, override via
    cfg["pipeline_axis"]) routes through `plan_pipeline` instead, and a
    plan carrying stage cuts pins them as `program._pipeline_stages`
    (stage op ranges + per-param stage map) — resolved HERE, before the
    VERIFY_SPMD hook reads the program, so the analyzer and the stage
    assignment always describe the same plan."""
    cfg = dict(cfg if cfg is not None
               else getattr(program, "_auto_shard", None) or {})
    plan = cfg.get("plan")
    if plan is None:
        mesh = cfg.get("mesh")
        if mesh is None:
            from ..distributed import mesh as mesh_mod
            mesh = mesh_mod.get_mesh()
        axes = _mesh_axes(mesh)
        if not axes:
            return None
        pp_axis = cfg.get("pipeline_axis", "pp")
        if axes.get(pp_axis, 1) > 1:
            pp_plan = plan_pipeline(
                program, mesh=mesh, axis=pp_axis,
                num_micro=cfg.get("num_micro"),
                num_virtual=int(cfg.get("num_virtual", 1)),
                schedule=cfg.get("schedule"), names=cfg.get("names"),
                data_specs=cfg.get("data_specs"),
                zero_dp=bool(cfg.get("zero_dp", False)),
                inner_beam=cfg.get("beam"),
                coll_weight=cfg.get("coll_weight"),
                inner_hbm_weight=cfg.get("hbm_weight"))
            plan = pp_plan.inner
        else:
            plan = plan_program(
                program, mesh=mesh, names=cfg.get("names"),
                data_specs=cfg.get("data_specs"),
                zero_dp=bool(cfg.get("zero_dp", False)),
                coll_weight=cfg.get("coll_weight"),
                hbm_weight=cfg.get("hbm_weight"), beam=cfg.get("beam"))
        cfg["plan"] = plan
        program._auto_shard = cfg  # memoize: compile may re-enter
    plan.apply(program)
    pp = getattr(plan, "pipeline", None)
    if pp is not None:
        program._pipeline_stages = {
            "axis": pp.axis,
            "num_stages": pp.num_stages,
            "num_virtual": pp.num_virtual,
            "num_micro": pp.num_micro,
            "schedule": pp.schedule,
            "stage_op_ranges": [tuple(s.op_range) for s in pp.stages],
            "param_stages": pp.param_stages(program),
        }
    return plan
