"""Async pipelined training hot loop.

The compiled step (executor.py) is fast; the loop that DRIVES it was not:
every `Executor.run` re-read the persist scope name-by-name, re-converted
feeds through the host, and blocked on `np.asarray(fetch)` — the TPU idled
between steps on exactly the host-overhead tax the TensorFlow paper's
async dataflow runtime and the MLPerf TPU-pod work identify as the
dominant step-time cost once compute is optimized (PAPERS.md).

Three mechanisms, composable and individually flag-gated:

1. **In-flight steps** (`FLAGS_executor_max_inflight`, default 2): jax
   dispatch is non-blocking, so `submit()` returns lazy `FetchHandle`s
   and keeps up to N steps queued; fetches materialize only at
   print/callback/epoch boundaries. An exception inside an in-flight
   step surfaces at the NEXT materialization as a `PipelineStepError`
   naming the failing step index (in-order verification: the first
   unverified step whose outputs fail to materialize is the culprit).

2. **Device-resident carry**: between steps the donated
   `(scope_vals, slots, lr, t)` carry stays as the previous step's output
   pytree instead of round-tripping through per-name Scope get/set; the
   Scope and optimizer slots are written back lazily at `sync()`
   (context-manager exit, checkpoint, or whenever the caller needs the
   Scope coherent). External Scope writes between submits are therefore
   NOT seen until the next runner is built — the Downpour PS pre/post
   hooks mutate the scope per batch, which is why `train_from_dataset`
   keeps the synchronous loop whenever `ps_config` is given.

3. **Scan-fused megasteps** (`FLAGS_executor_scan_steps` = K, opt-in):
   when feed shapes are stable, K batches stack on the host and ONE
   compiled `lax.scan` over the existing step runs them — 1 dispatch per
   K steps. Bitwise-equal to K serial steps: the scanned body IS the
   serial step function and the per-step (lr, t, rng-key) stream is
   precomputed on the host exactly as the serial loop would produce it.

`run(feeds)` additionally overlaps the NEXT batch's host->device transfer
with the in-flight step via a prefetch thread doing `jax.device_put`
(with the program's dp sharding when data-parallel).

Monitor gauges: `executor/{step_wall_ms,host_overhead_ms,inflight_depth,
scan_megasteps}`.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from ..core import trace as _trace

__all__ = ["PipelineRunner", "FetchHandle", "PipelineStepError",
           "InflightDriver", "StagedPipelineRunner"]

# Flow-id namespace: each runner gets a disjoint block so step flows from
# two runners in one process can't alias in the Chrome trace. Step idx
# rides in the low 40 bits (no aliasing until ~10^12 steps); bit 41
# marks prefetch->dispatch flows. Python ints are unbounded and Chrome
# takes 64-bit ids, so the wide layout costs nothing.
_FLOW_NS = itertools.count(1)

# Rolling-median straggler detector over the per-sync mean step time
# (shared by every runner in the process — the counter it feeds,
# executor.step_anomalies, is process-wide too). min_samples keeps JIT
# warm-up syncs training the baseline instead of paging on it.
from ..core.slo import RollingMedianDetector as _RollingMedianDetector  # noqa: E402

_step_anomalies = _RollingMedianDetector(window=32, k=3.0, min_samples=8)


class PipelineStepError(RuntimeError):
    """An in-flight step failed; raised at the materialization boundary
    that first observed it, naming the failing step index. Constructing
    one triggers a flight-recorder dump (recent spans + metrics to
    PADDLE_TPU_DUMP_DIR; no-op when unset) — the failure was in flight,
    so the dump is the only timeline of what the pipeline was doing."""

    def __init__(self, step_index, original, last_index=None):
        self.step_index = step_index
        self.last_index = last_index if last_index is not None else step_index
        which = (f"step {step_index}" if self.last_index == step_index
                 else f"scan-fused steps {step_index}..{self.last_index}")
        super().__init__(
            f"pipelined {which} failed: "
            f"{type(original).__name__}: {original}")
        self.original = original
        from ..core import flight_recorder as _fr
        _fr.dump("pipeline_step_error", original,
                 extra={"step_index": step_index,
                        "last_index": self.last_index})


class FetchHandle:
    """Lazy fetch: holds the (possibly still computing) device array and
    materializes on demand. `np.asarray(handle)` works."""

    __slots__ = ("_value", "_index", "_runner", "_row")

    def __init__(self, value, step_index, runner=None, row=None):
        self._value = value
        self._index = step_index
        self._runner = runner
        self._row = row  # scan megastep: my row of the stacked fetch

    @property
    def step_index(self):
        return self._index

    def numpy(self):
        sp = _trace.begin(
            "pipeline/materialize", step=self._index,
            parent=None if self._runner is None
            else self._runner._trace_ctx)
        if self._runner is not None:
            sp.flow(self._runner._flow_base + self._index, "f")
        try:
            if self._runner is not None:
                self._runner._verify_through(self._index)
            if self._value is None:  # dispatch was skipped: pipeline broken
                raise PipelineStepError(
                    self._index,
                    RuntimeError("step was never dispatched (an earlier "
                                 "in-flight step already failed)"))
            try:
                arr = np.asarray(self._value)
            except Exception as e:
                raise PipelineStepError(self._index, e) from e
        except BaseException as e:
            sp.attrs["error"] = type(e).__name__
            raise
        finally:
            _trace.end(sp)
        if self._row is not None:  # np scalar -> 0-d ndarray for __array__
            arr = np.asarray(arr[self._row])
        from ..core import flags as _flags
        if _flags.flag("FLAGS_check_nan_inf"):
            from ..core.numeric_check import sweep
            sweep({"fetch": arr}, f"pipelined step {self._index}")
        return arr

    def block_until_ready(self):
        self.numpy()
        return self

    def __array__(self, dtype=None, copy=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def __float__(self):
        return float(self.numpy())

    def __repr__(self):
        return f"FetchHandle(step={self._index}, row={self._row})"


class _Inflight:
    __slots__ = ("first", "last", "fetches")

    def __init__(self, first, last, fetches):
        self.first = first
        self.last = last
        self.fetches = fetches


class _InflightWindow:
    """The shared in-flight window machinery: bounded retire, in-order
    verification, first-failure recording. PipelineRunner and
    InflightDriver both extend it, so failure-ordering/retire semantics
    cannot drift between the training and serving pipelines. Subclasses
    provide `_window`, `_failure`, `_flow_base`, `_trace_ctx` and the
    `_retire_span` name."""

    _retire_span = "pipeline/retire"

    def _record_failure(self, first, last, exc):
        if self._failure is None:
            self._failure = (first, last, exc)

    def _retire_over(self, depth):
        """Bound the in-flight window: block (in submission order) on the
        oldest steps past `depth`. A step that fails here is recorded and
        surfaces at the next materialization boundary."""
        while len(self._window) > depth:
            e = self._window.popleft()
            if not e.fetches:
                continue  # nothing observable; sync() verifies the carry
            sp = _trace.begin(self._retire_span, step_first=e.first,
                              step_last=e.last,
                              parent=self._trace_ctx)
            for i in range(e.first, e.last + 1):
                sp.flow(self._flow_base + i, "t")
            try:
                jax.block_until_ready(e.fetches)
            except Exception as exc:
                sp.attrs["error"] = type(exc).__name__
                _trace.end(sp)
                self._record_failure(e.first, e.last, exc)
                return
            _trace.end(sp)

    def _verify_through(self, index):
        """Materialization boundary: verify (in order) every in-flight
        step up to and including `index`; raise the first failure with
        its step index."""
        while self._window and self._window[0].first <= index:
            e = self._window.popleft()
            if not e.fetches:
                continue
            sp = _trace.begin(self._retire_span, step_first=e.first,
                              step_last=e.last, boundary=True,
                              parent=self._trace_ctx)
            for i in range(e.first, e.last + 1):
                sp.flow(self._flow_base + i, "t")
            try:
                jax.block_until_ready(e.fetches)
            except Exception as exc:
                sp.attrs["error"] = type(exc).__name__
                _trace.end(sp)
                self._record_failure(e.first, e.last, exc)
                break
            _trace.end(sp)
        # steps BEFORE the failure still materialize normally; the
        # failure surfaces for any step at-or-after its index
        if self._failure is not None and self._failure[0] <= index:
            first, last, exc = self._failure
            raise PipelineStepError(first, exc, last)


class InflightDriver(_InflightWindow):
    """The PipelineRunner's in-flight window machinery, factored for
    drivers that are not static Programs — the continuous-batching serve
    loop (inference/serving.py) dispatches its fused decode steps
    through one of these so dispatch of step N+1 overlaps
    sampling/detokenization of step N, with the same semantics the
    training pipeline proved out:

    - `submit(thunk)` dispatches non-blocking jax work; the thunk
      returns (carry, fetches) — the carry comes back raw (device
      arrays the next submit consumes), each fetch leaf comes back as a
      lazy `FetchHandle`;
    - the window is bounded at `max_inflight` by blocking on the oldest
      step (device wait, not host work);
    - a failed step is recorded and surfaces as `PipelineStepError`
      (naming the step index, flight-recorder dump attached) at the
      NEXT materialization — steps before it still materialize;
    - every dispatch/retire leaves spans with per-step flow chains, so
      a serve run renders in obs_report/Chrome-trace exactly like a
      training run, and pulses the elastic liveness listeners.

    span names are `{name}/dispatch` and `{name}/retire_wait`; pass
    name="serve/decode_step" style prefixes to namespace them."""

    def __init__(self, name="driver", max_inflight=None):
        from ..core import flags as _flags
        self._name = name
        self._retire_span = f"{name}/retire_wait"
        if max_inflight is None:
            max_inflight = _flags.flag("FLAGS_executor_max_inflight")
        self._max_inflight = max(1, int(max_inflight))
        self._window: deque = deque()
        self._next_index = 0
        self._failure = None
        self._depth_peak = 0
        self._flow_base = next(_FLOW_NS) << 42
        self._trace_ctx = _trace.current() or (_trace.new_trace_id(),
                                               None)
        from ..distributed.elastic import notify_step
        self._notify_step = notify_step

    @property
    def inflight_depth_peak(self):
        return self._depth_peak

    def submit(self, thunk, **attrs):
        """Dispatch thunk() -> (carry, fetches). Returns (carry,
        handles); carry is None when the dispatch itself failed (the
        failure surfaces at the handles' materialization)."""
        if self._failure is not None:
            idx = self._next_index
            self._next_index += 1
            return None, [FetchHandle(None, idx, self)]
        sp = _trace.begin(f"{self._name}/dispatch",
                          parent=self._trace_ctx, **attrs)
        idx = self._next_index
        self._next_index += 1
        sp.attrs["step"] = idx
        sp.flow(self._flow_base + idx, "s")
        try:
            try:
                carry, fetches = thunk()
            except Exception as exc:
                sp.attrs["error"] = type(exc).__name__
                self._record_failure(idx, idx, exc)
                return None, [FetchHandle(None, idx, self)]
        finally:
            _trace.end(sp)
        if not isinstance(fetches, (tuple, list)):
            fetches = [fetches]
        self._window.append(_Inflight(idx, idx, list(fetches)))
        self._retire_over(self._max_inflight)
        self._depth_peak = max(self._depth_peak, len(self._window))
        self._notify_step(idx + 1)
        return carry, [FetchHandle(f, idx, self) for f in fetches]

    def sync(self):
        """Materialize ALL in-flight work; raises PipelineStepError
        naming the first failed step, if any."""
        self._verify_through(self._next_index)


class StagedPipelineRunner(InflightDriver):
    """Executes a PLANNED pipeline partition (`static/spmd_planner.
    plan_pipeline` -> `PipelinePlan`) as one SPMD program per train
    step: the plan's global stages become per-rank chunks (interleaved
    1F1B convention — global stage g is chunk g//n on rank g%n), each
    step runs the plan's `num_micro` microbatches through
    `distributed/pipeline.pipeline_loss` (schedule "1f1b" for v=1,
    "interleaved" for v>1) inside `shard_map` over the pp (and
    optionally dp) mesh axes, and successive steps dispatch through the
    inherited bounded in-flight window — the PR 5 microbatch engine now
    driving planned stage chunks.

    The model is supplied as homogeneous UNITS (hidden -> hidden),
    one per plan segment (`plan.n_segments` — the regions between the
    planner's legal cut boundaries): `unit_apply(h, unit_params) -> h`
    plus a list of per-unit parameter pytrees with identical structure
    and leaf shapes. Stages owning fewer units than the deepest stage
    are padded with masked no-op slots, so every rank traces the SAME
    program (the single-program SPMD invariant pipeline.py documents).

    Training is SGD on the stacked params (`learning_rate`); `step(x,
    y)` returns a lazy loss FetchHandle, `unit_params()` unstacks the
    live params back into plan-segment order, `sync()` materializes all
    in-flight steps (PipelineStepError semantics inherited)."""

    def __init__(self, plan, unit_apply, unit_params, loss_fn, mesh=None,
                 learning_rate=0.1, dp_axis="dp", max_inflight=None):
        super().__init__(name="pipeline/staged",
                         max_inflight=max_inflight)
        from jax.sharding import PartitionSpec as P

        from ..distributed import mesh as mesh_mod
        from ..distributed import pipeline as pipe

        if mesh is None:
            mesh = mesh_mod.get_mesh()
        if mesh is None or plan.axis not in mesh.axis_names:
            have = None if mesh is None else tuple(mesh.axis_names)
            raise ValueError(
                "StagedPipelineRunner needs a mesh with the plan's "
                f"'{plan.axis}' axis (got axes {have}) — a leaked "
                "default mesh does not qualify")
        n, v = plan.num_stages, plan.num_virtual
        segs = plan.stage_segments()
        if len(unit_params) != plan.n_segments:
            raise ValueError(
                f"plan has {plan.n_segments} segments but "
                f"{len(unit_params)} unit param pytrees were given")
        u_max = max((len(s) for s in segs), default=1) or 1
        self._plan = plan
        self._mesh = mesh
        self._lr = float(learning_rate)
        self._M = plan.num_micro
        self._axis = plan.axis
        self._dp = dp_axis if dp_axis in mesh.axis_names else None
        self._seg_pos = {}  # segment -> (rank, chunk, unit slot)

        # pad slots carry a COPY of real params, not zeros: the masked
        # where-branch still evaluates unit_apply on them, and a
        # singular input (division by a zero scale, w/||w||) would
        # NaN-poison the shared cotangent through NaN * 0
        pad = unit_params[0]
        grid = [[[pad] * u_max for _ in range(v)] for _ in range(n)]
        mask = np.zeros((n, v, u_max), np.float32)
        for g, seg_list in enumerate(segs):
            r, c = g % n, g // n
            for u, seg in enumerate(seg_list):
                grid[r][c][u] = unit_params[seg]
                mask[r, c, u] = 1.0
                self._seg_pos[seg] = (r, c, u)
        # leaves -> [n, v, u_max, ...]
        self._w = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves).reshape(
                (n, v, u_max) + leaves[0].shape),
            *[grid[r][c][u] for r in range(n) for c in range(v)
              for u in range(u_max)])
        self._mask = jnp.asarray(mask)

        schedule = "interleaved" if v > 1 else \
            (plan.schedule if plan.schedule in ("gpipe", "1f1b")
             else "1f1b")
        axis = self._axis
        dp = self._dp

        def spmd(wr, mr, xm, ym):
            # wr leaves [1, v, u_max, ...] (this rank's chunks)
            def chunk_fn(c):
                def f(h):
                    for u in range(u_max):
                        p_u = jax.tree_util.tree_map(
                            lambda leaf: leaf[0, c, u], wr)
                        h = jnp.where(mr[0, c, u] > 0,
                                      unit_apply(h, p_u), h)
                    return h
                return f
            fns = [chunk_fn(c) for c in range(v)]
            loss = pipe.pipeline_loss(
                fns if schedule == "interleaved" else fns[0],
                loss_fn, xm, ym, axis=axis, schedule=schedule)
            if dp is not None:
                loss = jax.lax.pmean(loss, dp)
            return loss

        in_x = P(None, dp) if dp is not None else P()

        def outer(w, m, x, y):
            return mesh_mod.shard_map(
                spmd, mesh=mesh, in_specs=(P(axis), P(axis), in_x, in_x),
                out_specs=P())(w, m, x, y).mean()

        lr = self._lr

        def train_step(w, m, x, y):
            loss, g = jax.value_and_grad(outer)(w, m, x, y)
            new_w = jax.tree_util.tree_map(lambda p, gg: p - lr * gg,
                                           w, g)
            return new_w, loss

        self._jit = jax.jit(train_step, donate_argnums=(0,))

    @property
    def plan(self):
        return self._plan

    def step(self, x, y):
        """Dispatch one pipelined train step over the plan's num_micro
        microbatches of (x, y); returns a lazy loss FetchHandle."""
        from ..distributed.pipeline import micro_batch
        xm = micro_batch(jnp.asarray(x), self._M)
        ym = micro_batch(jnp.asarray(y), self._M)
        w, mask = self._w, self._mask

        def thunk():
            new_w, loss = self._jit(w, mask, xm, ym)
            return new_w, [loss]

        carry, handles = self.submit(
            thunk, stages=self._plan.num_stages,
            num_virtual=self._plan.num_virtual, micro=self._M)
        if carry is not None:
            self._w = carry
        return handles[0]

    def unit_params(self):
        """The live parameters, unstacked back into plan-segment order
        (materializes in-flight work first)."""
        self.sync()
        out = []
        for seg in range(self._plan.n_segments):
            r, c, u = self._seg_pos[seg]
            out.append(jax.tree_util.tree_map(
                lambda leaf: leaf[r, c, u], self._w))
        return out


class PipelineRunner(_InflightWindow):
    """Drives a static Program's compiled step with in-flight steps and a
    device-resident carry. Use as a context manager; `sync()` (or exit)
    materializes all in-flight work and writes the Scope/slots back.

    `stage_plan` (a `spmd_planner.PipelinePlan`) makes the runner
    stage-aware: the plan rides on dispatch spans and the
    `executor/pipeline_stages` gauge, so a planned-pipeline program's
    trace names its partition. Execution of the planned stages
    themselves is `StagedPipelineRunner`'s job (one SPMD program per
    step); this runner remains the host-side step driver."""

    def __init__(self, executor, program, fetch_list=None, scope=None,
                 max_inflight=None, scan_steps=None, stage_plan=None):
        from ..core import flags as _flags
        from .executor import CompiledProgram
        from .program import default_main_program, global_scope
        self._exe = executor
        self.stage_plan = stage_plan
        if stage_plan is not None:
            from ..core import monitor as _monitor
            _monitor.stat_set("executor/pipeline_stages",
                              stage_plan.num_stages
                              * stage_plan.num_virtual)
        self._data_parallel = False
        if isinstance(program, CompiledProgram):
            self._data_parallel = program.data_parallel
            program = program.program
        self._program = program or default_main_program()
        self._scope = scope or global_scope()
        self._fetch_list = list(fetch_list or [])
        if max_inflight is None:
            max_inflight = _flags.flag("FLAGS_executor_max_inflight")
        self._max_inflight = max(1, int(max_inflight))
        if scan_steps is None:
            scan_steps = _flags.flag("FLAGS_executor_scan_steps")
        self._scan_steps = int(scan_steps or 0)
        self._entry = None
        self._carry = None            # (scope_vals, slots) device pytrees
        self._window: deque = deque()  # unverified _Inflight entries
        self._next_index = 0
        self._synced_through = 0      # gauges cover [synced_through, next)
        self._failure = None          # (first_idx, last_idx, exc)
        self._host_s = 0.0
        self._wall_t0 = None
        self._depth_peak = 0
        # disjoint flow-id block for this runner's step flows (s: dispatch,
        # t: retire, f: materialize) and prefetch->dispatch handoffs
        self._flow_base = next(_FLOW_NS) << 42
        self._prefetch_flow = None    # set by run()'s consumer per item
        # one trace per runner lifetime: every dispatch/retire/
        # materialize/prefetch span joins it, so a whole training run is
        # one connected trace even when nothing opened a root span
        self._trace_ctx = _trace.current() or (_trace.new_trace_id(),
                                               None)
        # liveness pulse: every dispatched step refreshes the active
        # StallMonitor/Heartbeat listeners (distributed/elastic.py)
        from ..distributed.elastic import notify_step
        self._notify_step = notify_step

    # -- lifecycle -----------------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.sync()
        else:
            try:  # body already failing: don't mask its exception
                self.sync()
            except Exception:
                pass
        return False

    # -- internals -----------------------------------------------------------
    def _ensure(self, feed_vals):
        if self._entry is None:
            entry = self._exe._prepare(self._program, feed_vals,
                                       self._fetch_list,
                                       self._data_parallel)
            for n, v0 in (entry.amp_init or {}).items():
                if not self._scope.has(n):
                    self._scope.set(n, v0)
            scope_vals = {n: self._scope.get(n) for n in entry.read_names}
            self._entry = entry
            self._carry = (scope_vals, None)
            self._wall_t0 = time.perf_counter()
        return self._entry

    def _slots_in(self, scope_vals, prev_slots):
        entry = self._entry
        if entry.opt is None:
            return {}
        if prev_slots is None:  # first step: seed from the optimizer
            entry.opt._ensure_slots(
                {n: scope_vals[n] for n in entry.opt_pnames})
            return {n: entry.opt._slots[n] for n in entry.opt_pnames}
        return prev_slots

    def _dead_handles(self, k=1):
        entry = self._entry
        n_fetch = len(entry.fetch_ids) if entry is not None else 0
        out = []
        for _ in range(k):
            idx = self._next_index
            self._next_index += 1
            out.append([FetchHandle(None, idx, self)
                        for _ in range(n_fetch)])
        return out

    # _record_failure/_retire_over/_verify_through: _InflightWindow

    # -- submission ----------------------------------------------------------
    def submit(self, feed):
        """Dispatch one step (non-blocking); returns a list of
        FetchHandle, one per fetch_list entry."""
        from ..core import monitor as _monitor
        from ..core import rng as _rng
        if self._failure is not None:
            return self._dead_handles(1)[0]
        t0 = time.perf_counter()
        sp = _trace.begin("pipeline/dispatch", parent=self._trace_ctx)
        if self.stage_plan is not None:
            sp.attrs["pipeline_stages"] = self.stage_plan.num_stages \
                * self.stage_plan.num_virtual
        pf = self._prefetch_flow
        if pf is not None:        # close the prefetch->dispatch handoff
            self._prefetch_flow = None
            sp.flow(pf, "f")
        try:
            feed_vals = self._exe._convert_feeds(self._program, feed)
            entry = self._ensure(feed_vals)
            scope_vals, prev_slots = self._carry
            slots = self._slots_in(scope_vals, prev_slots)
            lr, t = jnp.zeros(()), jnp.zeros((), jnp.int32)
            if entry.opt is not None:
                entry.opt._step_count += 1
                lr = jnp.asarray(entry.opt.get_lr(), jnp.float32)
                t = jnp.asarray(entry.opt._step_count, jnp.int32)
            key = _rng.next_key()
            idx = self._next_index
            self._next_index += 1
            sp.attrs["step"] = idx
            sp.flow(self._flow_base + idx, "s")
            try:
                fetches, new_scope, new_slots = entry.jitted(
                    tuple(feed_vals[n] for n in entry.feed_names),
                    scope_vals, slots, lr, t, key)
            except Exception as exc:
                sp.attrs["error"] = type(exc).__name__
                self._record_failure(idx, idx, exc)
                self._host_s += time.perf_counter() - t0
                return [FetchHandle(None, idx, self)
                        for _ in entry.fetch_ids]
        finally:
            _trace.end(sp)
        self._carry = (new_scope, new_slots)
        self._window.append(_Inflight(idx, idx, fetches))
        r0 = time.perf_counter()
        self._retire_over(self._max_inflight)
        r1 = time.perf_counter()  # retire blocks on the DEVICE, not host
        self._depth_peak = max(self._depth_peak, len(self._window))
        self._host_s += (r1 - t0) - (r1 - r0)
        _monitor.stat_add("executor/runs")
        self._notify_step(idx + 1)
        return [FetchHandle(f, idx, self) for f in fetches]

    def submit_scan(self, stacked_feed, k):
        """Dispatch ONE scan-fused megastep over `k` host-stacked batches
        (each feed value has a leading K axis). Returns k FetchHandle
        lists — rows of the stacked fetches."""
        from ..core import monitor as _monitor
        from ..core import rng as _rng
        if self._failure is not None:
            return self._dead_handles(k)
        t0 = time.perf_counter()
        sp = _trace.begin("pipeline/dispatch_scan", k=k,
                          parent=self._trace_ctx)
        pf = self._prefetch_flow
        if pf is not None:
            self._prefetch_flow = None
            sp.flow(pf, "f")
        try:
            feed_vals = self._exe._convert_feeds(self._program,
                                                 stacked_feed)
            entry = self._ensure(feed_vals)
            scope_vals, prev_slots = self._carry
            slots = self._slots_in(scope_vals, prev_slots)
            lrs, ts, keys = [], [], []
            for _ in range(k):  # the exact per-step stream the serial loop
                if entry.opt is not None:  # would have produced
                    entry.opt._step_count += 1
                    lrs.append(entry.opt.get_lr())
                    ts.append(entry.opt._step_count)
                else:
                    lrs.append(0.0)
                    ts.append(0)
                keys.append(_rng.next_key())
            lrs = jnp.asarray(np.asarray(lrs, np.float32))
            ts = jnp.asarray(np.asarray(ts, np.int32))
            keys = jnp.stack(keys)
            first = self._next_index
            self._next_index += k
            last = first + k - 1
            sp.attrs["step_first"], sp.attrs["step_last"] = first, last
            for i in range(first, last + 1):
                sp.flow(self._flow_base + i, "s")
            try:
                fetches, new_scope, new_slots = entry.scan_jitted()(
                    tuple(feed_vals[n] for n in entry.feed_names),
                    scope_vals, slots, lrs, ts, keys)
            except Exception as exc:
                sp.attrs["error"] = type(exc).__name__
                self._record_failure(first, last, exc)
                self._host_s += time.perf_counter() - t0
                return [[FetchHandle(None, first + i, self)
                         for _ in entry.fetch_ids] for i in range(k)]
        finally:
            _trace.end(sp)
        self._carry = (new_scope, new_slots)
        self._window.append(_Inflight(first, last, fetches))
        r0 = time.perf_counter()
        self._retire_over(self._max_inflight)
        r1 = time.perf_counter()  # retire blocks on the DEVICE, not host
        self._depth_peak = max(self._depth_peak, len(self._window))
        self._host_s += (r1 - t0) - (r1 - r0)
        _monitor.stat_add("executor/runs", k)
        _monitor.stat_add("executor/scan_megasteps")
        self._notify_step(last + 1)
        return [[FetchHandle(f, first + i, self, row=i) for f in fetches]
                for i in range(k)]

    # -- the driving loop ----------------------------------------------------
    def run(self, feeds):
        """Drive an iterable of feed dicts through the pipeline, yielding
        one FetchHandle list per logical step. Feed conversion and the
        host->device transfer run on a prefetch thread (with the
        program's dp sharding when data-parallel), overlapping the
        in-flight steps; K-batch groups are stacked there for the
        scan-fused path when enabled and shape-stable."""
        scan_k = self._scan_steps if self._scan_steps > 1 else 0
        q: queue.Queue = queue.Queue(maxsize=max(2, self._max_inflight + 1))
        stop = threading.Event()
        sentinel = object()
        program = self._program
        from .executor import _convert_feed, _dp_shardings
        dp = _dp_shardings() if self._data_parallel else None
        batch_sh = scan_sh = None
        if dp is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            mesh = dp[0]
            batch_sh = dp[2]
            scan_sh = NamedSharding(mesh, P(None, "dp"))

        def convert(feed, stacked=False):
            out = {}
            for name, val in feed.items():
                var = program.data_vars.get(name)
                if var is None:
                    raise KeyError(
                        f"feed '{name}' is not a data variable of the "
                        f"program (have {list(program.data_vars)})")
                out[name] = _convert_feed(
                    val, var.aval, scan_sh if stacked else batch_sh)
            return out

        def sig(feed):
            return tuple(sorted(
                (n, tuple(np.shape(v)),
                 str(getattr(v, "dtype", None) or np.asarray(v).dtype))
                for n, v in feed.items()))

        def put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        # prefetch spans join the caller's trace; each converted item
        # carries a flow id so the Chrome trace draws the cross-thread
        # handoff prefetch(s) -> dispatch(f) for every batch
        parent_ctx = self._trace_ctx
        flow_seq = itertools.count()

        def _fid():
            return self._flow_base | (1 << 41) | next(flow_seq)

        def _convert_traced(feed, stacked=False, k=1):
            fid = _fid()
            with _trace.span("pipeline/prefetch", stacked=stacked,
                             k=k) as psp:
                psp.flow(fid, "s")
                return convert(feed, stacked), fid

        def _produce():
            buf, cur_sig = [], None
            for feed in feeds:
                if stop.is_set():
                    return
                if not scan_k:
                    if not put(("one",) + _convert_traced(feed)):
                        return
                    continue
                s = sig(feed)
                if buf and s != cur_sig:  # shape break: no fusion
                    for f in buf:
                        if not put(("one",) + _convert_traced(f)):
                            return
                    buf = []
                buf.append(feed)
                cur_sig = s
                if len(buf) == scan_k:
                    stacked = {
                        n: np.stack([np.asarray(f[n]) for f in buf])
                        for n in buf[0]}
                    vals, fid = _convert_traced(stacked, True, scan_k)
                    if not put(("scan", vals, scan_k, fid)):
                        return
                    buf = []
            for f in buf:  # remainder < K runs unfused
                if not put(("one",) + _convert_traced(f)):
                    return

        def producer():
            try:
                with _trace.attach(parent_ctx):
                    _produce()
            except BaseException as e:  # surfaced on the consumer side
                put(("error", e))
            finally:
                put(sentinel)

        th = threading.Thread(target=producer, daemon=True,
                              name="pipeline-prefetch")
        th.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                if item[0] == "error":
                    raise item[1]
                if item[0] == "one":
                    self._prefetch_flow = item[2]
                    yield self.submit(item[1])
                else:
                    self._prefetch_flow = item[3]
                    for handles in self.submit_scan(item[1], item[2]):
                        yield handles
        finally:
            stop.set()
            try:  # unblock a producer stuck on a full queue
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            th.join(timeout=5)

    # -- materialization / write-back ---------------------------------------
    def sync(self):
        """Materialize ALL in-flight work, write the carry back into the
        Scope, update the optimizer slots, and publish the pipeline
        gauges. Raises PipelineStepError (naming the failing step) if any
        in-flight step failed; no partial/poisoned state is written back,
        but the step's donation has already CONSUMED the Scope-owned
        buffers of a donating program (same as a failed serial
        Executor.run) — recovery is restart-from-checkpoint, not
        resume-from-Scope."""
        from ..core import flags as _flags
        from ..core import monitor as _monitor
        if self._entry is None:
            return
        with _trace.span("pipeline/sync", parent=self._trace_ctx,
                         step_first=self._synced_through,
                         step_last=self._next_index - 1):
            self._verify_through(self._next_index)
            new_scope, new_slots = self._carry
            try:
                jax.block_until_ready((new_scope, new_slots or {}))
            except Exception as exc:
                self._record_failure(
                    self._window[0].first if self._window else
                    max(self._next_index - 1, 0),
                    max(self._next_index - 1, 0), exc)
                first, last, e = self._failure
                raise PipelineStepError(first, e, last)
        if _flags.flag("FLAGS_check_nan_inf"):
            # the serial loop swept {fetches, scope} every batch; the
            # pipelined loop sweeps the carry at every sync boundary
            # (fetch handles sweep themselves at materialization) — and
            # BEFORE the write-back, so a NaN leaves the Scope at its
            # last good state
            from ..core.numeric_check import sweep
            sweep({"scope": new_scope},
                  f"PipelineRunner.sync (steps "
                  f"{self._synced_through}..{self._next_index - 1})")
        for n, v in new_scope.items():
            self._scope.set(n, v)
        if self._entry.opt is not None and new_slots:
            self._entry.opt._slots.update(new_slots)
        # gauges cover the interval since the LAST sync, then reset — so
        # a bench warmup + sync leaves the timed window free of first-call
        # compile cost
        steps = self._next_index - self._synced_through
        if steps > 0:
            wall_ms = ((time.perf_counter() - self._wall_t0) * 1000.0
                       if self._wall_t0 is not None else 0.0)
            _monitor.stat_set_many({
                "executor/step_wall_ms": wall_ms / steps,
                "executor/host_overhead_ms":
                    self._host_s * 1000.0 / steps,
                "executor/inflight_depth": self._depth_peak,
            })
            # distribution + trajectory, not just the last window's mean
            _monitor.observe("executor/step_ms", wall_ms / steps)
            _monitor.observe("executor/host_ms",
                             self._host_s * 1000.0 / steps)
            if _step_anomalies.observe(wall_ms / steps):
                # straggler step: out of family vs the rolling median
                # (core/slo.py) — counted so the telemetry hub's fleet
                # view can attribute pod-scale step-time jitter
                _monitor.stat_add("executor.step_anomalies")
        self._synced_through = self._next_index
        self._host_s = 0.0
        self._wall_t0 = time.perf_counter()

    close = sync
