"""paddle.static — static graph mode.

Analog of reference python/paddle/static/ + python/paddle/fluid
graph-building (framework.py Program/append_op, executor.py,
backward.py append_backward, compiler.py CompiledProgram).
See program.py / executor.py docstrings for the compile-first design.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..hapi.model import InputSpec  # noqa: F401
from . import amp  # noqa: F401
from .executor import (BuildStrategy, CompiledProgram, ExecutionStrategy,  # noqa: F401
                       Executor)
from .pipeline_runner import (FetchHandle, PipelineRunner,  # noqa: F401
                              PipelineStepError, StagedPipelineRunner)
from .program import (Program, Variable, StaticParam, default_main_program,  # noqa: F401
                      default_startup_program, disable_static_,
                      enable_static_, global_scope, in_static_mode,
                      name_scope, program_guard)
from .shape_infer import (ShapeInferError, analyze_memory,  # noqa: F401
                          infer_program, register_infer_rule)
from .spmd_analyzer import (Collective, SpmdDiagnostic,  # noqa: F401
                            SpmdLintError, SpmdReport, analyze_params,
                            analyze_program, maybe_verify_spmd,
                            register_spmd_rule, set_verify_spmd,
                            verify_spmd_enabled)
from .spmd_planner import (PipelinePlan, PlanRule, ShardingPlan,  # noqa: F401
                           StageCost, legal_cut_points, plan_pipeline,
                           plan_program, resolve_auto_shard)
from .verifier import ProgramVerifyError, verify_program  # noqa: F401

__all__ = ["data", "InputSpec", "Program", "Variable", "Executor",
           "CompiledProgram", "BuildStrategy", "ExecutionStrategy",
           "program_guard", "name_scope", "default_main_program",
           "default_startup_program", "global_scope", "append_backward",
           "gradients", "save", "load", "set_program_state", "nn",
           "save_inference_model", "load_inference_model",
           "cpu_places", "cuda_places",
           "verify_program", "ProgramVerifyError", "infer_program",
           "ShapeInferError", "register_infer_rule", "analyze_memory",
           "analyze_program", "analyze_params", "SpmdLintError",
           "SpmdReport", "SpmdDiagnostic", "Collective",
           "register_spmd_rule", "set_verify_spmd", "verify_spmd_enabled",
           "maybe_verify_spmd", "ShardingPlan", "PlanRule",
           "plan_program", "resolve_auto_shard", "PipelinePlan",
           "StageCost", "plan_pipeline", "legal_cut_points",
           "PipelineRunner", "FetchHandle", "PipelineStepError",
           "StagedPipelineRunner"]


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed variable (reference python/paddle/static/input.py data;
    feed ops become jit arguments). dim values of None/-1 mean
    'recompile per fed size' — XLA needs static shapes per compilation."""
    shape = [(-1 if s is None else int(s)) for s in shape]
    # aval for record-time inference substitutes 1 for dynamic dims; the
    # executed program re-lowers against the actually-fed shapes.
    aval_shape = [1 if s == -1 else s for s in shape]
    var = Variable(aval_shape, dtype, name=name, is_data=True,
                   program=default_main_program())
    var.stop_gradient = True
    default_main_program().add_data_var(var)
    return var


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Mark the backward section (reference fluid/backward.py:1288).

    Delta: no grad-op chain is woven into the program — the Executor
    differentiates the lowered forward function with jax.grad at compile
    time. Returns [(param, grad_var)] like the reference.
    """
    program = loss.program or default_main_program()
    if parameter_list:
        params = list(parameter_list)
    else:
        params = [p for p in program.persistable_vars.values()
                  if getattr(p, "is_parameter", False)
                  and getattr(p, "trainable", True)]
    pairs = []
    for p in params:
        g = Variable(p.shape, p.dtype, name=f"{p.name}@GRAD", program=program)
        pairs.append((p, g))
    program.backward_section = (loss, pairs)
    program._version += 1
    return pairs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference fluid/backward.py:1741 calc_gradient. Currently supports
    gradients w.r.t. scope-backed parameters (the dominant reference use);
    grads w.r.t. activations/data are a planned extension."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    scoped = [i for i in inputs if getattr(i, "scope_name", None)]
    if len(scoped) != len(inputs):
        bad = [getattr(i, "name", i) for i in inputs
               if not getattr(i, "scope_name", None)]
        raise NotImplementedError(
            f"static gradients() w.r.t. non-parameter variables {bad} is not "
            "supported yet; use dygraph paddle.grad for activation grads")
    pairs = append_backward(targets[0], parameter_list=scoped)
    return [g for _, g in pairs]


def set_program_state(program, state_dict):
    scope = global_scope()
    import jax.numpy as jnp
    for name, var in program.persistable_vars.items():
        if name in state_dict:
            val = state_dict[name]
            arr = val.numpy() if hasattr(val, "numpy") else np.asarray(val)
            scope.set(name, jnp.asarray(arr, var.aval.dtype))


def save(program, path, protocol=4):
    """Persist program persistables from the scope
    (reference fluid/io.py:620 save_persistables via save ops)."""
    from ..framework.io import save as _save
    scope = global_scope()
    state = {n: np.asarray(scope.get(n))
             for n in program.persistable_vars if scope.has(n)}
    _save(state, path + ".pdparams" if not path.endswith(".pdparams") else path)


def load(program, path, executor=None, var_list=None):
    from ..framework.io import load as _load
    p = path + ".pdparams" if not path.endswith(".pdparams") else path
    state = _load(p, return_numpy=True)
    set_program_state(program, state)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None):
    """Freeze the feed->fetch subgraph to a deployment artifact
    (reference fluid/io.py:1198 save_inference_model). Emits:
      - {prefix}.pdmodel   — pruned Program pickle (fine-tuning parity)
      - {prefix}.pdiparams — persistable state
      - {prefix}.stablehlo + {prefix}.pdinfer.json — serialized jax.export
        module with parameters baked as constants, loadable by
        paddle_tpu.inference.Predictor in a fresh process (the
        OptimizeInferenceProgram pass pipeline collapses into XLA
        compilation of this module).
    """
    import json
    import pickle

    import jax
    import jax.export as jexport
    import jax.numpy as jnp

    if program is None:  # the graph the fetches live in, not the ambient
        program = next((v.program for v in fetch_vars
                        if getattr(v, "program", None) is not None),
                       None) or default_main_program()
    import copy
    prog = copy.copy(program)
    prog._jit_fetch_vars = list(fetch_vars)
    # inference export prunes to the feed->fetch subgraph (reference
    # io.py:1198 save_inference_model): training sections must not survive
    # into the artifact, or the lowered step would demand label feeds
    prog.backward_section = None
    prog.optimizer_section = None
    # through apply_pass so the pass-safety harness (verify-before/after
    # under PADDLE_TPU_VERIFY_PASSES) covers the export path too
    from .passes import apply_pass
    pruned = apply_pass(prog, ["eliminate_dead_ops", "fold_constants"])

    feed_names = [v.name for v in feed_vars]
    # versioned schema format (framework/program_serde.py) with pickle
    # only as a fallback for non-registry kernels — same migration as
    # jit.save
    from ..framework.program_serde import save_program
    try:
        save_program(pruned, path_prefix, feed_names=feed_names)
    except TypeError:
        with open(path_prefix + ".pdmodel", "wb") as f:
            pickle.dump({"program": pruned, "feed_names": feed_names}, f,
                        protocol=4)
    save(program, path_prefix + ".pdiparams")

    # lower the pruned program once and export it with params baked in
    entry = executor._compile(pruned, sorted(feed_names),
                              [v.var_id for v in fetch_vars], False)
    step, persist_names = entry.step_fn, entry.read_names
    scope = global_scope()
    scope_vals = {n: scope.get(n) for n in persist_names}
    order = {n: i for i, n in enumerate(sorted(feed_names))}

    def infer(*feeds):  # feeds arrive in feed_vars order
        by_sorted = tuple(feeds[feed_names.index(n)]
                          for n in sorted(feed_names))
        fetches, _, _ = step(by_sorted, dict(scope_vals), {},
                             jnp.zeros(()), jnp.zeros((), jnp.int32),
                             jax.random.PRNGKey(0))
        return tuple(fetches)

    example = [jnp.zeros(v.aval.shape, v.aval.dtype) for v in feed_vars]
    exported = jexport.export(jax.jit(infer), platforms=("cpu", "tpu"))(
        *example)
    with open(path_prefix + ".stablehlo", "wb") as f:
        f.write(bytes(exported.serialize()))
    meta = {
        "input_names": feed_names,
        "input_dtypes": [str(np.dtype(v.aval.dtype)) for v in feed_vars],
        "output_names": [v.name for v in fetch_vars],
        "format": "stablehlo+jax.export",
    }
    with open(path_prefix + ".pdinfer.json", "w") as f:
        json.dump(meta, f)
    return pruned


def load_inference_model(path_prefix, executor=None):
    """reference fluid/io.py load_inference_model: returns
    [program, feed_names, fetch_vars]. (For the no-Python-model-class
    deployment path use paddle_tpu.inference.Predictor instead.)"""
    import pickle
    with open(path_prefix + ".pdmodel", "rb") as f:
        head = f.read(1)
    if head == b"{":  # versioned JSON schema
        from ..framework.program_serde import load_program
        program, feed_names = load_program(path_prefix)
    else:
        with open(path_prefix + ".pdmodel", "rb") as f:
            payload = pickle.load(f)
        program = payload["program"]
        feed_names = payload["feed_names"]
    load(program, path_prefix + ".pdiparams")
    return [program, feed_names,
            list(getattr(program, "_jit_fetch_vars", []))]


def cpu_places(device_count=None):
    from ..device import CPUPlace
    return [CPUPlace(0)]


def cuda_places(device_ids=None):
    from ..device import TPUPlace
    return [TPUPlace(0)]


class _StaticNN:
    """paddle.static.nn.* builder shims (reference fluid/layers/nn.py
    LayerHelper-based builders). Each creates the layer's parameters in the
    current program and applies it immediately. Names not defined here
    fall through to the fluid.layers v1 adapters (embedding, conv2d,
    pool2d, dropout, sequence_*, ...)."""

    def __getattr__(self, name):
        from ..fluid import layers as _fl
        if hasattr(_fl, name):
            return getattr(_fl, name)
        raise AttributeError(f"paddle.static.nn has no attribute {name!r}")

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None,
           weight_attr=None, bias_attr=None):
        from .. import nn, ops
        in_features = int(np.prod(x.shape[num_flatten_dims:]))
        layer = nn.Linear(in_features, size, weight_attr=weight_attr,
                          bias_attr=bias_attr)
        h = x if x.ndim == 2 else ops.flatten(x, num_flatten_dims)
        out = layer(h)
        if activation:
            out = getattr(nn.functional, activation)(out)
        return out

    @staticmethod
    def batch_norm(input, momentum=0.9, epsilon=1e-5, data_layout="NCHW",  # noqa: A002
                   is_test=False, name=None):
        from .. import nn
        c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
        layer = nn.BatchNorm2D(c, momentum=momentum, epsilon=epsilon)
        layer.training = not is_test
        return layer(input)

    @staticmethod
    def conv2d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
               dilation=1, groups=1, param_attr=None, bias_attr=None,
               name=None):
        from .. import nn
        layer = nn.Conv2D(input.shape[1], num_filters, filter_size,
                          stride=stride, padding=padding, dilation=dilation,
                          groups=groups, weight_attr=param_attr,
                          bias_attr=bias_attr)
        return layer(input)

    @staticmethod
    def embedding(input, size, is_sparse=False, padding_idx=None,  # noqa: A002
                  param_attr=None, dtype="float32"):
        from .. import nn
        layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                             sparse=is_sparse, weight_attr=param_attr)
        return layer(input)


nn = _StaticNN()

from .control_flow import cond, while_loop  # noqa: E402,F401

nn.while_loop = while_loop  # instance attrs: plain functions, unbound
nn.cond = cond
