"""Program pass infrastructure.

Analog of reference framework/ir/ (ir/pass.h Pass::Apply, ~50 registered
passes, graph_viz_pass.cc). Design delta (SURVEY §7.1): operator fusion
belongs to XLA here, so the pass tier owns what the compiler can't see —
whole-Program surgery (dead-op elimination against fetch/persist targets)
and debuggability (DOT dumps, the multi_devices_graph_print_pass analog).
Passes run on the flat SSA op list; registration mirrors ir::PassRegistry.
"""
from __future__ import annotations

import os
from typing import Callable, Dict

from .program import Program, _Ref

__all__ = ["Pass", "register_pass", "get_pass", "apply_pass",
           "eliminate_dead_ops", "fold_constants", "graph_viz",
           "verify_passes_enabled", "set_verify_passes"]

_PASS_REGISTRY: Dict[str, Callable] = {}

# -- pass-safety harness ------------------------------------------------------
# Every pass applied through apply_pass() runs verify-before/verify-after
# (static/verifier.py) when enabled, so a pass that corrupts def-use
# chains fails AT THE REWRITE with a ProgramVerifyError naming the pass —
# not as a wrong number at Executor.run time. Controlled by the
# PADDLE_TPU_VERIFY_PASSES env var (default on under pytest via
# tests/conftest.py; off in production, where passes are trusted and the
# check is pure overhead) or set_verify_passes().

_verify_override = None


def verify_passes_enabled() -> bool:
    if _verify_override is not None:
        return _verify_override
    return os.environ.get("PADDLE_TPU_VERIFY_PASSES", "0").strip().lower() \
        not in ("0", "false", "off", "")


def set_verify_passes(enabled):
    """Force the harness on/off from code (None restores the env-var
    default); returns the previous override."""
    global _verify_override
    old = _verify_override
    _verify_override = None if enabled is None else bool(enabled)
    return old


class Pass:
    """Base pass (reference ir/pass.h). Subclass and implement apply()."""

    name = "pass"

    def apply(self, program: Program) -> Program:
        raise NotImplementedError

    def __call__(self, program):
        return self.apply(program)


def register_pass(name):
    def deco(fn):
        _PASS_REGISTRY[name] = fn
        return fn
    return deco


def get_pass(name):
    if name not in _PASS_REGISTRY:
        raise KeyError(f"no pass named {name!r}; have "
                       f"{sorted(_PASS_REGISTRY)}")
    return _PASS_REGISTRY[name]


def apply_pass(program, names):
    if isinstance(names, str):
        names = [names]
    verify = verify_passes_enabled()
    if verify:
        from .verifier import verify_program
        verify_program(program)  # a pre-broken input is the CALLER's bug
    for idx, n in enumerate(names):
        program = get_pass(n)(program)
        if not isinstance(program, Program):
            # analysis passes (graph_viz) return artifacts, not Programs:
            # legal only as the LAST pass — feeding an artifact into the
            # next pass would crash far from the cause
            if idx != len(names) - 1:
                raise TypeError(
                    f"pass '{n}' returned {type(program).__name__}, not a "
                    f"Program — analysis passes must come last in the "
                    f"chain {list(names)}")
            break
        if verify:
            from .verifier import verify_program
            verify_program(program, pass_name=n)
    if isinstance(program, Program):
        # PADDLE_TPU_VERIFY_SPMD (default off, mirroring the env-var
        # contract above): the rewritten program's declared shardings
        # must still analyze clean — a pass that reorders or rewires a
        # sharded matmul fails HERE with a named SpmdLintError, not as
        # an unplanned all-gather after jit
        from .spmd_analyzer import maybe_verify_spmd
        maybe_verify_spmd(program)
    return program


def _live_ids(program):
    """Roots every op must ultimately feed: persistables, state writes,
    backward/optimizer section variables, jit fetches."""
    roots = set(program.persist_ids.values()) | set(
        program.state_writes.values())
    if program.backward_section is not None:
        loss_var, pairs = program.backward_section
        roots.add(loss_var.var_id)
        for p, g in pairs:
            roots.add(g.var_id)
    for v in getattr(program, "_jit_fetch_vars", []) or []:
        roots.add(v.var_id)
    return roots


@register_pass("eliminate_dead_ops")
def eliminate_dead_ops(program, extra_live=()):
    """Drop ops whose outputs reach no fetch/persist/backward root
    (reference memory_optimize_pass/eager_deletion spirit at the
    Program level). Returns a pruned clone; the original is untouched."""
    live = _live_ids(program) | set(extra_live)
    kept = []
    for op in reversed(program.ops):
        if any(oid in live for oid in op.out_ids):
            kept.append(op)
            for x in op.flat:
                if isinstance(x, _Ref):
                    live.add(x.var_id)
    kept.reverse()
    import copy
    new = copy.copy(program)
    new.ops = kept
    new._version = getattr(program, "_version", 0) + 1
    return new


@register_pass("graph_viz")
def graph_viz(program, path=None):
    """DOT dump (reference ir/graph_viz_pass.cc). Returns the DOT text;
    writes it when `path` is given. Ops are boxes, variables ellipses."""
    lines = ["digraph program {", "  rankdir=TB;",
             '  node [fontsize=10];']
    var_names = {}
    for name, v in list(program.data_vars.items()) \
            + list(program.persistable_vars.items()):
        var_names[v.var_id] = name
    for i, op in enumerate(program.ops):
        lines.append(f'  op{i} [shape=box,style=filled,fillcolor='
                     f'lightgray,label="{op.name}"];')
        for x in op.flat:
            if isinstance(x, _Ref):
                vid = x.var_id
                label = var_names.get(vid, x.name or f"v{vid}")
                lines.append(f'  v{vid} [shape=ellipse,label="{label}"];')
                lines.append(f"  v{vid} -> op{i};")
        for oid in op.out_ids:
            lines.append(f'  v{oid} [shape=ellipse,label='
                         f'"{var_names.get(oid, f"v{oid}")}"];')
            lines.append(f"  op{i} -> v{oid};")
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot


_IMPURE_MARKERS = ("rand", "normal", "uniform", "bernoulli", "multinomial",
                   "poisson", "dropout", "gumbel", "seed", "shuffle",
                   "sampling", "noise", "exponential", "rrelu", "gamma",
                   "binomial")


def _is_pure(op):
    return not any(m in op.name for m in _IMPURE_MARKERS)


# NOTE on constant folding: it happens at TRACE time by construction —
# ops whose inputs are all literals never touch a Variable, so record_op
# executes them eagerly and their results enter the program as baked
# constants (tests/test_passes2.py asserts this design property). The
# pass tier therefore owns what tracing can't see: CSE below, dead-op
# elimination, and visualization.


@register_pass("cse")
def common_subexpression_elimination(program):
    """Merge ops with identical (name, inputs, kwargs) into one
    (reference ir/ identity-graph dedup passes): later duplicates' outputs
    are rewired to the first occurrence's variables. Impure ops are never
    merged."""
    import copy

    def sig(op, remap):
        parts = [op.name, op.n_args]
        for x in op.flat:
            if isinstance(x, _Ref):
                parts.append(("ref", remap.get(x.var_id, x.var_id)))
            else:
                try:
                    parts.append(("lit", np.asarray(x).tobytes()
                                  if hasattr(x, "shape") else x))
                except Exception:
                    return None
        return tuple(str(p) for p in parts)

    import numpy as np
    roots = _live_ids(program)
    seen = {}
    remap = {}
    new_ops = []
    for op in program.ops:
        if not _is_pure(op):
            new_ops.append(op)
            continue
        s = sig(op, remap)
        dup = (s is not None and s in seen
               and not any(oid in roots for oid in op.out_ids))
        if dup:
            for mine, theirs in zip(op.out_ids, seen[s]):
                remap[mine] = theirs
            continue
        op2 = copy.copy(op)
        # rewrite remapped input refs
        op2.flat = [x if not isinstance(x, _Ref) or x.var_id not in remap
                    else _remapped_ref(x, remap[x.var_id])
                    for x in op.flat]
        if s is not None and s not in seen:
            seen[s] = list(op.out_ids)
        new_ops.append(op2)
    new = copy.copy(program)
    new.ops = new_ops
    new._cse_remap = dict(remap)
    new._version = getattr(program, "_version", 0) + 1
    return new


def _remapped_ref(ref, new_id):
    import copy
    r = copy.copy(ref)
    r.var_id = new_id
    return r


@register_pass("fold_constants")
def fold_constants(program, max_bytes=1 << 24):
    """Evaluate ops whose every input is a compile-time constant and bake
    their results (reference ir constant_folding_pass; VERDICT r04 weak
    #8). Freshly-traced programs rarely need it — record-time eager
    evaluation already computes const-only expressions during tracing —
    but deserialized artifacts (older exporters, hand-built Programs,
    transpiler output) can carry const chains as recorded ops; this
    collapses them before the Executor lowers or an artifact re-exports.

    Never folds: nondeterministic ops, control-flow blocks, fetch/state
    targets, or results larger than max_bytes. Returns a rewritten clone.
    """
    import copy

    import jax.numpy as jnp
    import jax.tree_util as jtu
    import numpy as np

    # same roots the other passes protect (incl. backward section), plus
    # the same purity oracle CSE uses — one marker set, no divergence
    protected = _live_ids(program)
    protected |= set(program.persist_ids.values())

    const_env = {}
    new_ops = []
    for op in program.ops:
        fn = op.fn
        name = getattr(fn, "op_name", None)
        refs = [x for x in op.flat if isinstance(x, _Ref)]
        can_fold = (name is not None and _is_pure(op)
                    and all(r.var_id in const_env for r in refs)
                    and not any(oid in protected for oid in op.out_ids))
        if can_fold:
            vals = [const_env[x.var_id] if isinstance(x, _Ref) else x
                    for x in op.flat]
            kw = jtu.tree_unflatten(op.kw_tree, vals[op.n_args:])
            try:
                out = fn(*vals[:op.n_args], **kw)
            except Exception:
                out = None  # keep the op; refs substitute below
            if out is not None:
                outs = (list(out) if isinstance(out, (tuple, list))
                        else [out])
                if sum(np.asarray(o).nbytes for o in outs) <= max_bytes:
                    for oid, v in zip(op.out_ids, outs):
                        const_env[oid] = jnp.asarray(v)
                    continue  # op folded away entirely
        # unfolded op: any input produced by a folded op becomes a
        # literal, so no dangling _Ref survives
        if any(isinstance(x, _Ref) and x.var_id in const_env
               for x in op.flat):
            op2 = copy.copy(op)
            op2.flat = [const_env[x.var_id]
                        if isinstance(x, _Ref) and x.var_id in const_env
                        else x for x in op.flat]
            op = op2
        new_ops.append(op)
    new = copy.copy(program)
    new.ops = new_ops
    new._version = getattr(program, "_version", 0) + 1
    return new
