"""Python side of the C-ABI trainer (reference `train/demo/demo_trainer.cc`
— the C++ train API: load a saved ProgramDesc, run startup then step the
main program with an Executor; N33 in SURVEY §2.1).

Artifact format (`save_train_program`): one pickle holding the full
training Program (forward + backward + optimizer sections) and a snapshot
of its persistable scope values, so a C host can resume training without
any Python authoring step.
"""
from __future__ import annotations

import pickle

import numpy as np

__all__ = ["save_train_program", "create", "run_step", "save_params"]

_DTYPES = {0: np.float32, 1: np.int32, 2: np.int64}


def save_train_program(program, path, scope=None):
    """Persist a TRAINING program (unpruned: backward + optimizer sections
    ride along) plus current persistable values."""
    from .program import global_scope
    scope = scope or global_scope()
    state = {}
    for name in program.persistable_vars:
        if scope.has(name):
            state[name] = np.asarray(scope.get(name))
    with open(path, "wb") as f:
        pickle.dump({"program": program, "state": state}, f, protocol=4)
    return path


def create(path):
    """Load a train artifact into a fresh (program, executor, scope)."""
    from .executor import Executor
    from .program import Scope
    with open(path, "rb") as f:
        payload = pickle.load(f)  # noqa: S301 — local artifact
    program = payload["program"]
    scope = Scope()
    import jax.numpy as jnp
    for name, val in payload["state"].items():
        scope.set(name, jnp.asarray(val))
    return {"program": program, "exe": Executor(), "scope": scope,
            "feed_names": list(program.data_vars)}


def feed_names(handle):
    return list(handle["feed_names"])


def run_step(handle, inputs, fetch_name=None):
    """inputs: list of (memoryview, dtype_code, shape) in feed_names
    order. Returns the mean of the first fetch (the loss) as float."""
    feed = {}
    for name, (mv, code, shape) in zip(handle["feed_names"], inputs):
        feed[name] = np.frombuffer(mv, dtype=_DTYPES[int(code)]).reshape(
            tuple(int(s) for s in shape))
    program = handle["program"]
    if fetch_name:
        fetch = [fetch_name]
    else:
        bw = getattr(program, "backward_section", None)
        if bw is None:
            raise ValueError("train program has no backward section")
        fetch = [bw[0]]
    outs = handle["exe"].run(program, feed=feed, fetch_list=fetch,
                             scope=handle["scope"])
    return float(np.asarray(outs[0]).mean())


def save_params(handle, path):
    state = {n: np.asarray(handle["scope"].get(n))
             for n in handle["program"].persistable_vars
             if handle["scope"].has(n)}
    from ..framework.io import save as _save
    _save(state, path if path.endswith(".pdparams") else path + ".pdparams")
    return path
