"""Static-graph control flow: while_loop / cond with Program sub-blocks.

Analog of the reference's control-flow operators
(operators/controlflow/while_op.cc, conditional_block_op.cc — ops that OWN
sub-blocks and run them with a nested executor;
fluid/layers/control_flow.py while_loop :1096, cond :2334).

TPU-native design delta: the reference interprets sub-blocks op-by-op at
runtime with scope copy-in/copy-out. Here a sub-block is a traced op list
(SubBlock) closed over by a single recorded op whose kernel lowers to
`lax.while_loop` / `lax.cond` — XLA compiles the loop as a native HLO
While/Conditional with the sub-block fused inside, no interpreter at
runtime. Free outer variables are promoted to explicit op inputs (the
reference's scope-parent-chain lookup, made SSA).

Shape invariants are checked at build time (lax.while_loop requires carry
avals fixed), matching the reference's sub-block var shape checks.

Differentiation: `cond` differentiates (lax.cond has a vjp). A `while_loop`
with data-dependent trip count has no reverse-mode derivative in XLA —
pass `maximum_trip_count` to lower onto a masked `lax.scan`, which is
differentiable (the reference's WhileGrad records per-iteration scopes for
the same reason: bounded storage).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
from jax import lax

from .program import (Program, Variable, _Ref, default_main_program,
                      force_program, in_static_mode, program_guard)

__all__ = ["while_loop", "cond", "SubBlock"]


class SubBlock:
    """A picklable traced sub-program: op list + placeholder/free/output
    ids. The runtime analog of the reference's BlockDesc owned by a
    control-flow op."""

    def __init__(self, ops, in_ids, free_ids, out_ids):
        self.ops = list(ops)
        self.in_ids = list(in_ids)
        self.free_ids = list(free_ids)
        self.out_ids = list(out_ids)

    def run(self, carry_vals, free_vals):
        env = dict(zip(self.in_ids, carry_vals))
        env.update(zip(self.free_ids, free_vals))
        for op in self.ops:
            vals = [env[x.var_id] if isinstance(x, _Ref) else x
                    for x in op.flat]
            kw = jtu.tree_unflatten(op.kw_tree, vals[op.n_args:])
            out = op.fn(*vals[:op.n_args], **kw)
            if len(op.out_ids) == 1 and not isinstance(out, (tuple, list)):
                env[op.out_ids[0]] = out
            else:
                for oid, v in zip(op.out_ids, out):
                    env[oid] = v
        return [env[i] for i in self.out_ids]


def _aval(v):
    """Shape/dtype of a loop var: symbolic Variable or eager initial value
    (constants like ops.zeros run eagerly even in static mode — they are
    legitimate carry initials, baked as op inputs)."""
    if isinstance(v, Variable):
        return v.aval
    import numpy as np
    from ..core.tensor import Tensor
    raw = v._value if isinstance(v, Tensor) else np.asarray(v)
    return jax.ShapeDtypeStruct(tuple(raw.shape), raw.dtype)


def _parent_programs():
    """Per-thread stack of programs enclosing the current sub-block trace
    (the SSA form of the reference's scope parent chain, scope.h FindVar);
    lives on _StaticState beside `forced` so concurrent static builds
    stay isolated."""
    from .program import _state
    return _state.cf_parents


def _trace_subblock(fn, arg_vars, name):
    """Trace `fn` over fresh placeholders into its own Program; returns
    (ops, placeholder_ids, out_vars, free_ids)."""
    sub = Program(name)
    ph = [Variable(_aval(v).shape, _aval(v).dtype, program=sub)
          for v in arg_vars]
    sub._cf_placeholders = ph
    parents = _parent_programs()
    parents.append(default_main_program())
    try:
        with program_guard(sub), force_program(sub):
            out = fn(*ph)
    finally:
        parents.pop()
    outs = list(out) if isinstance(out, (tuple, list)) else [out]
    for o in outs:
        if not isinstance(o, Variable):
            raise TypeError(
                f"{name}: sub-block functions must return static Variables "
                f"(got {type(o).__name__}); return values must be computed "
                "from the loop variables / captured Variables")
    produced = {oid for op in sub.ops for oid in op.out_ids}
    produced |= {p.var_id for p in ph}
    seen = {}
    for op in sub.ops:
        for x in op.flat:
            if isinstance(x, _Ref) and x.var_id not in produced:
                seen[x.var_id] = x.name
    # an output may be a passthrough of a placeholder or outer var
    for o in outs:
        if o.var_id not in produced:
            seen[o.var_id] = o.name
    return sub.ops, [p.var_id for p in ph], outs, seen


def _resolve_free(free_map):
    """free var_id -> the actual outer Variable objects (promoted to op
    inputs; the SSA form of the reference's parent-scope lookup). Searches
    the current program AND every enclosing sub-block trace — a nested
    cond/while may capture a grandparent's variable or an enclosing
    block's placeholder."""
    progs = [default_main_program()] + list(reversed(_parent_programs()))
    by_id = {}
    for main in progs:
        for v in main.data_vars.values():
            by_id.setdefault(v.var_id, v)
        for v in main.persistable_vars.values():
            by_id.setdefault(v.var_id, v)
        for op in main.ops:
            for v in op.out_vars:
                by_id.setdefault(v.var_id, v)
        for ph in getattr(main, "_cf_placeholders", ()):
            by_id.setdefault(ph.var_id, ph)
    missing = [name for vid, name in free_map.items() if vid not in by_id]
    if missing:
        raise ValueError(
            f"control-flow sub-block captured variables not visible in the "
            f"current program: {missing}; pass them through loop_vars or "
            "build them in the same program")
    return [by_id[vid] for vid in free_map]


def _check_scalar_bool(var, what):
    size = 1
    for s in var.aval.shape:
        size *= s
    if size != 1:
        raise ValueError(
            f"{what} must produce a scalar boolean, got shape "
            f"{tuple(var.aval.shape)}")


class _WhileFn:
    """Kernel of a recorded while op: lax.while_loop over SubBlocks
    (pickles structurally with the Program — no registry entry needed)."""

    def __init__(self, cond_block, body_block, n_loop, max_trip=None):
        self.cond_block = cond_block
        self.body_block = body_block
        self.n_loop = n_loop
        self.max_trip = max_trip

    def __call__(self, *vals):
        init = tuple(vals[:self.n_loop])
        free = tuple(vals[self.n_loop:])

        def c(carry):
            r = self.cond_block.run(list(carry), free)[0]
            return jnp.reshape(r, ()).astype(bool)

        def b(carry):
            outs = self.body_block.run(list(carry), free)
            return tuple(jnp.asarray(o).astype(i.dtype).reshape(i.shape)
                         for o, i in zip(outs, carry))

        if self.max_trip is None:
            return lax.while_loop(c, b, init)

        # bounded, differentiable form: scan max_trip steps, freeze the
        # carry once the predicate goes false (reference WhileGrad's
        # bounded per-iteration storage, made explicit)
        def step(carry, _):
            alive = c(carry)
            new = b(carry)
            keep = tuple(jnp.where(alive, n, o) for n, o in zip(new, carry))
            return keep, None

        final, _ = lax.scan(step, init, None, length=self.max_trip)
        return final


class _CondFn:
    def __init__(self, true_block, false_block):
        self.true_block = true_block
        self.false_block = false_block

    def __call__(self, pred, *free):
        p = jnp.reshape(pred, ()).astype(bool)

        def t(fv):
            return tuple(self.true_block.run([], list(fv)))

        def f(fv):
            return tuple(self.false_block.run([], list(fv)))

        return lax.cond(p, t, f, tuple(free))


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None,
               maximum_trip_count=None):
    """reference fluid/layers/control_flow.py:1096 while_loop.

    Static mode: records ONE op lowering to lax.while_loop (or a masked
    lax.scan when `maximum_trip_count` is given — required if gradients
    must flow through the loop). Dygraph: a plain Python loop.
    """
    loop_vars = list(loop_vars)
    if not loop_vars:
        raise ValueError("loop_vars must be non-empty")
    if not (in_static_mode() and any(isinstance(v, Variable)
                                     for v in loop_vars)):
        import numpy as np

        def truthy(x):
            return bool(np.asarray(x.numpy() if hasattr(x, "numpy") else x))

        vals = loop_vars
        while truthy(cond_fn(*vals)):
            out = body_fn(*vals)
            vals = list(out) if isinstance(out, (tuple, list)) else [out]
        return vals

    c_ops, c_ph, c_outs, c_free = _trace_subblock(cond_fn, loop_vars,
                                                  "while_cond")
    if len(c_outs) != 1:
        raise ValueError("while_loop cond must return exactly one value")
    _check_scalar_bool(c_outs[0], "while_loop cond")
    b_ops, b_ph, b_outs, b_free = _trace_subblock(body_fn, loop_vars,
                                                  "while_body")
    if len(b_outs) != len(loop_vars):
        raise ValueError(
            f"while_loop body returned {len(b_outs)} values for "
            f"{len(loop_vars)} loop_vars")
    for i, (lv, bo) in enumerate(zip(loop_vars, b_outs)):
        la = _aval(lv)
        if tuple(bo.aval.shape) != tuple(la.shape) \
                or bo.aval.dtype != la.dtype:
            raise ValueError(
                f"while_loop shape invariant violated for loop_var {i}: "
                f"carry is {tuple(la.shape)}/{la.dtype} but body "
                f"returns {tuple(bo.aval.shape)}/{bo.aval.dtype} (XLA "
                "While requires a fixed carry shape — pad or restructure)")

    free_map = dict(c_free)
    free_map.update(b_free)
    free_vars = _resolve_free(free_map)
    free_ids = list(free_map)
    fn = _WhileFn(SubBlock(c_ops, c_ph, free_ids, [c_outs[0].var_id]),
                  SubBlock(b_ops, b_ph, free_ids,
                           [o.var_id for o in b_outs]),
                  len(loop_vars), maximum_trip_count)
    from ..core.tape import record_op
    out = record_op(fn, tuple(loop_vars) + tuple(free_vars), {},
                    "while_loop")
    return list(out) if isinstance(out, (tuple, list)) else [out]


def cond(pred, true_fn=None, false_fn=None, name=None):
    """reference fluid/layers/control_flow.py:2334 cond."""
    if true_fn is None or false_fn is None:
        raise ValueError("cond requires both true_fn and false_fn (they "
                         "must return the same structure)")
    if not (in_static_mode() and isinstance(pred, Variable)):
        import numpy as np
        p = pred.numpy() if hasattr(pred, "numpy") else pred
        return true_fn() if bool(np.asarray(p)) else false_fn()

    _check_scalar_bool(pred, "cond pred")
    t_ops, _, t_outs, t_free = _trace_subblock(lambda: true_fn(), [],
                                               "cond_true")
    f_ops, _, f_outs, f_free = _trace_subblock(lambda: false_fn(), [],
                                               "cond_false")
    if len(t_outs) != len(f_outs):
        raise ValueError(
            f"cond branches return different numbers of values: "
            f"{len(t_outs)} vs {len(f_outs)}")
    for i, (t, f) in enumerate(zip(t_outs, f_outs)):
        if tuple(t.aval.shape) != tuple(f.aval.shape) \
                or t.aval.dtype != f.aval.dtype:
            raise ValueError(
                f"cond branch output {i} mismatch: true is "
                f"{tuple(t.aval.shape)}/{t.aval.dtype}, false is "
                f"{tuple(f.aval.shape)}/{f.aval.dtype}")

    free_map = dict(t_free)
    free_map.update(f_free)
    free_vars = _resolve_free(free_map)
    free_ids = list(free_map)
    fn = _CondFn(SubBlock(t_ops, [], free_ids,
                          [o.var_id for o in t_outs]),
                 SubBlock(f_ops, [], free_ids,
                          [o.var_id for o in f_outs]))
    from ..core.tape import record_op
    out = record_op(fn, (pred,) + tuple(free_vars), {}, "cond")
    if isinstance(out, (tuple, list)) and len(out) == 1:
        return out[0]
    return out
