"""Static-graph Executor.

Analog of reference framework/executor.cc (Executor::Run :179, Prepare :375,
hot loop :473) + python/paddle/fluid/executor.py (:914 run, :1110 _run_impl
with program caching). Design delta: `Prepare` = lower the whole Program to
one pure function; `Run` = call the jitted function once. Feed/fetch-op
injection, per-op kernel choice, scope var churn and GC all disappear.
The compiled step carries (feeds, scope, optimizer slots) -> (fetches,
scope', slots'), with scope/slots donated.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .program import (Program, Variable, _Ref, default_main_program,
                      default_startup_program, global_scope, in_static_mode)

__all__ = ["Executor", "CompiledProgram", "BuildStrategy", "ExecutionStrategy"]


def _resolve(arg, env):
    if isinstance(arg, _Ref):
        return env[arg.var_id]
    return arg


def _convert_feed(val, aval, sharding=None):
    """One feed value -> device array of the program's declared dtype.

    A value that is ALREADY a jax array of the right dtype passes through
    untouched — the old unconditional `jnp.asarray(np.asarray(val))`
    forced a device->host->device round trip on every step for callers
    that keep their batches device-resident (bench loops, the pipeline
    prefetcher feeding its own output back)."""
    from ..core.tensor import Tensor
    if isinstance(val, Tensor) and val._value is not None:
        val = val._value
    if isinstance(val, jax.Array) and val.dtype == aval.dtype:
        return val  # jit re-shards if the placement disagrees
    arr = np.asarray(val)
    if sharding is not None:
        if arr.dtype != aval.dtype:
            arr = arr.astype(aval.dtype)
        return jax.device_put(arr, sharding)
    return jnp.asarray(arr, aval.dtype)


def _dp_shardings():
    """(mesh, replicated, batch) NamedShardings when a dp mesh with >1
    device is active, else None — shared by _compile and the pipeline
    prefetcher so both put feeds where the compiled step expects them."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..distributed import mesh as mesh_mod
    mesh = mesh_mod.auto_mesh()
    if "dp" not in mesh.axis_names or mesh.shape["dp"] <= 1:
        return None
    return mesh, NamedSharding(mesh, P()), NamedSharding(mesh, P("dp"))


def make_scan_step(step_fn):
    """lax.scan over one compiled step: the scan-fused K-batch megastep
    body. ONE definition shared by _CompiledEntry.scan_jitted (production)
    and tools/hlo_evidence.py (the lowered proof), so the evidence is for
    the computation the runtime actually executes."""

    def scan_step(feeds, scope_vals, slots, lrs, ts, keys):
        def body(carry, x):
            sv, sl = carry
            feed_tuple, lr, t, key = x
            fetches, new_sv, new_sl = step_fn(feed_tuple, sv, sl, lr, t,
                                              key)
            return (new_sv, new_sl), fetches

        (new_sv, new_sl), fetches = jax.lax.scan(
            body, (scope_vals, slots), (feeds, lrs, ts, keys))
        return fetches, new_sv, new_sl

    return scan_step


class _CompiledEntry:
    """One lowered program: the jitted step, the raw (unjitted) step for
    scan fusion, and the host-side metadata the run loops need."""

    __slots__ = ("jitted", "step_fn", "feed_names", "fetch_ids",
                 "read_names", "opt", "opt_pnames", "amp_init", "donate",
                 "dp", "_scan_jitted")

    def __init__(self, jitted, step_fn, feed_names, fetch_ids, read_names,
                 opt, opt_pnames, amp_init, donate, dp):
        self.jitted = jitted
        self.step_fn = step_fn
        self.feed_names = list(feed_names)   # sorted; feed-tuple order
        self.fetch_ids = list(fetch_ids)
        self.read_names = list(read_names)
        self.opt = opt
        self.opt_pnames = list(opt_pnames)
        self.amp_init = amp_init
        self.donate = donate
        self.dp = dp                          # None | (mesh, repl, batch)
        self._scan_jitted = None

    def scan_jitted(self):
        """jit(lax.scan(step)) — ONE dispatch runs K stacked batches
        (K is implicit in the stacked leading dim; jax re-specializes per
        K/shape). Bitwise-equal to K serial steps: the scanned body IS
        the serial step function, and the per-step (lr, t, key) stream is
        precomputed on host exactly as the serial loop would."""
        if self._scan_jitted is None:
            scan_step = make_scan_step(self.step_fn)
            donate = tuple(d for d in self.donate)  # (1, 2) or ()
            if self.dp is None:
                self._scan_jitted = jax.jit(scan_step,
                                            donate_argnums=donate)
            else:
                from jax.sharding import NamedSharding, PartitionSpec as P
                mesh, repl, _batch = self.dp
                scan_batch = NamedSharding(mesh, P(None, "dp"))
                self._scan_jitted = jax.jit(
                    scan_step,
                    in_shardings=(
                        (scan_batch,) * len(self.feed_names),
                        {n: repl for n in self.read_names},
                        None, repl, repl, repl),
                    donate_argnums=donate)
        return self._scan_jitted


class BuildStrategy:
    """Parity shim for fluid.BuildStrategy (details/build_strategy.cc):
    XLA owns fusion/memory decisions, so knobs are accepted and recorded."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True
        self.reduce_strategy = None


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        # async hot-loop knobs (None = inherit the FLAGS_executor_*
        # defaults; see docs/async_executor.md)
        self.max_inflight = None      # FLAGS_executor_max_inflight
        self.scan_fuse_steps = None   # FLAGS_executor_scan_steps


class CompiledProgram:
    """reference fluid/compiler.py CompiledProgram (:88). with_data_parallel
    (:164) marks the batch axis for 'dp' mesh sharding instead of cloning
    the program per device (parallel_executor.cc:606)."""

    def __init__(self, program, build_strategy=None, exec_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()
        self.exec_strategy = exec_strategy or ExecutionStrategy()
        self.data_parallel = False
        self.loss_name = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        self.data_parallel = True
        self.loss_name = loss_name
        if build_strategy is not None:
            self.build_strategy = build_strategy
        if exec_strategy is not None:
            self.exec_strategy = exec_strategy
        return self


class Executor:
    def __init__(self, place=None):
        self.place = place
        self._cache: "OrderedDict" = OrderedDict()

    # -- compiled-entry cache ------------------------------------------------
    def _prepare(self, program, feed_vals, fetch_list, data_parallel):
        """Resolve fetches and return the cached _CompiledEntry, compiling
        on miss. Keyed on program.uid (NOT id(program): a garbage-collected
        Program whose id the allocator reuses for a new Program would hit
        a stale compiled entry — the AMP state tags learned this first),
        with an LRU bound so long-lived executors serving many programs
        don't hold every lowering forever."""
        fetch_ids = []
        for f in fetch_list:
            if isinstance(f, str):
                matches = [v for v in program.list_vars() if v.name == f]
                if not matches:
                    raise KeyError(f"fetch '{f}' not found in program")
                fetch_ids.append(matches[0].var_id)
            else:
                fetch_ids.append(f.var_id)

        key = (program.uid, program._version, tuple(sorted(feed_vals)),
               tuple(v.shape for _, v in sorted(feed_vals.items())),
               tuple(fetch_ids), data_parallel)
        entry = self._cache.get(key)
        if entry is not None:
            self._cache.move_to_end(key)
            return entry
        from ..core import flags as _flags0
        from ..core import monitor as _monitor
        from ..core import trace as _trace
        # strategy.auto_shard (fleet.distributed_optimizer): derive the
        # PartitionSpec plan for this program at compile, BEFORE the
        # verify/estimate hooks below read program.spmd_*_specs. A plan
        # carrying pipeline stage cuts also pins the stage assignment
        # (program._pipeline_stages) here — stages resolve before the
        # VERIFY_SPMD hook, so hook findings and the stage table always
        # describe the same plan.
        if getattr(program, "_auto_shard", None) is not None \
                and (getattr(program, "spmd_param_specs", None) is None
                     or getattr(program, "_pipeline_stages", None) is None
                     and getattr((program._auto_shard or {}).get("plan"),
                                 "pipeline", None) is not None):
            from .spmd_planner import resolve_auto_shard
            resolve_auto_shard(program)
        # PADDLE_TPU_VERIFY_SPMD: sharding findings (unbound axis,
        # non-divisible dim, implied reshard, ...) fail HERE — before
        # jit tracing, where they would surface as silent replication
        # or an opaque XLA error (mirrors PADDLE_TPU_VERIFY_PASSES)
        from .spmd_analyzer import maybe_verify_spmd
        spmd_rep = maybe_verify_spmd(program)
        # always-on span (absorbs the old RecordEvent annotation): a
        # compile on the hot path is exactly what a flight-recorder dump
        # needs to show
        with _trace.span("executor/lower_program", program=program.name,
                         ops=len(program.ops),
                         data_parallel=bool(data_parallel)):
            entry = self._compile(program, sorted(feed_vals), fetch_ids,
                                  data_parallel)
        self._cache[key] = entry
        cap = max(1, int(_flags0.flag("FLAGS_executor_cache_size")))
        while len(self._cache) > cap:
            self._cache.popitem(last=False)
            _monitor.stat_add("executor/cache_evictions")
        _monitor.stat_add("executor/lowerings")
        if _flags0.flag("FLAGS_log_memory_estimate"):
            from .shape_infer import analyze_memory
            est = analyze_memory(program)
            _monitor.stat_set("executor/estimated_peak_bytes",
                              est["peak_bytes"])
        # spmd_rep already published the gauges when the strict hook
        # ran — don't re-walk the program for the same numbers
        if _flags0.flag("FLAGS_log_spmd_estimate") and spmd_rep is None:
            from ..distributed import mesh as _mesh_mod
            if _mesh_mod.get_mesh() is not None:
                from .spmd_analyzer import analyze_program
                analyze_program(
                    program,
                    param_specs=getattr(program, "spmd_param_specs",
                                        None),
                    data_specs=getattr(program, "spmd_data_specs",
                                       None)).publish()
        return entry

    @staticmethod
    def _convert_feeds(program, feed):
        feed_vals = {}
        for name, val in feed.items():
            var = program.data_vars.get(name)
            if var is None:
                raise KeyError(f"feed '{name}' is not a data variable of the "
                               f"program (have {list(program.data_vars)})")
            feed_vals[name] = _convert_feed(val, var.aval)
        return feed_vals

    # -- public API ----------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True,
            return_handles=False):
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        data_parallel = False
        if isinstance(program, CompiledProgram):
            data_parallel = program.data_parallel
            program = program.program
        if program is None:
            program = default_main_program()
        if program is default_startup_program() or program.name == "startup":
            # initializers already ran eagerly at parameter creation
            # (reference runs startup-program init ops here)
            return []
        scope = scope or global_scope()

        feed_vals = self._convert_feeds(program, feed)
        entry = self._prepare(program, feed_vals, fetch_list, data_parallel)

        for n, v0 in (entry.amp_init or {}).items():
            if not scope.has(n):
                scope.set(n, v0)
        scope_vals = {n: scope.get(n) for n in entry.read_names}
        slots, lr, t = {}, jnp.zeros(()), jnp.zeros((), jnp.int32)
        opt = entry.opt
        if opt is not None:
            opt._ensure_slots({n: scope_vals[n] for n in entry.opt_pnames})
            slots = {n: opt._slots[n] for n in entry.opt_pnames}
            opt._step_count += 1
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            t = jnp.asarray(opt._step_count, jnp.int32)

        from ..core import rng as _rng
        from ..core import monitor as _monitor
        from ..core import trace as _trace
        _monitor.stat_add("executor/runs")
        with _trace.span("executor/run_step", program=program.name):
            fetches, new_scope, new_slots = entry.jitted(
                tuple(feed_vals[n] for n in entry.feed_names), scope_vals,
                slots, lr, t, _rng.next_key())

        from ..core import flags as _flags
        if _flags.flag("FLAGS_check_nan_inf"):
            # sweep BEFORE the write-back (never commit NaN state), in
            # return_handles mode too — the nan check is a debugging
            # mode and a param-only NaN would otherwise slip past the
            # per-fetch sweep in FetchHandle.numpy()
            from ..core.numeric_check import sweep
            sweep({"fetches": list(fetches), "scope": new_scope},
                  "Executor.run step")

        for n, v in new_scope.items():
            scope.set(n, v)
        if opt is not None:
            opt._slots.update(new_slots)

        if return_handles:
            # async mode: dispatch is already queued; hand back lazy
            # handles so the caller materializes at its own boundaries
            from .pipeline_runner import FetchHandle
            idx = int(_monitor.stat_get("executor/runs")) - 1
            return [FetchHandle(f, idx) for f in fetches]

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    # -- dataset/trainer path ------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           ps_config=None, start_batch=0):
        """The industrial hot path (reference executor.py:1425
        _run_from_dataset -> framework/executor.cc:165 RunFromDataset ->
        HogwildWorker::TrainFiles hogwild_worker.cc:196).

        Design delta: the reference spawns one DeviceWorker thread per
        card, each looping ops over channel batches; on the
        single-controller runtime ONE loop drives the whole mesh — the
        compiled step is already data-parallel over the devices, and the
        dataset's thread pool keeps the parse ahead of the step.

        ps_config enables the Downpour loop (reference
        framework/downpour_worker.cc: pull sparse rows before each batch,
        run, push sparse grads after):
          {"client": PSClient, "communicator": Communicator | None,
           "sparse": [{"param": var_name, "slot": feed_slot,
                       "table": table_name}]}
        PS-managed params are pulled into the scope for the batch's ids,
        their grads are fetched and pushed as (ids, rows) pairs, and they
        are EXCLUDED from the program's local optimizer section — the
        server's accessor owns the update rule.

        ps_config {"mode": "online", ...} switches to the CONTINUOUS
        Downpour variant (docs/online_learning.md): params keep the
        LOCAL optimizer and accumulated deltas flow to a "geo_sparse"
        table via replay-keyed push_sparse_delta every "sync_every"
        batches under the PADDLE_ONLINE_STALENESS_BATCHES bound —
        feed it a dataset/streaming.StreamingDataset to train from
        live serving traffic.

        start_batch resumes mid-epoch at the exact batch: the first N
        batches are skipped (at the dataset's index level when it
        supports batches(start_batch=...), by islice otherwise) and step
        numbering continues from N — pair with the dataset's
        state_dict()/load_state_dict() for a bit-exact data resume after
        a trainer kill (docs/fault_tolerance.md "Trainer recovery")."""
        if dataset is None:
            raise ValueError("train_from_dataset requires a dataset")
        from ..core import flags as _flags
        from ..core import monitor as _monitor
        program_ = program if not isinstance(program, CompiledProgram) \
            else program.program
        from .program import default_main_program
        dp = _DownpourDriver(program_ or default_main_program(),
                             scope, ps_config) if ps_config else None
        base_fetch = list(fetch_list or [])

        es = program.exec_strategy if isinstance(program, CompiledProgram) \
            else None
        inflight = getattr(es, "max_inflight", None)
        if inflight is None:
            inflight = _flags.flag("FLAGS_executor_max_inflight")

        start_batch = int(start_batch or 0)

        def _batches():
            try:
                return dataset.batches(start_batch=start_batch)
            except TypeError:
                import itertools
                return itertools.islice(dataset.batches(),
                                        start_batch, None)

        if dp is None and inflight > 0:
            # async hot path: in-flight steps + device-resident carry +
            # (opt-in) scan-fused megasteps; fetches materialize only at
            # the print boundary (docs/async_executor.md)
            from .pipeline_runner import PipelineRunner
            names = fetch_info or [getattr(f, "name", str(f))
                                   for f in (fetch_list or [])]
            it = start_batch
            with PipelineRunner(
                    self, program, fetch_list=base_fetch, scope=scope,
                    max_inflight=inflight,
                    scan_steps=getattr(es, "scan_fuse_steps", None)) \
                    as runner:
                for handles in runner.run(_batches()):
                    _monitor.stat_add("executor/dataset_batches")
                    it += 1
                    if debug or (fetch_list and print_period
                                 and it % print_period == 0):
                        msg = ", ".join(
                            f"{n}={np.asarray(h).mean():.6f}"
                            for n, h in zip(names, handles))
                        print(f"batch {it}: {msg}")
            return None

        # synchronous loop: the Downpour pre/post hooks read AND write the
        # scope around every batch (sparse pull into the param, grad rows
        # pushed after) — a per-step host sync boundary by construction
        from ..distributed import elastic as _elastic
        it = start_batch
        for feed in _batches():
            if dp is not None:
                feed = dp.pre_step(feed)
            outs = self.run(program, feed=feed,
                            fetch_list=base_fetch + (dp.grad_fetches
                                                     if dp else []),
                            scope=scope)
            if dp is not None:
                dp.post_step(outs[len(base_fetch):])
                outs = outs[:len(base_fetch)]
            _monitor.stat_add("executor/dataset_batches")
            it += 1
            _elastic.notify_step(it)
            if debug or (fetch_list and print_period
                         and it % print_period == 0):
                names = fetch_info or [getattr(f, "name", str(f))
                                       for f in (fetch_list or [])]
                msg = ", ".join(f"{n}={np.asarray(v).mean():.6f}"
                                for n, v in zip(names, outs))
                print(f"batch {it}: {msg}")
        if dp is not None:
            dp.flush()
        return None

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """reference executor.py infer_from_dataset — same loop, the
        program simply has no optimizer section."""
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period)

    @staticmethod
    def _recompute_segments(program, ops, fetch_ids, persist, state_writes,
                            bwd):
        """Split the op list at recompute checkpoint variables and compute
        each boundary's live set (vars read by any later op, fetched,
        persisted, or state-written) so segment outputs can be pruned to
        exactly what must be saved."""
        ck_names = getattr(program, "recompute_checkpoints", None)
        if not ck_names:
            return None
        names = set(ck_names)
        ck_ids = {v.var_id for v in program.list_vars() if v.name in names}
        cuts = sorted({i + 1 for i, op in enumerate(program.ops)
                       if any(oid in ck_ids for oid in op.out_ids)})
        cuts = [c for c in cuts if c < len(ops)]
        if not cuts:
            return None
        bounds = [0] + cuts + [len(ops)]
        segments = [(bounds[i], bounds[i + 1])
                    for i in range(len(bounds) - 1)]
        final_needed = set(fetch_ids) | {vid for _, vid in persist} \
            | set(state_writes.values())
        if bwd is not None:
            final_needed.add(bwd[0].var_id)
        read_sets = [{x.var_id for x in op.flat if isinstance(x, _Ref)}
                     for op in program.ops]
        live_out = []
        for _lo, hi in segments:
            needed = set(final_needed)
            for rs in read_sets[hi:]:
                needed |= rs
            live_out.append(frozenset(needed))
        policy = getattr(program, "recompute_policy", "nothing")
        return segments, live_out, policy

    # -- lowering ------------------------------------------------------------
    def _compile(self, program: Program, feed_names, fetch_ids,
                 data_parallel):
        import jax.tree_util as jtu
        ops = [(op.fn, op.flat, op.n_args, op.kw_tree, op.out_ids, op.name)
               for op in program.ops]
        amp_level = getattr(program, "amp_level", None)
        amp_dtype = getattr(program, "amp_dtype", jnp.bfloat16)
        amp_white, amp_black = getattr(program, "amp_lists", (None, None))
        # in-program dynamic loss scaling (fp16 static AMP; reference
        # contrib/mixed_precision/decorator.py + the amp op pair
        # check_finite_and_unscale / update_loss_scaling): scale state
        # lives in the scope and threads through the compiled step
        amp_dyn = bool(getattr(program, "amp_dynamic_scaling", False))
        amp_hp = dict(getattr(program, "amp_scaling_hparams", {}) or {})
        # per-program state keys: two programs sharing the global scope
        # must not share loss-scale state (uid, not name — default names
        # like "main" repeat across Program objects)
        _tag = f"{program.name}#{getattr(program, 'uid', id(program))}"
        _SCALE = f"_amp_loss_scale_@{_tag}"
        _GOOD = f"_amp_good_steps_@{_tag}"
        _BAD = f"_amp_bad_steps_@{_tag}"
        persist = list(program.persist_ids.items())
        persist_names = [n for n, _ in persist]
        data_ids = {n: v.var_id for n, v in program.data_vars.items()}
        state_writes = dict(program.state_writes)
        bwd = program.backward_section
        amp_dyn = amp_dyn and bwd is not None
        opt_sec = program.optimizer_section
        opt = opt_sec[0] if opt_sec else None
        meta = None
        if opt is not None:
            meta = {p.scope_name: {
                "lr_ratio": getattr(p, "optimize_attr", {}).get("learning_rate", 1.0),
                "regularizer": getattr(p, "regularizer", None) or opt._coupled_decay_default(),
                "need_clip": getattr(p, "need_clip", True)}
                for p, _ in opt_sec[1]}

        def run_op_range(env, op_range):
            for fn, flat, n_args, kw_tree, out_ids, opname in op_range:
                vals = [_resolve(x, env) for x in flat]
                if amp_level:  # program-level AMP (paddle_tpu.static.amp)
                    from .. import amp as amp_mod
                    vals = amp_mod.cast_vals(opname, vals, amp_level,
                                             amp_dtype, amp_white, amp_black)
                kw = jtu.tree_unflatten(kw_tree, vals[n_args:])
                out = fn(*vals[:n_args], **kw)
                if len(out_ids) == 1 and not isinstance(out, (tuple, list)):
                    env[out_ids[0]] = out
                else:
                    for oid, val in zip(out_ids, out):
                        env[oid] = val
            return env

        recompute_segments = self._recompute_segments(
            program, ops, fetch_ids, persist, state_writes, bwd)

        def run_ops(env):
            if recompute_segments is None:
                return run_op_range(env, ops)
            # recompute: each segment's intermediates are rematerialized in
            # the backward pass; only each boundary's live set is saved
            # (reference backward.py:701; here jax.checkpoint over env-dict
            # segment functions with liveness-pruned boundaries)
            segments, live_out, policy = recompute_segments
            from ..distributed.recompute import checkpoint_policy
            pol = checkpoint_policy(policy)
            for idx, (lo, hi) in enumerate(segments):
                seg_ops = ops[lo:hi]
                keep = live_out[idx]

                def seg_fn(e, _ops=seg_ops, _keep=keep):
                    e = dict(e)
                    e = run_op_range(e, _ops)
                    return {k: v for k, v in e.items() if k in _keep}

                env = jax.checkpoint(seg_fn, policy=pol)(env)
            return env

        def step(feed_tuple, scope_vals, slots, lr, t, key):
            from ..core import rng as _rng

            # ONE forward pass. With a backward section, fetches come out of
            # the grad pass's own forward (has_aux) so stochastic ops (e.g.
            # dropout) use exactly the keys the applied gradient saw — the
            # chain is re-seated on `key` inside `forward` either way.
            def forward(pvals):
                with _rng.rng_state(key):
                    env = {}
                    for name, val in zip(sorted(feed_names), feed_tuple):
                        env[data_ids[name]] = val
                    for name, vid in persist:
                        env[vid] = (scope_vals[name] if pvals is None
                                    else pvals.get(name, scope_vals[name]))
                    return run_ops(env)

            new_slots = slots
            amp_out = {}
            if bwd is not None:
                loss_var, pairs = bwd
                grad_names = [p.scope_name for p, _ in pairs]
                scale = (scope_vals[_SCALE] if amp_dyn
                         else jnp.ones((), jnp.float32))

                def loss_of(pvals):
                    env2 = forward(pvals)
                    loss = env2[loss_var.var_id]
                    if amp_dyn:  # scaled objective; env keeps the real loss
                        loss = (loss.astype(jnp.float32) * scale).astype(
                            loss.dtype)
                    return loss, env2

                grads, env = jax.grad(loss_of, has_aux=True)(
                    {n: scope_vals[n] for n in grad_names})
                found_inf = jnp.zeros((), jnp.bool_)
                if amp_dyn:
                    from ..amp import (check_finite_and_unscale,
                                       update_loss_scaling)
                    grads, found_inf = check_finite_and_unscale(grads,
                                                                scale)
                    new_scale, good, bad = update_loss_scaling(
                        scale, scope_vals[_GOOD], scope_vals[_BAD],
                        found_inf,
                        incr_ratio=amp_hp.get("incr_ratio", 2.0),
                        decr_ratio=amp_hp.get("decr_ratio", 0.5),
                        incr_every_n_steps=amp_hp.get(
                            "incr_every_n_steps", 1000),
                        decr_every_n_nan_or_inf=amp_hp.get(
                            "decr_every_n_nan_or_inf", 2))
                    amp_out = {_SCALE: new_scale, _GOOD: good, _BAD: bad}
                for p, g in pairs:
                    env[g.var_id] = grads[p.scope_name]
                if opt is not None:
                    import jax.tree_util as _jtu
                    pvals = {n: scope_vals[n] for n in grad_names}
                    new_p, new_slots = opt.apply_gradients_pure(
                        pvals, grads, slots, lr, t, param_meta=meta)
                    if amp_dyn:  # skip the update on overflow steps
                        new_p = _jtu.tree_map(
                            lambda nw, od: jnp.where(found_inf, od, nw),
                            new_p, pvals)
                        new_slots = _jtu.tree_map(
                            lambda nw, od: jnp.where(found_inf, od, nw),
                            new_slots, dict(slots))
                    for n, v in new_p.items():
                        env[("param", n)] = v
            else:
                env = forward(None)

            # every donated scope array must flow back out (unchanged
            # entries alias through) or the next run reads deleted buffers
            new_scope = {n: env[vid] for n, vid in persist}
            for n, vid in state_writes.items():
                new_scope[n] = env[vid]
            new_scope.update(amp_out)
            if opt is not None and bwd is not None:
                for p, _ in opt_sec[1]:
                    new_scope[p.scope_name] = env[("param", p.scope_name)]
            fetches = tuple(env[fid] for fid in fetch_ids)
            return fetches, new_scope, new_slots

        amp_init = None
        read_names = list(persist_names)
        if amp_dyn:
            amp_init = {
                _SCALE: jnp.asarray(amp_hp.get("init", 2.0 ** 15),
                                    jnp.float32),
                _GOOD: jnp.zeros((), jnp.int32),
                _BAD: jnp.zeros((), jnp.int32)}
            read_names += [_SCALE, _GOOD, _BAD]

        # donating the scope only pays off when the step writes it back
        donate = (1, 2) if (state_writes or opt is not None or amp_dyn) \
            else ()
        jitted = jax.jit(step, donate_argnums=donate)

        dp = _dp_shardings() if data_parallel else None
        if dp is not None:
            mesh, repl, batch = dp
            jitted = jax.jit(
                step,
                in_shardings=((batch,) * len(feed_names),
                              {n: repl for n in read_names},
                              None, repl, repl, repl),
                donate_argnums=donate)

        opt_pnames = [p.scope_name for p, _ in opt_sec[1]] \
            if opt is not None else []
        return _CompiledEntry(jitted, step, sorted(feed_names), fetch_ids,
                              read_names, opt, opt_pnames, amp_init,
                              donate, dp)


class _DownpourDriver:
    """Per-batch sparse pull/push around the compiled step (reference
    framework/downpour_worker.cc FillSparseValue / push_sparse; N11/N22).

    The PS-managed embedding param stays a scope var; before each batch
    the rows the batch touches are pulled from the server into it, after
    the step those rows of its gradient are pushed back (optionally via
    the async Communicator). The param is removed from the local optimizer
    section — the server-side accessor (sgd/adagrad/adam) owns the update,
    exactly the reference's division of labor.

    mode="online" is the CONTINUOUS Downpour/Geo variant that closes the
    serve→train loop (docs/online_learning.md): the param KEEPS its local
    optimizer (the worker applies its own update rule, reference
    GeoCommunicator), and what flows to the server is the accumulated
    LOCAL DELTA — pushed via `push_sparse_delta` against a "geo_sparse"
    table every `sync_every` batches. Each cut payload carries a stable
    request key (trainer id + flush sequence), so a flush retried across
    transport faults, server failover, or even a trainer restart (with
    the client's replay state restored) applies EXACTLY ONCE. A failing
    flush is deferred and retried at the next cadence up to the bounded-
    staleness knob (PADDLE_ONLINE_STALENESS_BATCHES), then the error
    propagates — fail-stop beats serving an arbitrarily stale model.
    Per-spec "prefetcher" (PR 12 EmbeddingPrefetcher) routes pulls
    through the prefetch/conflict machinery and gets `note_pushed` after
    every acked flush. `flush_log` records every cut payload
    (spec, seq, ids) — the deterministic schedule exactly-once drills
    replay against per-server `table_applied`."""

    def __init__(self, program, scope, ps_config):
        from .program import global_scope
        self.scope = scope or global_scope()
        self.client = ps_config["client"]
        self.comm = ps_config.get("communicator")
        self.mode = ps_config.get("mode", "sync")
        if self.mode not in ("sync", "online"):
            raise ValueError(f"ps_config mode {self.mode!r} "
                             f"(want 'sync' or 'online')")
        self.online = self.mode == "online"
        self.specs = [dict(s) for s in ps_config.get("sparse", [])]
        for s in self.specs:
            target = s["param"]
            pv = None
            for v in program.persistable_vars.values():
                if v.name == target \
                        or getattr(v, "scope_name", None) == target:
                    pv = v
                    break
            if pv is None:
                raise ValueError(
                    f"ps_config param {target!r} is not a persistable var "
                    f"of the program")
            s["_name"] = pv.name
            s["_scope"] = getattr(pv, "scope_name", pv.name)
        ps_names = {s["_name"] for s in self.specs}
        if program.optimizer_section and not self.online:
            opt, pairs = program.optimizer_section
            keep = [(p, g) for p, g in pairs if p.name not in ps_names]
            if len(keep) != len(pairs):
                program.optimizer_section = (opt, keep)
                program._version += 1
        self.grad_fetches = []
        if not self.online:
            bw = getattr(program, "backward_section", None)
            bw_pairs = bw[1] if bw else []
            for s in self.specs:
                gvar = next((g for p, g in bw_pairs
                             if p.name == s["_name"]), None)
                if gvar is None:
                    raise ValueError(
                        f"ps_config param {s['param']!r} has no grad var "
                        "— run minimize()/append_backward over it")
                self.grad_fetches.append(gvar)
        else:
            from ..core import flags as _flags
            self.sync_every = int(
                ps_config.get("sync_every")
                or _flags.flag("PADDLE_ONLINE_SYNC_EVERY"))
            self.staleness = max(
                int(ps_config.get("staleness_batches")
                    or _flags.flag("PADDLE_ONLINE_STALENESS_BATCHES")),
                self.sync_every)
            self.trainer_id = int(ps_config.get("trainer_id", 0))
            self.on_batch = ps_config.get("on_batch")
            self._pending = [{} for _ in self.specs]  # id -> delta row
            self._frozen = [None] * len(self.specs)   # unacked payload
            self._flush_seq = [0] * len(self.specs)
            self._unflushed = 0       # batches past last acked flush
            self._batch_count = 0
            self.flush_log = []       # (spec_idx, seq, ids) of payloads
            if ps_config.get("state"):
                self.load_online_state(ps_config["state"])
        self._pulled = [None] * len(self.specs)
        self._before = [None] * len(self.specs)

    def pre_step(self, feed):
        import jax.numpy as jnp
        for i, s in enumerate(self.specs):
            ids = np.asarray(feed[s["slot"]]).reshape(-1)
            uniq = np.unique(ids.astype(np.int64))
            pf = s.get("prefetcher")
            if self.online and pf is not None:
                pf.prefetch(uniq)
                rows = np.asarray(pf.get(uniq), np.float32)
            else:
                rows = np.asarray(
                    self.client.pull_sparse(s["table"], uniq),
                    np.float32)
            if self.online:
                # local view = server rows + this worker's un-acked
                # progress (pending accumulation and any frozen payload
                # still in retry) — Downpour: the worker trains on its
                # own freshest rows, the server sees deltas at flush
                rows = rows.copy()
                pend = self._pending[i]
                frozen = self._frozen[i]
                fpos = {} if frozen is None else {
                    int(x): k for k, x in enumerate(frozen[1])}
                for j, ident in enumerate(uniq.tolist()):
                    d = pend.get(ident)
                    if d is not None:
                        rows[j] += d
                    k = fpos.get(ident)
                    if k is not None:
                        rows[j] += frozen[2][k]
                self._before[i] = rows
            w = self.scope.get(s["_scope"])
            self.scope.set(s["_scope"], jnp.asarray(w).at[
                jnp.asarray(uniq)].set(jnp.asarray(rows, w.dtype)))
            self._pulled[i] = uniq
        return feed

    def post_step(self, grad_outs):
        if not self.online:
            for s, uniq, g in zip(self.specs, self._pulled, grad_outs):
                rows_g = np.asarray(g)[uniq]
                if self.comm is not None:
                    self.comm.push_sparse(s["table"], uniq, rows_g)
                else:
                    self.client.push_sparse_grad(s["table"], uniq,
                                                 rows_g)
            return
        for i, s in enumerate(self.specs):
            uniq = self._pulled[i]
            after = np.asarray(self.scope.get(s["_scope"]),
                               np.float32)[uniq]
            delta = after - self._before[i]
            pend = self._pending[i]
            for j, ident in enumerate(uniq.tolist()):
                d = pend.get(ident)
                pend[ident] = delta[j].copy() if d is None \
                    else d + delta[j]
        self._unflushed += 1
        self._batch_count += 1
        self._maybe_flush()
        if self.on_batch is not None:
            self.on_batch(self)

    # -- online (continuous Downpour) flush machinery -----------------------
    def _maybe_flush(self, force=False):
        from ..core import monitor as _monitor
        if not force and self._unflushed < self.sync_every:
            _monitor.stat_set("ps.online.staleness_batches",
                              self._unflushed)
            return
        try:
            self._push_all()
            self._unflushed = 0
        except (ConnectionError, OSError, RuntimeError):
            # transient PS trouble (chaos, failover in progress): defer
            # to the next cadence — but only inside the staleness bound
            _monitor.stat_add("ps.online.deferred_flushes")
            if force or self._unflushed >= self.staleness:
                raise
        _monitor.stat_set("ps.online.staleness_batches",
                          self._unflushed)

    def _push_all(self):
        for i, s in enumerate(self.specs):
            if self._frozen[i] is not None:
                # retry the frozen payload FIRST, under its original
                # request key — if the failed attempt actually applied
                # server-side, the replay cache swallows this resend
                seq, fids, fdeltas = self._frozen[i]
                self._push_payload(s, seq, fids, fdeltas)
                self._frozen[i] = None
            pend = self._pending[i]
            if not pend:
                continue
            ids = np.fromiter(sorted(pend), np.int64, len(pend))
            deltas = np.stack([pend[int(x)] for x in ids])
            seq = self._flush_seq[i]
            self._flush_seq[i] += 1
            # the payload is CUT here: logged once, then pushed under a
            # stable key until acked — the log IS the delta schedule
            self.flush_log.append((i, seq,
                                   tuple(int(x) for x in ids)))
            self._pending[i] = {}
            self._frozen[i] = (seq, ids, deltas)
            self._push_payload(s, seq, ids, deltas)
            self._frozen[i] = None

    def _push_payload(self, s, seq, ids, deltas):
        from ..core import monitor as _monitor
        self.client.push_sparse_delta(
            s["table"], ids, deltas,
            request_key=("online", self.trainer_id, int(seq)))
        pf = s.get("prefetcher")
        if pf is not None:
            pf.note_pushed(ids)
        _monitor.stat_add("ps.online.flushes")
        _monitor.stat_add("ps.online.delta_rows", len(ids))

    def online_state(self):
        """Checkpoint payload of the continuous trainer: un-pushed
        accumulation, any frozen (cut, unacked) payloads with their
        flush sequence numbers, and the client's replay identity — a
        restarted trainer restoring this (plus the dataset's
        state_dict) resumes the EXACT delta schedule, and resent
        payloads dedupe server-side."""
        return {
            "flush_seq": list(self._flush_seq),
            "unflushed": int(self._unflushed),
            "batch_count": int(self._batch_count),
            "pending": [{int(k): v.tolist() for k, v in p.items()}
                        for p in self._pending],
            "frozen": [None if f is None else
                       [int(f[0]), np.asarray(f[1]).tolist(),
                        np.asarray(f[2]).tolist()] for f in self._frozen],
            "flush_log": [[i, seq, list(ids)]
                          for i, seq, ids in self.flush_log],
            "replay": self.client.replay_state(),
        }

    def load_online_state(self, state):
        self._flush_seq = [int(x) for x in state["flush_seq"]]
        self._unflushed = int(state["unflushed"])
        self._batch_count = int(state["batch_count"])
        self._pending = [
            {int(k): np.asarray(v, np.float32) for k, v in p.items()}
            for p in state["pending"]]
        self._frozen = [
            None if f is None else
            (int(f[0]), np.asarray(f[1], np.int64),
             np.asarray(f[2], np.float32)) for f in state["frozen"]]
        self.flush_log = [(int(i), int(seq), tuple(ids))
                          for i, seq, ids in state["flush_log"]]
        self.client.load_replay_state(state["replay"])

    def flush(self):
        if self.online:
            # end of stream: push everything, fail-stop on error
            self._maybe_flush(force=True)
            return
        if self.comm is not None:
            self.comm.flush()
