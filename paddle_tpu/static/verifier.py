"""Program structural verifier.

Analog of the reference's graph sanity layer (reference framework/ir/
graph.cc IsTopologySortOperationsUnique + node sanity checks inside
Pass::Apply, and framework/program_desc.cc block validation): every
Program rewrite (transpiler, DCE, CSE, constant folding) must leave the
op list well-formed, and a buggy pass should fail LOUDLY at rewrite time
with the op/var it corrupted — not as a wrong number three subsystems
later.

Checks (each raises `ProgramVerifyError` naming the op, the var, and —
when run under the pass-safety harness in passes.py — the pass that
broke it):

  use-before-def    every `_Ref` input of every op resolves to a data
                    var, a persistable seed id, or the output of an
                    EARLIER op (SSA order).
  dangling-ref      no `_Ref` points at a var id nothing in the program
                    defines at all (classic symptom of a pass dropping a
                    producer op but not its consumers).
  single-assignment no two ops produce the same output var id, and op
                    outputs never shadow data/persistable ids.
  out-ids-sync      `op.out_ids` mirrors `op.out_vars` (rewrites that
                    copy OpNodes must keep both in sync — the executor
                    keys its env on out_ids but serde walks out_vars).
  root-liveness     persistable seeds, state-write targets, backward
                    loss/grad vars and jit fetches all remain defined —
                    i.e. DCE may never eliminate a scope-backed or
                    fetched value.
  sub-blocks        control-flow ops (`cond`/`while_loop`) carry
                    well-formed SubBlocks: inner refs resolve against
                    placeholders/free ids/earlier inner ops, outputs are
                    defined, and the free-id list matches the op's
                    promoted inputs.
"""
from __future__ import annotations

from typing import Optional, Set

from .program import Program, _Ref

__all__ = ["ProgramVerifyError", "verify_program"]


class ProgramVerifyError(RuntimeError):
    """A structural invariant of a Program does not hold.

    Attributes pinpoint the failure: `rule` (which invariant), `op_name`
    and `op_index` (the offending op, when any), `var` (the offending
    variable name or id), `pass_name` (the pass that produced the broken
    program, when verification runs under the pass harness).
    """

    def __init__(self, message, *, rule, op_name=None, op_index=None,
                 var=None, pass_name=None):
        self.raw_message = message
        self.rule = rule
        self.op_name = op_name
        self.op_index = op_index
        self.var = var
        self.pass_name = pass_name
        where = ""
        if op_name is not None:
            where = f" [op #{op_index} '{op_name}']" \
                if op_index is not None else f" [op '{op_name}']"
        blame = f" (after pass '{pass_name}')" if pass_name else ""
        super().__init__(f"{rule}{where}: {message}{blame}")

    def with_pass(self, pass_name):
        return ProgramVerifyError(
            self.raw_message, rule=self.rule, op_name=self.op_name,
            op_index=self.op_index, var=self.var, pass_name=pass_name)


def _ref_name(ref):
    return getattr(ref, "name", None) or f"v{ref.var_id}"


def _seed_ids(program: Program) -> Set[int]:
    # environment inputs the executor seeds: fed data + persistable SEED
    # ids (persist_ids). A rebinded persistable's CURRENT var_id is an op
    # output (program.py Variable._rebind), so it is deliberately absent —
    # it must be defined by the op that produced it.
    ids = {v.var_id for v in program.data_vars.values()}
    ids |= set(program.persist_ids.values())
    return ids


def verify_program(program: Program, pass_name: Optional[str] = None):
    """Check every structural invariant; returns the program on success.

    `pass_name` tags raised diagnostics with the rewrite that produced
    this program (the pass-safety harness in passes.py supplies it).
    """
    try:
        _verify(program)
    except ProgramVerifyError as e:
        if pass_name and e.pass_name is None:
            raise e.with_pass(pass_name) from None
        raise
    return program


def _verify(program: Program):
    seeds = _seed_ids(program)
    defined = set(seeds)
    all_defined = set(defined)
    for op in program.ops:
        for oid in op.out_ids:
            all_defined.add(oid)

    produced = {}
    for i, op in enumerate(program.ops):
        # out_ids must mirror out_vars
        if len(op.out_ids) != len(op.out_vars) or any(
                oid != v.var_id for oid, v in zip(op.out_ids, op.out_vars)):
            raise ProgramVerifyError(
                f"out_ids {list(op.out_ids)} do not mirror out_vars "
                f"{[v.var_id for v in op.out_vars]}",
                rule="out-ids-sync", op_name=op.name, op_index=i)
        for x in op.flat:
            if not isinstance(x, _Ref):
                continue
            if x.var_id in defined:
                continue
            if x.var_id in all_defined:
                prod_i, prod_name = next(
                    (j, o.name) for j, o in enumerate(program.ops)
                    if x.var_id in o.out_ids)
                raise ProgramVerifyError(
                    f"input '{_ref_name(x)}' (id {x.var_id}) is used "
                    f"before its producer op #{prod_i} '{prod_name}' runs",
                    rule="use-before-def", op_name=op.name, op_index=i,
                    var=_ref_name(x))
            raise ProgramVerifyError(
                f"input '{_ref_name(x)}' (id {x.var_id}) is defined "
                "nowhere in the program — its producer was likely removed "
                "by a rewrite that kept this consumer",
                rule="dangling-ref", op_name=op.name, op_index=i,
                var=_ref_name(x))
        for oid, v in zip(op.out_ids, op.out_vars):
            if oid in produced:
                j, jname = produced[oid]
                raise ProgramVerifyError(
                    f"output '{v.name}' (id {oid}) is already produced by "
                    f"op #{j} '{jname}' — SSA requires single assignment",
                    rule="single-assignment", op_name=op.name, op_index=i,
                    var=v.name)
            if oid in seeds:
                raise ProgramVerifyError(
                    f"output '{v.name}' (id {oid}) shadows a "
                    "data/persistable variable",
                    rule="single-assignment", op_name=op.name, op_index=i,
                    var=v.name)
            produced[oid] = (i, op.name)
            defined.add(oid)
        _verify_subblocks(op, i)

    _verify_roots(program, defined)


def _verify_roots(program: Program, defined: Set[int]):
    """Fetch/persist/backward roots must survive every rewrite."""
    def need(vid, what, var=None):
        if vid not in defined:
            raise ProgramVerifyError(
                f"{what} (id {vid}) is not defined by the program — a "
                "rewrite (dead-code elimination?) removed a live value",
                rule="root-liveness", var=var or f"v{vid}")

    for scope_name, vid in program.state_writes.items():
        need(vid, f"state write target '{scope_name}'", var=scope_name)
    if program.backward_section is not None:
        loss, pairs = program.backward_section
        need(loss.var_id, f"backward loss '{loss.name}'", var=loss.name)
        for p, g in pairs:
            # grad vars are synthesized by the executor, but their params
            # must still be environment inputs
            if p.scope_name not in program.persist_ids \
                    and p.scope_name not in program.persistable_vars:
                raise ProgramVerifyError(
                    f"backward param '{p.name}' is no longer a persistable "
                    "of the program", rule="root-liveness", var=p.name)
    for v in getattr(program, "_jit_fetch_vars", []) or []:
        need(v.var_id, f"fetch '{v.name}'", var=v.name)


def _verify_subblocks(op, op_index):
    """Validate control-flow SubBlocks owned by this op's kernel."""
    from .control_flow import _CondFn, _WhileFn

    fn = op.fn
    blocks = ()
    if isinstance(fn, _WhileFn):
        blocks = (("while_cond", fn.cond_block), ("while_body", fn.body_block))
        for label, blk in blocks:
            if len(blk.in_ids) != fn.n_loop:
                raise ProgramVerifyError(
                    f"{label} sub-block declares {len(blk.in_ids)} "
                    f"placeholders for {fn.n_loop} loop vars",
                    rule="sub-blocks", op_name=op.name, op_index=op_index)
    elif isinstance(fn, _CondFn):
        blocks = (("cond_true", fn.true_block), ("cond_false", fn.false_block))
    if not blocks:
        return

    # the op's recorded inputs are (loop_vars | pred) + promoted free
    # vars, in that order — each block's free_ids must match that arity
    carried = fn.n_loop if isinstance(fn, _WhileFn) else 1
    for label, blk in blocks:
        if len(blk.free_ids) != op.n_args - carried:
            raise ProgramVerifyError(
                f"{label} sub-block wants {len(blk.free_ids)} free vars "
                f"but the op records {op.n_args - carried} promoted "
                "inputs", rule="sub-blocks", op_name=op.name,
                op_index=op_index)
        _verify_block_body(label, blk, op, op_index)


def _verify_block_body(label, blk, op, op_index):
    defined = set(blk.in_ids) | set(blk.free_ids)
    all_defined = set(defined)
    for sub in blk.ops:
        all_defined.update(sub.out_ids)
    for j, sub in enumerate(blk.ops):
        for x in sub.flat:
            if isinstance(x, _Ref) and x.var_id not in defined:
                word = ("used before definition" if x.var_id in all_defined
                        else "defined nowhere in the sub-block")
                raise ProgramVerifyError(
                    f"{label} sub-block op #{j} '{sub.name}' input "
                    f"'{_ref_name(x)}' (id {x.var_id}) is {word}",
                    rule="sub-blocks", op_name=op.name, op_index=op_index,
                    var=_ref_name(x))
        defined.update(sub.out_ids)
        _verify_subblocks(sub, op_index)  # nested control flow
    for oid in blk.out_ids:
        if oid not in defined:
            raise ProgramVerifyError(
                f"{label} sub-block output id {oid} is not defined by the "
                "sub-block", rule="sub-blocks", op_name=op.name,
                op_index=op_index, var=f"v{oid}")
