"""Analytic serve capacity model: predict TTFT/token p50/p99 and the
saturation knee from an offered-load spec, BEFORE running any traffic.

The static-analysis headline of the traffic lab (docs/traffic_lab.md).
Composition, per ISSUE 18:

- **Decode beat cost** from the HLO-evidence `serve_decode` graph
  (flops + bytes_accessed roofline, split into a fixed weight-read
  floor and a per-active-stream KV/FLOPs slope via the
  `kv_bytes_per_step` model) — `analytic_profile`.
- **Prefill cost** priced by the per-op FLOPs registry: a traced
  tiny-GPT `static.Program` at each prefill bucket, through
  `spmd_analyzer.analyze_flops`.
- **Topology tier costs** (PR 16): the fleet section prices the weight
  publish (hot-swap push to N serve replicas) over the DCN tier from
  `FLAGS_topology_dcn_gbps`.
- **Admission/pool queueing**: the same FCFS + worst-case-block
  admission gate the ServeLoop runs, replayed as a deterministic
  discrete-event simulation of the scheduler beat over the workload
  generator's OWN schedule (`simulate`) — plus a closed-form
  M/G/k-style wait estimate (`queue_wait_ms`, Allen–Cunneen) and the
  knee `lambda_knee = slots / (E[n]*beat + slots*E[prefill])`.
- **Measured host overheads** on CPU: `calibrate_cpu` fits the beat
  base/slope and per-bucket prefill from the live tiny loop, which is
  what `tools/capacity_plan.py --validate` scores the model with.

Everything here is pure host math over a deterministic schedule — two
calls with the same (spec, seed, profile) return identical predictions.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

__all__ = ["DeviceProfile", "DEVICE_PEAKS", "calibrate_cpu",
           "analytic_profile", "prefill_flops", "simulate", "predict",
           "knee_rps", "queue_wait_ms"]

# per-chip peaks the analytic (no-hardware) path prices against;
# v3 numbers per the MLPerf pod-scaling paper's roofline methodology
DEVICE_PEAKS = {
    "tpu-v3": {"flops_per_s": 105e12, "hbm_bytes_per_s": 900e9},
    "tpu-v4": {"flops_per_s": 275e12, "hbm_bytes_per_s": 1200e9},
}


def _bucket(n: int) -> int:
    """The serve prefill pad bucket a prompt of length n compiles into
    (mirrors the load tools' warm-up loop)."""
    b = 8
    while b < n:
        b *= 2
    return b


@dataclass
class DeviceProfile:
    """What one serving device costs, in the two quantities the beat
    simulation consumes: an affine decode-beat model
    `beat_ms(active) = base + slope*active` and a per-bucket prefill
    table. `source` records how it was derived ("calibrated-cpu" from
    live measurement, "analytic-<device>" from the cost models)."""

    source: str
    beat_ms_base: float
    beat_ms_per_active: float
    prefill_ms: Dict[int, float] = field(default_factory=dict)
    host_overhead_ms: float = 0.0
    # per-admission LATENCY overhead under paced load (scheduler wake
    # from the idle wait, submit-side key/dispatch) — felt by the
    # arriving request's TTFT but NOT serialized into the beat timeline
    # (the wakeup overlaps decode of other streams). Invisible to a
    # hot-loop measurement; fitted by the refinement pass.
    admit_ms: float = 0.0
    # the SERIALIZED share of the admission overhead (per-admission
    # scheduler work beyond prefill compute that does block the beat
    # loop, so arrival clumps queue behind it). Separated from admit_ms
    # by the second, high-rate refinement operating point — at low rate
    # the two are indistinguishable, at high rate only this one bends
    # the TTFT tail.
    admit_serial_ms: float = 0.0
    # host-jitter tail offsets: the p99 − p50 spread the OS scheduler
    # adds on top of anything a beat-cost model can derive. Fitted once
    # at the refinement operating point, held to every other spec.
    ttft_tail_ms: float = 0.0
    token_tail_ms: float = 0.0

    def beat_ms(self, active: int) -> float:
        return (self.beat_ms_base + self.host_overhead_ms
                + self.beat_ms_per_active * max(0, int(active)))

    def prefill_cost_ms(self, prompt_len: int) -> float:
        b = _bucket(prompt_len)
        if b in self.prefill_ms:
            return self.prefill_ms[b] + self.host_overhead_ms
        if not self.prefill_ms:
            return self.host_overhead_ms
        # extrapolate linearly in bucket width from the nearest bucket
        ref = min(self.prefill_ms, key=lambda k: abs(k - b))
        return self.prefill_ms[ref] * (b / ref) + self.host_overhead_ms

    def as_dict(self) -> Dict:
        return {"source": self.source,
                "beat_ms_base": round(self.beat_ms_base, 4),
                "beat_ms_per_active": round(self.beat_ms_per_active, 4),
                "prefill_ms": {str(k): round(v, 4)
                               for k, v in sorted(self.prefill_ms.items())},
                "host_overhead_ms": round(self.host_overhead_ms, 4),
                "admit_ms": round(self.admit_ms, 4),
                "admit_serial_ms": round(self.admit_serial_ms, 4),
                "ttft_tail_ms": round(self.ttft_tail_ms, 4),
                "token_tail_ms": round(self.token_tail_ms, 4)}

    @classmethod
    def from_dict(cls, d: Dict) -> "DeviceProfile":
        return cls(source=d["source"],
                   beat_ms_base=float(d["beat_ms_base"]),
                   beat_ms_per_active=float(d["beat_ms_per_active"]),
                   prefill_ms={int(k): float(v)
                               for k, v in d.get("prefill_ms", {}).items()},
                   host_overhead_ms=float(d.get("host_overhead_ms", 0.0)),
                   admit_ms=float(d.get("admit_ms", 0.0)),
                   admit_serial_ms=float(d.get("admit_serial_ms", 0.0)),
                   ttft_tail_ms=float(d.get("ttft_tail_ms", 0.0)),
                   token_tail_ms=float(d.get("token_tail_ms", 0.0)))


# ---------------------------------------------------------------------------
# profiles: measured (CPU) and analytic (TPU cost models)
# ---------------------------------------------------------------------------

def calibrate_cpu(serve_cfg=None, *, beats: Optional[int] = None,
                  buckets=(8, 16, 32), refine: bool = True
                  ) -> DeviceProfile:
    """Fit a DeviceProfile from the live CPU tiny-GPT loop: per-bucket
    prefill from single-request TTFT, beat base/slope from per-token
    latency at two active levels. This is the profile `--validate`
    scores the model with — the analytic path swaps in roofline costs
    but reuses every other term."""
    from ..core import flags as _flags
    from ..core.slo import percentile
    from ..traffic.harness import build_tiny_loop

    if beats is None:
        beats = int(_flags.flag("FLAGS_capacity_calib_beats"))
    _net, loop = build_tiny_loop(serve_cfg)
    cap = loop._cap
    buckets = tuple(b for b in buckets if b + 2 <= cap)
    # compile outside the measurement (a cold XLA trace is not a beat)
    for b in buckets:
        loop.serve([np.arange(1, b + 1, dtype=np.int64)],
                   max_new_tokens=2)
    loop.start()
    try:
        prefill_ms: Dict[int, float] = {}
        for b in buckets:
            samples = []
            for _ in range(3):
                r = loop.submit(np.arange(1, b + 1, dtype=np.int64),
                                max_new_tokens=2)
                r.result(timeout=120)
                samples.append(r.ttft_s * 1e3)
            prefill_ms[b] = percentile(samples, 50)

        def beat_at(k):
            n = max(4, min(beats, cap - buckets[0]))
            reqs = [loop.submit(
                np.arange(1, buckets[0] + 1, dtype=np.int64),
                max_new_tokens=n) for _ in range(k)]
            vals = []
            for r in reqs:
                r.result(timeout=300)
                vals.append(r.per_token_s * 1e3)
            return percentile(vals, 50)

        k2 = max(2, min(4, loop._A))
        b1 = beat_at(1)
        b2 = beat_at(k2)
    finally:
        loop.stop()
    slope = max(0.0, (b2 - b1) / max(1, k2 - 1))
    base = max(1e-4, b1 - slope)
    prof = DeviceProfile(source="calibrated-cpu", beat_ms_base=base,
                         beat_ms_per_active=slope, prefill_ms=prefill_ms)
    if refine:
        _refine_cpu(prof, serve_cfg)
    return prof


_REFINE_SEED = 123


def _refine_cpu(prof: DeviceProfile, serve_cfg=None, passes: int = 2):
    """System-identification pass: the hot-loop fit misses the overhead
    a PACED arrival pays (idle-wait wakeup, submit-side dispatch work on
    the same backend). Run one short low-rate spec through the real
    harness and fit two scalar offsets — `admit_ms` from the TTFT p50
    gap and a beat-base bump from the token p50 gap. Fitted at ONE
    operating point; --validate then holds the model to other rates and
    arrival shapes."""
    from ..traffic import workload as W
    from ..traffic.harness import run_spec

    # two operating points: A (low rate) separates latency from compute
    # — queueing is negligible there; B (high rate) exposes the
    # serialized share of the admission overhead, the only parameter
    # that bends the TTFT tail with load
    spec_a = W.builtin_spec("steady", rate=25.0, duration_s=6.0)
    spec_b = W.builtin_spec("steady", rate=60.0, duration_s=6.0)
    sc = dict(serve_cfg or {})
    slots = sc.get("max_active", 8)
    blocks = sc.get("kv_blocks", 48)
    bs = sc.get("block_size", 8)

    def observe(spec):
        runs = [run_spec(spec, seed=_REFINE_SEED, serve_cfg=serve_cfg)
                for _ in range(max(1, passes))]
        med = lambda xs: float(np.median([x for x in xs  # noqa: E731
                                          if x is not None] or [0.0]))
        return {"ttft50": med([r.ttft_ms.get("p50") for r in runs]),
                "ttft99": med([r.ttft_ms.get("p99") for r in runs]),
                "tok50": med([r.token_ms.get("p50") for r in runs]),
                "tok99": med([r.token_ms.get("p99") for r in runs])}

    def pred(spec):
        return predict(spec, _REFINE_SEED, prof, slots=slots,
                       kv_blocks=blocks, block_size=bs)

    obs_a = observe(spec_a)
    p = pred(spec_a)
    if obs_a["tok50"] and p["token_ms"]["p50"]:
        prof.beat_ms_base += max(
            0.0, obs_a["tok50"] - p["token_ms"]["p50"])
    if obs_a["ttft50"] and p["ttft_ms"]["p50"]:
        prof.admit_ms += max(
            0.0, obs_a["ttft50"] - p["ttft_ms"]["p50"])
    # with the p50s anchored, attribute the low-rate TTFT p99 gap to
    # host jitter (constant tail offset)
    p = pred(spec_a)
    if obs_a["ttft99"] and p["ttft_ms"]["p99"]:
        prof.ttft_tail_ms = max(
            0.0, obs_a["ttft99"] - p["ttft_ms"]["p99"])
    # point B: bisect how much of the admission overhead serializes.
    # Moving mass from admit_ms (latency) to admit_serial_ms (timeline)
    # leaves point A nearly unchanged but steepens B's queueing tail.
    obs_b = observe(spec_b)
    if obs_b["ttft99"]:
        total = prof.admit_ms
        lo, hi = 0.0, total
        for _ in range(12):
            mid = (lo + hi) / 2
            prof.admit_serial_ms = mid
            prof.admit_ms = total - mid
            pb = pred(spec_b)["ttft_ms"]["p99"]
            if pb is not None and pb < obs_b["ttft99"]:
                lo = mid
            else:
                hi = mid
        prof.admit_serial_ms = (lo + hi) / 2
        prof.admit_ms = total - prof.admit_serial_ms
    # token tail: mean of both operating points' residuals — a single
    # run's p99 is too noisy to fit a tail from
    deltas = []
    for spec, obs in ((spec_a, obs_a), (spec_b, obs_b)):
        pp = pred(spec)["token_ms"]["p99"]
        if obs["tok99"] and pp:
            deltas.append(max(0.0, obs["tok99"] - pp))
    if deltas:
        prof.token_tail_ms = float(np.mean(deltas))


def prefill_flops(prompt_len: int, gpt_cfg=None) -> float:
    """Forward FLOPs of one prefill at `prompt_len`, priced by the
    analyzer's per-op FLOPs registry over a traced GPT Program (NOT a
    hand formula — the same registry the pipeline planner balances
    stages with)."""
    import paddle_tpu as paddle
    from ..text.models.gpt import GPT, GPTConfig
    from . import Program, data, program_guard
    from .program import in_static_mode
    from .spmd_analyzer import analyze_flops

    cfg = gpt_cfg or GPTConfig.tiny()
    was_static = in_static_mode()
    if not was_static:
        paddle.enable_static()
    try:
        main = Program(f"capacity_prefill_{prompt_len}")
        with program_guard(main):
            ids = data("input_ids", [1, _bucket(prompt_len)], "int64")
            net = GPT(cfg)
            net(ids)
        return analyze_flops(main)["total"]
    finally:
        if not was_static:
            paddle.disable_static()


def analytic_profile(evidence: Dict, *, device: str = "tpu-v3",
                     buckets=(8, 16, 32), gpt_cfg=None) -> DeviceProfile:
    """DeviceProfile from the static cost models alone: the HLO-evidence
    serve_decode roofline split into weight-read floor + per-stream
    slope, prefill priced by `prefill_flops`. No hardware needed."""
    peaks = DEVICE_PEAKS[device]
    sd = evidence["graphs"]["serve_decode"]
    slots = int(sd["config"]["slots"])
    flops = float(sd["cost_analysis"]["flops"])
    total_bytes = float(sd["cost_analysis"]["bytes_accessed"])
    kv = sd.get("kv_bytes_per_step", {})
    kv_typical = float(kv.get("typical_kv_bytes_per_step", 0.0))
    fixed_bytes = max(0.0, total_bytes - kv_typical)
    # the beat floor is the weight/activation read no batch size
    # amortizes away; each extra active stream adds its FLOPs share and
    # its KV-page DMA
    base_ms = fixed_bytes / peaks["hbm_bytes_per_s"] * 1e3
    per_active_ms = max(flops / slots / peaks["flops_per_s"],
                        (kv_typical / slots) / peaks["hbm_bytes_per_s"]) \
        * 1e3
    prefill_ms = {b: prefill_flops(b, gpt_cfg) / peaks["flops_per_s"]
                  * 1e3 for b in buckets}
    return DeviceProfile(source=f"analytic-{device}",
                         beat_ms_base=base_ms,
                         beat_ms_per_active=per_active_ms,
                         prefill_ms=prefill_ms)


def publish_wire_ms(param_bytes: float, replicas: int) -> float:
    """Hot-swap weight-publish cost to a serve fleet over the DCN tier
    (PR 16 topology flags): one push per replica, serialized at the
    publisher's NIC."""
    from ..core import flags as _flags
    gbps = float(_flags.flag("FLAGS_topology_dcn_gbps"))
    return param_bytes * max(1, int(replicas)) / (gbps * 1e9) * 1e3


# ---------------------------------------------------------------------------
# queueing: closed forms
# ---------------------------------------------------------------------------

def _erlang_c(lam: float, mu: float, k: int) -> float:
    """P(wait) for M/M/k (Erlang C)."""
    a = lam / mu
    rho = a / k
    if rho >= 1.0:
        return 1.0
    s = sum(a ** n / math.factorial(n) for n in range(k))
    top = a ** k / math.factorial(k) / (1.0 - rho)
    return top / (s + top)


def queue_wait_ms(lam: float, service_s: float, scv: float,
                  k: int) -> float:
    """Allen–Cunneen M/G/k mean queue-wait approximation: the Erlang-C
    wait scaled by the service-time variability (1+scv)/2. The beat
    simulation is the primary TTFT predictor; this closed form is the
    sanity rail the report prints next to it (and diverges at the knee,
    which is the point)."""
    if lam <= 0 or service_s <= 0:
        return 0.0
    mu = 1.0 / service_s
    if lam / (k * mu) >= 1.0:
        return float("inf")
    pw = _erlang_c(lam, mu, k)
    wq = pw / (k * mu - lam)
    return wq * (1.0 + max(0.0, scv)) / 2.0 * 1e3


def knee_rps(profile: DeviceProfile, *, slots: int, mean_new: float,
             mean_prompt: float) -> float:
    """Saturation knee: at full batch one request holds a slot for
    E[n] beats while every admission serializes a prefill through the
    scheduler, so lambda_knee = slots / (E[n]*beat(slots) +
    slots*E[prefill])."""
    beat_s = profile.beat_ms(slots) / 1e3
    pf_s = (profile.prefill_cost_ms(int(round(mean_prompt)))
            + profile.admit_serial_ms) / 1e3
    return slots / max(1e-9, mean_new * beat_s + slots * pf_s)


# ---------------------------------------------------------------------------
# the beat simulation (deterministic discrete-event replay)
# ---------------------------------------------------------------------------

def _plus(x, dx):
    return None if x is None else round(x + dx, 3)


class _Req:
    __slots__ = ("ev", "t_arr", "total", "generated", "blocks",
                 "t_first", "preemptions", "decode_s")

    def __init__(self, ev, t_arr):
        self.ev = ev
        self.t_arr = t_arr
        self.total = ev.tokens_total()
        self.generated = 0
        self.blocks = 0
        self.t_first = None
        self.preemptions = 0
        self.decode_s = 0.0


def simulate(events, profile: DeviceProfile, *, slots: int,
             kv_blocks: int, block_size: int,
             time_scale: float = 1.0) -> Dict:
    """Replay a workload schedule through an analytic model of the
    ServeLoop scheduler beat: FCFS admission gated on a free slot AND
    worst-case block availability (`can_alloc(blocks_for(total))` —
    serving.py `_admit`), prefill serialized through the beat, one
    token per active stream per beat at `profile.beat_ms(active)`,
    block growth with preempt-on-exhaustion. Deterministic: same
    schedule + profile => same prediction."""
    bf = lambda n: max(1, -(-int(n) // int(block_size)))  # noqa: E731
    arrivals = sorted(events, key=lambda e: (e.t, e.index))
    n = len(arrivals)
    i = 0
    t = 0.0
    free = int(kv_blocks)
    queue: List[_Req] = []
    active: List[_Req] = []
    ttfts_ms: List[float] = []
    token_ms: List[float] = []
    retire_ts: List[float] = []
    completed = preempted = backpressure = 0

    def pull(now):
        nonlocal i
        while i < n and arrivals[i].t * time_scale <= now + 1e-12:
            queue.append(_Req(arrivals[i], arrivals[i].t * time_scale))
            i += 1

    while i < n or queue or active:
        if not queue and not active:
            t = max(t, arrivals[i].t * time_scale)
        pull(t)
        # FCFS admission (head of queue only — the real gate)
        while queue and len(active) < slots:
            r = queue[0]
            plen = r.ev.prompt.size + r.generated
            if free < bf(r.total):
                backpressure += 1
                break
            queue.pop(0)
            r.blocks = bf(plen)
            free -= r.blocks
            t += (profile.prefill_cost_ms(plen)
                  + profile.admit_serial_ms) / 1e3
            if r.t_first is None:
                r.t_first = t          # prefill emits the first token
                r.generated = 1
            active.append(r)
        if active:
            beat_s = profile.beat_ms(len(active)) / 1e3
            t += beat_s
            still = []
            for r in active:
                r.generated += 1
                # token gaps accrue decode beats only: the pipelined
                # driver overlaps admission prefills with decode settle,
                # so admission work delays QUEUED requests (TTFT), not
                # the active streams' token cadence
                r.decode_s += beat_s
                length = r.ev.prompt.size + r.generated
                need = bf(length)
                if need > r.blocks:
                    if free >= need - r.blocks:
                        free -= need - r.blocks
                        r.blocks = need
                    else:               # pool exhausted: preempt, requeue
                        free += r.blocks
                        r.blocks = 0
                        r.preemptions += 1
                        preempted += 1
                        queue.insert(0, r)
                        continue
                if r.generated >= r.ev.new_tokens:
                    free += r.blocks
                    completed += 1
                    retire_ts.append(t)
                    # admit_ms is latency-only: the arriving request
                    # feels the wakeup, the beat timeline does not
                    ttfts_ms.append((r.t_first - r.t_arr) * 1e3
                                    + profile.admit_ms)
                    if r.ev.new_tokens >= 2:
                        token_ms.append(r.decode_s * 1e3
                                        / (r.ev.new_tokens - 1))
                else:
                    still.append(r)
            active = still
        elif queue and i < n:
            # head blocked on the pool with nothing active can't happen
            # (empty pool ⇒ full free); blocked on slots ⇒ active nonempty
            t = arrivals[i].t * time_scale
    makespan = retire_ts[-1] if retire_ts else t
    return {"completed": completed, "preempted": preempted,
            "backpressure_ticks": backpressure,
            "makespan_s": round(makespan, 4),
            "ttfts_ms": ttfts_ms, "token_ms": token_ms}


def predict(spec, seed: int, profile: DeviceProfile, *, slots: int,
            kv_blocks: int, block_size: int,
            time_scale: float = 1.0) -> Dict:
    """The capacity prediction for one workload spec: beat-simulated
    TTFT/token p50/p99 + throughput, the closed-form knee, and the
    M/G/k wait rail. This dict is what `--validate` holds the hub's
    observations against."""
    from ..core.slo import percentile
    from ..traffic import workload as W

    events = W.schedule(spec, seed)
    sim = simulate(events, profile, slots=slots, kv_blocks=kv_blocks,
                   block_size=block_size, time_scale=time_scale)
    mean_new = float(np.mean([e.new_tokens for e in events])) \
        if events else 0.0
    mean_prompt = float(np.mean([e.prompt.size for e in events])) \
        if events else 0.0
    dur = max(1e-9, spec.duration_s * time_scale)
    offered = len(events) / dur
    knee = knee_rps(profile, slots=slots, mean_new=mean_new,
                    mean_prompt=mean_prompt)
    peak = W.arrival_peak_rate(spec.arrival) / max(1e-9, time_scale)
    # the M/G/k rail: service = one request's slot occupancy
    svc_s = ((profile.prefill_cost_ms(int(round(mean_prompt)))
              + profile.admit_serial_ms) / 1e3
             + mean_new * profile.beat_ms(slots) / 1e3)
    news = np.asarray([e.new_tokens for e in events], float)
    scv = float(news.var() / max(news.mean() ** 2, 1e-12)) \
        if len(news) else 0.0
    wait = queue_wait_ms(offered, svc_s, scv, max(1, int(slots)))
    return {
        "spec": spec.name, "seed": int(seed), "events": len(events),
        "profile": profile.source,
        "offered_rps": round(offered, 3),
        "peak_rps": round(peak, 3),
        "throughput_rps": round(sim["completed"]
                                / max(sim["makespan_s"], 1e-9), 3),
        "ttft_ms": {
            "p50": percentile(sim["ttfts_ms"], 50, ndigits=3),
            "p99": _plus(percentile(sim["ttfts_ms"], 99),
                         profile.ttft_tail_ms)},
        "token_ms": {
            "p50": percentile(sim["token_ms"], 50, ndigits=3),
            "p99": _plus(percentile(sim["token_ms"], 99),
                         profile.token_tail_ms)},
        "knee_rps": round(knee, 3),
        "rho": round(offered / max(knee, 1e-9), 4),
        "peak_rho": round(peak / max(knee, 1e-9), 4),
        "mgk_wait_ms": (None if wait == float("inf")
                        else round(wait, 3)),
        "completed": sim["completed"],
        "preempted": sim["preempted"],
        "backpressure_ticks": sim["backpressure_ticks"],
        "makespan_s": sim["makespan_s"],
    }
