"""SPMD sharding analyzer — static PartitionSpec propagation.

The compile-time half of the mesh/GSPMD design delta (SURVEY §2.3,
distributed/mesh.py, distributed/sharding.py): parallelism here is
DECLARED as PartitionSpecs and the partitioner inserts the collectives,
which means a sharding mistake — a spec naming an unbound axis, a
non-divisible dim silently falling back to replication, a row-parallel
matmul fed a conflicting activation — produces no error today, just an
unplanned all-gather or an HBM OOM deep inside jit. This module computes
the consequences *statically*, the way shape_infer.py made shapes check
themselves (PR 1):

  * abstract spec propagation over a static `Program` (recorded avals
    supply all shapes — no tracing), with per-op rules: elementwise
    pass-through/merge, matmul contraction (implied all-reduce),
    reshape/transpose/concat/split spec remapping, vocab-parallel
    embedding gather, reductions;
  * the implied collective set — kind, mesh axis, per-device payload
    bytes (tensor nbytes divided by the shard divisor of its
    non-communicating dims);
  * a per-device peak-HBM estimate (analyze_memory with sharded dims
    divided by their axis sizes);
  * a diagnostic catalogue (`DIAGNOSTIC_CODES`), surfaced as
    `SpmdDiagnostic` records or raised as `SpmdLintError` naming the
    op, var, and axis;
  * a collective-order check across control-flow sub-blocks — the
    single-program-SPMD invariant pipeline.py documents (all ranks
    trace ONE program, so cond branches implying different collective
    sequences cannot be partitioned coherently).

Exposure: tools/spmd_lint.py (CLI report), the PADDLE_TPU_VERIFY_SPMD
hook in static/passes.py apply_pass and the Executor's compile path
(mirroring PADDLE_TPU_VERIFY_PASSES), and core/monitor gauges
`spmd.{collective_bytes,hbm_estimate,resharding_count}`.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import PartitionSpec as P

from .program import Program, _Ref

__all__ = ["SpmdLintError", "SpmdDiagnostic", "Collective", "SpmdReport",
           "analyze_program", "analyze_params", "analyze_flops",
           "register_spmd_rule", "register_flop_rule", "SPMD_RULES",
           "FLOP_RULES", "DIAGNOSTIC_CODES", "verify_spmd_enabled",
           "set_verify_spmd", "maybe_verify_spmd"]

# Every named finding the analyzer can produce. Each code has a dedicated
# broken-program test in tests/test_spmd_analyzer.py (the negative corpus,
# mirroring the PR-1 verifier corpus).
DIAGNOSTIC_CODES = (
    "unbound-axis",     # spec names an axis the mesh does not declare
    "duplicate-axis",   # one spec uses the same axis on two dims
    "non-divisible",    # dim not divisible by its axis size (silent
                        # replication in sharding._validate_divisible)
    "spec-rank",        # spec has more entries than the tensor has dims
                        # (trailing axes silently zip-truncated before)
    "reshard",          # spec conflict forcing an implicit all-gather
    "collective-divergence",  # cond branches imply different collective
                              # sequences (single-program SPMD invariant)
    "cross-tier",       # a recurring collective rides a slow-tier (DCN)
                        # link — model parallelism left crossing the pod
                        # boundary; only the dp gradient sync should
                        # cross, and hierarchically (hierarchical_sync)
)


class SpmdLintError(RuntimeError):
    """A sharding finding, raised in strict mode (the VERIFY_SPMD hook).

    `code` is one of DIAGNOSTIC_CODES; `op_name`/`op_index`, `var` and
    `axis` pinpoint the offending op, variable and mesh axis. The message
    lists every finding of the analysis run, not just the first.
    """

    def __init__(self, message, *, code=None, op_name=None, op_index=None,
                 var=None, axis=None):
        self.code = code
        self.op_name = op_name
        self.op_index = op_index
        self.var = var
        self.axis = axis
        super().__init__(message)


@dataclass
class SpmdDiagnostic:
    code: str
    message: str
    op_name: Optional[str] = None
    op_index: Optional[int] = None
    var: Optional[str] = None
    axis: Optional[str] = None

    def __str__(self):
        where = ""
        if self.op_name is not None:
            where = (f" [op #{self.op_index} '{self.op_name}']"
                     if self.op_index is not None
                     else f" [op '{self.op_name}']")
        return f"{self.code}{where}: {self.message}"


def _wire_dtype(dtype) -> np.dtype:
    """np.dtype that also resolves the ml_dtypes family by name
    ('float8_e4m3fn', 'float8_e5m2', 'bfloat16', ...) — numpy's own
    registry rejects the fp8 names the quantized-collective seam prices."""
    try:
        return np.dtype(dtype)
    except (TypeError, ValueError):
        import ml_dtypes
        t = getattr(ml_dtypes, str(dtype), None)
        if t is None:
            raise
        return np.dtype(t)


@dataclass
class Collective:
    """One implied collective. `bytes` is the per-device payload: the
    tensor's logical nbytes divided by the shard divisor of the dims NOT
    taking part in the communication. `dtype` is the element type riding
    the wire (numpy name), so quantized-collective analysis can re-price
    the payload under a narrower cast without re-walking the program.
    `tier`/`cost_us` price the payload against the two-tier topology
    model when the mesh declares per-axis link tiers (mesh.axis_tiers);
    on a flat mesh they stay at the defaults."""
    kind: str          # all_reduce | all_gather
    axis: str          # mesh axis (comma-joined when a dim carries several)
    bytes: int
    op_index: Optional[int] = None
    op_name: Optional[str] = None
    var: Optional[str] = None
    dtype: Optional[str] = None
    tier: str = "ici"  # slowest link tier the payload rides
    cost_us: float = 0.0  # bytes / (link GB/s * 1e3); 0 on flat meshes

    def bytes_if(self, dtype) -> int:
        """Per-device payload bytes if the wire format were `dtype`
        (the EQuARX quantized-AllReduce seam: int8/fp8 block-scaled
        collectives keep the element COUNT, shrink the element size)."""
        if self.dtype is None:
            return self.bytes
        old = _wire_dtype(self.dtype).itemsize
        new = _wire_dtype(dtype).itemsize
        return (self.bytes * new) // max(old, 1)

    @property
    def is_float(self) -> bool:
        if self.dtype is None:
            return False
        d = _wire_dtype(self.dtype)
        return d.kind == "f" or d.name.startswith(("float", "bfloat"))


def _spec_str(entries) -> str:
    parts = []
    for e in entries:
        if not e:
            parts.append("None")
        elif len(e) == 1:
            parts.append(f"'{e[0]}'")
        else:
            parts.append("(" + ",".join(f"'{a}'" for a in e) + ")")
    return "P(" + ", ".join(parts) + ")"


@dataclass
class SpmdReport:
    mesh_axes: Dict[str, int]
    specs: Dict[int, tuple] = field(default_factory=dict)
    var_names: Dict[int, str] = field(default_factory=dict)
    collectives: List[Collective] = field(default_factory=list)
    diagnostics: List[SpmdDiagnostic] = field(default_factory=list)
    hbm: Optional[dict] = None             # analyze_memory, per-device
    hbm_replicated: Optional[dict] = None  # same program, no sharding
    unknown_ops: set = field(default_factory=set)
    mesh_tiers: Dict[str, dict] = field(default_factory=dict)
    # ^ axis -> {"tier", "gbps"}; empty on a flat (single-tier) mesh
    dp_axes: Tuple[str, ...] = ()
    # ^ pure data-parallel axes: shard a feed but no persistable — the
    #   axes whose gradient sync the hierarchical decomposition targets

    def collective_bytes(self) -> int:
        return sum(c.bytes for c in self.collectives)

    def tier_bytes(self) -> Dict[str, int]:
        """Wire bytes per link tier (a collective counts toward the
        slowest tier it touches)."""
        out: Dict[str, int] = {}
        for c in self.collectives:
            out[c.tier] = out.get(c.tier, 0) + c.bytes
        return out

    def _axis_gbps(self, axis) -> float:
        gs = [float(self.mesh_tiers[ax]["gbps"])
              for ax in str(axis).split(",")
              if ax in self.mesh_tiers
              and float(self.mesh_tiers[ax].get("gbps", 0)) > 0]
        return min(gs) if gs else 0.0

    def weighted_collective_bytes(self, kind=None) -> float:
        """Collective bytes with each payload scaled by how much slower
        its link is than the fastest declared tier — the planner's
        topology-aware objective term. Equals collective_bytes() on a
        flat mesh, so single-tier plans and goldens are unchanged.
        `kind` restricts to one collective kind (e.g. "all_reduce")."""
        cs = [c for c in self.collectives
              if kind is None or c.kind == kind]
        if not self.mesh_tiers:
            return float(sum(c.bytes for c in cs))
        top = max((float(m.get("gbps", 0.0))
                   for m in self.mesh_tiers.values()), default=0.0)
        if top <= 0:
            return float(sum(c.bytes for c in cs))
        total = 0.0
        for c in cs:
            g = self._axis_gbps(c.axis)
            total += c.bytes * (top / g if g > 0 else 1.0)
        return total

    def hierarchical_sync(self, grad_bytes=None, k_steps=None
                          ) -> Optional[dict]:
        """Price the pure-dp gradient sync three ways over the two-tier
        mesh: a flat all-reduce over every dp axis, the hierarchical
        decomposition (reduce-scatter intra-pod -> inter-pod all-reduce
        over the 1/n shard -> all-gather intra-pod), and LocalSGD (flat
        sync every k steps). Per-device ring wire model: an all-reduce
        of B bytes over an axis of size s moves 2*B*(s-1)/s per device.
        `grad_bytes` defaults to the per-device param bytes from the HBM
        estimate. Returns None on a flat mesh or when no pure-dp axis
        exists."""
        from ..core.flags import flag as _flag
        tiers = self.mesh_tiers or {}
        if not tiers:
            return None
        dp = [a for a in self.dp_axes if a in self.mesh_axes]
        if not dp:
            return None
        if grad_bytes is None:
            grad_bytes = int((self.hbm or {}).get("param_bytes", 0))
        if k_steps is None:
            k_steps = int(_flag("FLAGS_topology_localsgd_k"))

        def meta(ax):
            return tiers.get(ax) or {
                "tier": "ici",
                "gbps": float(_flag("FLAGS_topology_ici_gbps"))}

        top = max((float(m.get("gbps", 0.0)) for m in tiers.values()),
                  default=0.0)
        slow = [a for a in dp if 0 < float(meta(a)["gbps"]) < top]
        fast = [a for a in dp if a not in slow]
        n = 1
        for a in fast:
            n *= self.mesh_axes[a]
        pods = 1
        for a in slow:
            pods *= self.mesh_axes[a]

        def ring(b, size):
            return 0 if size <= 1 else (2 * int(b) * (size - 1)) // size

        B = int(grad_bytes)
        flat = {"ici": ring(B, n), "dcn": ring(B, pods)}
        hier = {"ici": ring(B, n), "dcn": ring(B // max(n, 1), pods)}
        local = {t: b // max(int(k_steps), 1) for t, b in flat.items()}
        gs_fast = [float(meta(a)["gbps"]) for a in fast]
        gs_slow = [float(meta(a)["gbps"]) for a in slow]
        ici_g = min(gs_fast) if gs_fast else \
            float(_flag("FLAGS_topology_ici_gbps"))
        dcn_g = min(gs_slow) if gs_slow else \
            float(_flag("FLAGS_topology_dcn_gbps"))

        def cost(wire):
            return {"ici": wire["ici"] / (ici_g * 1e3) if ici_g else 0.0,
                    "dcn": wire["dcn"] / (dcn_g * 1e3) if dcn_g else 0.0}

        schemes = {}
        raw_costs = {}
        for name, wire in (("flat", flat), ("hierarchical", hier),
                           ("localsgd", local)):
            c = cost(wire)
            raw_costs[name] = c
            schemes[name] = {
                "wire_bytes": dict(wire),
                "cost_us": {k: round(v, 3) for k, v in c.items()},
                "total_cost_us": round(sum(c.values()), 3)}
        reduction = (flat["dcn"] / hier["dcn"]) if hier["dcn"] \
            else float(n if pods > 1 else 1)
        hc = raw_costs["hierarchical"]
        ratio = (hc["dcn"] / hc["ici"]) if hc["ici"] > 0 else None
        if pods <= 1 or n <= 1:
            # no slow boundary to hide, or no inner axis to shard the
            # inter-pod payload over — the decomposition buys nothing
            rec = "flat"
        elif ratio is not None and \
                ratio > float(_flag("FLAGS_topology_localsgd_ratio")):
            rec = "localsgd"
        else:
            rec = "hierarchical"
        return {"grad_bytes": B, "dp_axes": list(dp),
                "inner": {"axes": fast, "size": n},
                "outer": {"axes": slow, "size": pods},
                "schemes": schemes,
                "inter_pod_reduction_x": round(float(reduction), 3),
                "dcn_over_ici_cost":
                    round(ratio, 3) if ratio is not None else None,
                "recommendation": rec,
                "localsgd_k": int(k_steps)}

    def resharding_count(self) -> int:
        return sum(1 for d in self.diagnostics if d.code == "reshard")

    def spec_of(self, var) -> tuple:
        vid = getattr(var, "var_id", var)
        return self.specs.get(vid, ())

    def publish(self):
        """Export the spmd.* gauges (reference STAT_ADD dashboards)."""
        from ..core import monitor
        monitor.stat_set_many({
            "spmd.collective_bytes": self.collective_bytes(),
            "spmd.hbm_estimate":
                self.hbm["peak_bytes"] if self.hbm else 0,
            "spmd.resharding_count": self.resharding_count(),
        })

    def raise_on_findings(self):
        if not self.diagnostics:
            return self
        first = self.diagnostics[0]
        lines = [f"spmd-lint: {len(self.diagnostics)} finding(s):"]
        lines += [f"  {d}" for d in self.diagnostics]
        raise SpmdLintError("\n".join(lines), code=first.code,
                            op_name=first.op_name, op_index=first.op_index,
                            var=first.var, axis=first.axis)

    def quantized_savings(self, dtype="int8") -> Dict[str, dict]:
        """Per-mesh-axis wire-byte savings if every FLOAT collective were
        cast to `dtype` on the wire (EQuARX-style quantized AllReduce;
        integer payloads — index gathers etc. — are left untouched).
        Returns {axis: {bytes, bytes_quantized, saved}}."""
        out: Dict[str, dict] = {}
        for c in self.collectives:
            row = out.setdefault(c.axis, {"bytes": 0, "bytes_quantized": 0,
                                          "saved": 0})
            q = c.bytes_if(dtype) if c.is_float else c.bytes
            row["bytes"] += c.bytes
            row["bytes_quantized"] += q
            row["saved"] += c.bytes - q
        return out

    def render(self) -> str:
        """Human-readable report (tools/spmd_lint.py)."""
        lines = ["spmd analysis: mesh {" + ", ".join(
            f"{a}:{s}" for a, s in self.mesh_axes.items()) + "}"]
        if self.mesh_tiers:
            by_tier: Dict[tuple, List[str]] = {}
            for ax, m in self.mesh_tiers.items():
                by_tier.setdefault(
                    (str(m["tier"]), float(m["gbps"])), []).append(ax)
            lines.append("link tiers: " + "; ".join(
                f"{','.join(axs)}={t}@{g:g}GB/s"
                for (t, g), axs in sorted(by_tier.items())))
        if self.collectives:
            by_key: Dict[tuple, List[Collective]] = {}
            for c in self.collectives:
                by_key.setdefault((c.kind, c.axis), []).append(c)
            lines.append("collectives per step:")
            hdr = f"  {'kind':<12}{'axis':<8}{'count':>6}{'bytes':>14}"
            if self.mesh_tiers:
                hdr += f"{'tier':>6}{'cost_us':>10}"
            lines.append(hdr)
            for (kind, axis), cs in sorted(by_key.items()):
                row = (f"  {kind:<12}{axis:<8}{len(cs):>6}"
                       f"{sum(c.bytes for c in cs):>14}")
                if self.mesh_tiers:
                    row += (f"{cs[0].tier:>6}"
                            f"{sum(c.cost_us for c in cs):>10.1f}")
                lines.append(row)
            lines.append(f"collective bytes/step: {self.collective_bytes()}")
            if self.mesh_tiers:
                lines.append("wire bytes per tier: " + ", ".join(
                    f"{t}={b}" for t, b in sorted(
                        self.tier_bytes().items())))
            savings = self.quantized_savings("int8")
            if any(row["saved"] for row in savings.values()):
                lines.append("int8/fp8 quantized collectives would save "
                             "(per mesh axis, float payloads only):")
                for axis, row in sorted(savings.items()):
                    if not row["saved"]:
                        continue
                    ratio = row["bytes"] / max(row["bytes_quantized"], 1)
                    lines.append(
                        f"  axis {axis}: {row['bytes']} B -> "
                        f"{row['bytes_quantized']} B "
                        f"(saves {row['saved']} B, {ratio:.1f}x)")
        else:
            lines.append("collectives per step: none")
        if self.mesh_tiers:
            hs = self.hierarchical_sync()
            if hs:
                lines.append(
                    f"dp gradient sync ({'+'.join(hs['dp_axes'])}, "
                    f"{hs['grad_bytes']} B grads, per device):")
                for name in ("flat", "hierarchical", "localsgd"):
                    s = hs["schemes"][name]
                    lines.append(
                        f"  {name:<14}ici {s['wire_bytes']['ici']:>12} B"
                        f"  dcn {s['wire_bytes']['dcn']:>12} B"
                        f"  {s['total_cost_us']:>12.1f} us")
                lines.append(
                    "  hierarchical cuts inter-pod bytes "
                    f"{hs['inter_pod_reduction_x']:.1f}x vs flat; "
                    f"recommended: {hs['recommendation']}")
        if self.hbm:
            lines.append(
                f"per-device HBM estimate: peak {self.hbm['peak_bytes']} "
                f"(params {self.hbm['param_bytes']}, activations "
                f"{self.hbm['activation_peak_bytes']})")
            if self.hbm_replicated:
                lines.append("unsharded (replicated) peak: "
                             f"{self.hbm_replicated['peak_bytes']}")
        if self.unknown_ops:
            lines.append("ops with no spmd rule (sharded inputs dropped "
                         "to replicated): " + ", ".join(sorted(
                             self.unknown_ops)))
        if self.diagnostics:
            lines.append(f"diagnostics ({len(self.diagnostics)}):")
            lines += [f"  {d}" for d in self.diagnostics]
        else:
            lines.append("diagnostics: none")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# spec plumbing. Internally a spec is a tuple with one entry per dim, each
# entry a tuple of mesh-axis names (empty = replicated) — the normalized
# form of jax.sharding.PartitionSpec.
# ---------------------------------------------------------------------------

def _mesh_topology(mesh) -> Tuple[Dict[str, int], Dict[str, dict]]:
    """(axes, tiers) from a Mesh, an {axis: size-or-tier-dict} dict (no
    devices needed — lint a pod layout from a laptop), or the registered
    default. `tiers` is {} when the mesh is flat — every axis on the
    default tier at the default bandwidth — so single-tier reports stay
    byte-identical to pre-topology output."""
    from ..distributed import mesh as mesh_mod
    if mesh is None:
        mesh = mesh_mod.get_mesh()
    if mesh is None:
        return {}, {}
    if isinstance(mesh, dict):
        axes = mesh_mod.axis_sizes(mesh)
    else:
        axes = {str(n): int(mesh.shape[n]) for n in mesh.axis_names}
    tiers = mesh_mod.axis_tiers(mesh)
    base = mesh_mod._tier_gbps(mesh_mod.DEFAULT_TIER)
    if all(m["tier"] == mesh_mod.DEFAULT_TIER and
           float(m["gbps"]) == base for m in tiers.values()):
        tiers = {}
    return axes, tiers


def _mesh_axes(mesh) -> Dict[str, int]:
    """Axis name -> size (the size half of _mesh_topology)."""
    return _mesh_topology(mesh)[0]


def _norm_entry(e) -> tuple:
    if e is None:
        return ()
    if isinstance(e, str):
        return (e,)
    return tuple(e)


def _entries(spec) -> tuple:
    if spec is None:
        return ()
    return tuple(_norm_entry(e) for e in tuple(spec))


class _AV:
    """Abstract value during propagation: spec + aval (aval None for
    non-array literals)."""

    __slots__ = ("spec", "aval")

    def __init__(self, spec, aval):
        self.spec = spec
        self.aval = aval


def _aval_of(x):
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    return None


def _nbytes(aval) -> int:
    n = int(np.prod(aval.shape, dtype=np.int64)) if aval.shape else 1
    return n * np.dtype(aval.dtype).itemsize


def _lit(v, default=None):
    """Literal kwarg value (an _AV means a tensor slipped into an
    attr slot — fall back to the default)."""
    return default if isinstance(v, _AV) else v


class _Ctx:
    """Propagation context: mesh axes + the report being filled. Sub-block
    walks get a child with its OWN collective list (so branch sequences
    can be compared) but the shared diagnostic list."""

    def __init__(self, axes: Dict[str, int], report: SpmdReport,
                 collectives: Optional[list] = None, label: str = ""):
        self.axes = axes
        self.report = report
        self.collectives = report.collectives if collectives is None \
            else collectives
        self.label = label  # "cond#5/true/" inside sub-block walks
        self.op_index: Optional[int] = None
        self.op_name: Optional[str] = None
        self.tiers = report.mesh_tiers or {}
        gs = [float(m.get("gbps", 0.0)) for m in self.tiers.values()
              if float(m.get("gbps", 0.0)) > 0]
        self._top_gbps = max(gs, default=0.0)
        self.slow_axes = {ax for ax, m in self.tiers.items()
                          if 0 < float(m.get("gbps", 0.0)) < self._top_gbps}

    def child(self, label: str = ""):
        return _Ctx(self.axes, self.report, collectives=[],
                    label=self.label + label)

    def div(self, entry: tuple) -> int:
        d = 1
        for ax in entry:
            d *= self.axes.get(ax, 1)
        return d

    def spec_div(self, spec: tuple) -> int:
        d = 1
        for e in spec:
            d *= self.div(e)
        return d

    def payload(self, aval, spec, exclude=()) -> int:
        """Per-device payload bytes of `aval` under `spec`, not counting
        the axes in `exclude` (the axes doing the communicating)."""
        if aval is None:
            return 0
        d = 1
        for e in spec:
            for ax in e:
                if ax not in exclude:
                    d *= self.axes.get(ax, 1)
        return _nbytes(aval) // max(d, 1)

    def collective(self, kind, entry, bytes_, var=None, aval=None,
                   dtype=None):
        if dtype is None and aval is not None:
            dtype = np.dtype(aval.dtype).name
        axes = tuple(entry.split(",")) if isinstance(entry, str) \
            else tuple(entry)
        tier, cost = "ici", 0.0
        if self.tiers:
            metas = [self.tiers[ax] for ax in axes if ax in self.tiers]
            if metas:
                slowest = min(
                    metas, key=lambda m: float(m.get("gbps", 0.0)) or
                    float("inf"))
                tier = str(slowest.get("tier", tier))
                g = float(slowest.get("gbps", 0.0))
                cost = float(bytes_) / (g * 1e3) if g > 0 else 0.0
        self.collectives.append(Collective(
            kind=kind, axis=",".join(axes), bytes=int(bytes_),
            op_index=self.op_index, op_name=self.op_name, var=var,
            dtype=dtype, tier=tier, cost_us=cost))

    def diag(self, code, message, var=None, axis=None):
        self.report.diagnostics.append(SpmdDiagnostic(
            code=code, message=message, op_name=self.op_name,
            op_index=self.op_index, var=var, axis=axis))


def _validate_spec(ctx: _Ctx, spec_like, shape, var) -> tuple:
    """Normalize + validate a user/rule-supplied spec against a shape:
    rank, axis existence, duplicate axes, divisibility. Invalid entries
    degrade to replicated, each with a named diagnostic — the loud form
    of what sharding._validate_divisible used to do silently."""
    ents = list(_entries(spec_like))
    if len(ents) > len(shape):
        ctx.diag(
            "spec-rank",
            f"PartitionSpec {_spec_str(tuple(ents))} has {len(ents)} "
            f"entries for rank-{len(shape)} '{var}' — trailing axes "
            "would be silently dropped", var=var)
        ents = ents[:len(shape)]
    ents += [()] * (len(shape) - len(ents))
    seen: Dict[str, int] = {}
    out = []
    for d, ent in enumerate(ents):
        keep = []
        for ax in ent:
            if ax not in ctx.axes:
                ctx.diag(
                    "unbound-axis",
                    f"spec of '{var}' names axis '{ax}' but the mesh "
                    f"declares only {sorted(ctx.axes) or '(no axes)'}",
                    var=var, axis=ax)
                continue
            if ax in seen:
                ctx.diag(
                    "duplicate-axis",
                    f"axis '{ax}' appears on dims {seen[ax]} and {d} of "
                    f"the spec of '{var}' — one axis cannot shard two "
                    "dims", var=var, axis=ax)
                continue
            seen[ax] = d
            keep.append(ax)
        ent = tuple(keep)
        if ent and shape[d] % ctx.div(ent):
            ctx.diag(
                "non-divisible",
                f"dim {d} of '{var}' has size {shape[d]}, not divisible "
                f"by the size {ctx.div(ent)} of axis "
                f"{','.join(ent)} — GSPMD would pad and "
                "sharding._validate_divisible falls back to replication",
                var=var, axis=",".join(ent))
            ent = ()
        out.append(ent)
    return tuple(out)


# ---------------------------------------------------------------------------
# per-op propagation rules
# ---------------------------------------------------------------------------

SPMD_RULES: Dict[str, Any] = {}


def register_spmd_rule(*names):
    """Register a propagation rule: fn(ctx, ins, kw, out_avals, var) ->
    [spec, ...] (one per output). `ins` are the op's positional inputs as
    _AV (tensors) or raw literals; `kw` is the kwargs dict with tensor
    leaves as _AV."""
    def deco(fn):
        for n in names:
            SPMD_RULES[n] = fn
        return fn
    return deco


def _tensors(ins) -> List[_AV]:
    return [v for v in ins if isinstance(v, _AV) and v.aval is not None]


def _repl(aval) -> tuple:
    return ((),) * len(aval.shape)


def _merge_elementwise(ctx, ins, out_aval, var):
    """Right-aligned broadcast merge. A dim where two inputs carry
    different shardings is a conflict: the later operand is implicitly
    all-gathered (reported) and the dim stays with the first sharding."""
    nd = len(out_aval.shape)
    out = [()] * nd
    used: Dict[str, int] = {}
    for v in _tensors(ins):
        vnd = len(v.aval.shape)
        gathered = False
        for k in range(1, vnd + 1):
            ent = v.spec[vnd - k]
            if not ent or v.aval.shape[vnd - k] == 1:
                continue
            d = nd - k
            if out[d] == ent:
                continue
            if not out[d] and all(used.get(ax, d) == d for ax in ent):
                out[d] = ent
                for ax in ent:
                    used[ax] = d
            elif not gathered:
                gathered = True
                ctx.diag(
                    "reshard",
                    f"elementwise operands of '{ctx.op_name}' carry "
                    f"conflicting shardings on dim {d} "
                    f"({_spec_str((out[d],))} vs {_spec_str((ent,))}) — "
                    "an implicit all-gather reshard is required",
                    var=var, axis=",".join(ent))
                ctx.collective("all_gather", ent,
                               ctx.payload(v.aval, v.spec, exclude=ent),
                               var=var, aval=v.aval)
    return tuple(out)


@register_spmd_rule("add", "subtract", "multiply", "divide", "maximum",
                    "minimum", "floor_divide", "pow", "remainder", "where")
def _ew_rule(ctx, ins, kw, out_avals, var):
    return [_merge_elementwise(ctx, ins, out_avals[0], var)]


@register_spmd_rule("matmul")
def _matmul_rule(ctx, ins, kw, out_avals, var):
    x, y = ins[0], ins[1]
    out_aval = out_avals[0]
    if not isinstance(x, _AV) or not isinstance(y, _AV) \
            or x.aval is None or y.aval is None:
        return [_repl(out_aval)]
    xs, xsh = list(x.spec), list(x.aval.shape)
    ys, ysh = list(y.spec), list(y.aval.shape)
    if _lit(kw.get("transpose_x", False), False) and len(xsh) > 1:
        xsh[-1], xsh[-2] = xsh[-2], xsh[-1]
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if _lit(kw.get("transpose_y", False), False) and len(ysh) > 1:
        ysh[-1], ysh[-2] = ysh[-2], ysh[-1]
        ys[-1], ys[-2] = ys[-2], ys[-1]
    vec_x, vec_y = len(xsh) == 1, len(ysh) == 1
    if vec_x:
        xsh, xs = [1] + xsh, [()] + xs
    if vec_y:
        ysh, ys = ysh + [1], ys + [()]
    xc, yc = xs[-1], ys[-2]

    # assemble the full (padded) out spec: broadcast batch + row + col
    nb = max(len(xsh), len(ysh)) - 2
    batch = [()] * nb
    for spec, sh in ((xs, xsh), (ys, ysh)):
        bnd = len(sh) - 2
        for k in range(1, bnd + 1):
            ent = spec[bnd - k]
            if ent and sh[bnd - k] != 1 and not batch[nb - k]:
                batch[nb - k] = ent
    full = batch + [xs[-2], ys[-1]]

    if xc and yc and xc == yc:
        # true contraction sharding: partial sums -> all-reduce of the out
        out_spec_final = _finalize(ctx, full, vec_x, vec_y, out_aval,
                                   var=var)
        ctx.collective("all_reduce", xc,
                       ctx.payload(out_aval, out_spec_final), var=var,
                       aval=out_aval)
        return [out_spec_final]
    if xc or yc:
        if xc and yc:
            ctx.diag(
                "reshard",
                f"matmul contraction dim is sharded on DIFFERENT axes "
                f"({','.join(xc)} on x vs {','.join(yc)} on y) — both "
                "operands must be implicitly all-gathered before the "
                "matmul", var=var, axis=",".join(xc + yc))
        else:
            ent = xc or yc
            which, other = ("x", "y") if xc else ("y", "x")
            ctx.diag(
                "reshard",
                f"matmul contraction dim is sharded ({','.join(ent)}) on "
                f"operand {which} but replicated on {other} — an "
                "implicit all-gather reshard precedes the matmul",
                var=var, axis=",".join(ent))
        for side, ent in ((x, xc), (y, yc)):
            if ent:
                ctx.collective("all_gather", ent,
                               ctx.payload(side.aval, side.spec,
                                           exclude=ent), var=var,
                               aval=side.aval)
    return [_finalize(ctx, full, vec_x, vec_y, out_aval, var=var)]


def _finalize(ctx, full, vec_x, vec_y, out_aval, var=None):
    """Drop the padded vector dims and de-duplicate axes across dims (an
    axis cannot shard two output dims — e.g. a dp-sharded batch meeting
    a dp-column-sharded weight). The drop is NOT free: the operand that
    loses its sharding must be re-laid-out, so each dropped axis is
    PRICED as a reshard + all-gather of the output over that axis —
    otherwise a layout search against this cost model would "win" by
    sharding every weight on the batch axis at no modeled cost."""
    if vec_y:
        full = full[:-1]
    if vec_x:
        full = full[:-2] + full[-1:] if not vec_y else full[:-1]
    seen: set = set()
    out = []
    dropped: list = []
    for d, ent in enumerate(full):
        kept = tuple(ax for ax in ent if ax not in seen)
        seen.update(kept)
        out.append(kept)
        dropped += [(d, ax) for ax in ent if ax not in kept]
    out = (out + [()] * len(out_aval.shape))[:len(out_aval.shape)]
    out = tuple(out)
    for d, ax in dropped:
        ctx.diag(
            "reshard",
            f"matmul output dim {d} would reuse axis '{ax}', already "
            "sharding an earlier output dim — one axis cannot shard two "
            "dims, so the conflicting operand sharding is implicitly "
            "all-gathered", var=var, axis=ax)
        ctx.collective("all_gather", (ax,),
                       ctx.payload(out_aval, out, exclude=(ax,)),
                       var=var, aval=out_aval)
    return out


@register_spmd_rule("embedding")
def _embedding_rule(ctx, ins, kw, out_avals, var):
    w, ids = ins[0], ins[1]
    out_aval = out_avals[0]
    if not isinstance(w, _AV) or w.aval is None:
        return [_repl(out_aval)]
    v_ent = w.spec[0] if w.spec else ()
    d_ent = w.spec[1] if len(w.spec) > 1 else ()
    ids_spec = ids.spec if isinstance(ids, _AV) and ids.aval is not None \
        else ((),) * (len(out_aval.shape) - 1)
    used = {ax for e in ids_spec for ax in e}
    if v_ent and any(ax in used for ax in v_ent):
        # vocab-parallel over an axis that ALSO shards the ids: the
        # masked-partial all-reduce would mix different batch rows —
        # GSPMD must all-gather the table instead
        drop = tuple(ax for ax in v_ent if ax in used)
        ctx.diag(
            "reshard",
            f"embedding weight '{var}' is vocab-sharded on axis "
            f"{','.join(drop)} which also shards the ids — the "
            "vocab-parallel gather cannot reduce across it; the table "
            "is implicitly all-gathered", var=var, axis=",".join(drop))
        ctx.collective("all_gather", drop,
                       ctx.payload(w.aval, w.spec, exclude=drop),
                       var=var, aval=w.aval)
        v_ent = tuple(ax for ax in v_ent if ax not in drop)
    if d_ent and any(ax in used for ax in d_ent):
        # the embed-dim sharding collides with an id-batch axis: the
        # gather result cannot carry one axis on two dims — priced like
        # the matmul _finalize drop, not silently free
        drop = tuple(ax for ax in d_ent if ax in used)
        ctx.diag(
            "reshard",
            f"embedding output embed dim would reuse axis "
            f"{','.join(drop)}, already sharding the id batch — the "
            "weight's embed-dim sharding is implicitly all-gathered",
            var=var, axis=",".join(drop))
        d_ent = tuple(ax for ax in d_ent if ax not in used)
        out_probe = tuple(ids_spec) + (d_ent,)
        ctx.collective("all_gather", drop,
                       ctx.payload(out_aval, out_probe, exclude=drop),
                       var=var, aval=out_aval)
    out_spec = tuple(ids_spec) + (d_ent,)
    out_spec = (out_spec + ((),) * len(out_aval.shape))[
        :len(out_aval.shape)]
    if v_ent:
        # vocab-parallel gather: each shard contributes its rows, the
        # masked partial results sum across the vocab axis
        ctx.collective("all_reduce", v_ent,
                       ctx.payload(out_aval, out_spec), var=var,
                       aval=out_aval)
    return [out_spec]


def _dim_groups(in_shape, out_shape):
    """Decompose a reshape into (in_dims, out_dims) groups of equal
    element count — the standard composition used for sharding remap."""
    groups = []
    i = j = 0
    ni, nj = len(in_shape), len(out_shape)
    while i < ni or j < nj:
        gi, gj = [], []
        pi = pj = 1
        if i < ni:
            gi.append(i)
            pi = in_shape[i]
            i += 1
        if j < nj:
            gj.append(j)
            pj = out_shape[j]
            j += 1
        while pi != pj:
            if pi < pj and i < ni:
                pi *= in_shape[i]
                gi.append(i)
                i += 1
            elif pj < pi and j < nj:
                pj *= out_shape[j]
                gj.append(j)
                j += 1
            else:
                break
        # absorb trailing size-1 dims into the current group
        while i < ni and in_shape[i] == 1 and (pi == pj):
            gi.append(i)
            i += 1
        while j < nj and out_shape[j] == 1 and (pi == pj):
            gj.append(j)
            j += 1
        groups.append((gi, gj))
    return groups


def _reshape_like_rule(ctx, ins, kw, out_avals, var):
    """reshape/flatten/squeeze/unsqueeze: a sharded in-dim survives when
    it leads its factor group and the group's leading out-dim stays
    divisible; otherwise the tensor is implicitly all-gathered."""
    x = ins[0]
    out_aval = out_avals[0]
    if not isinstance(x, _AV) or x.aval is None:
        return [_repl(out_aval)]
    in_shape = tuple(x.aval.shape)
    out_shape = tuple(out_aval.shape)
    out = [()] * len(out_shape)
    for gi, gj in _dim_groups(in_shape, out_shape):
        sharded = [(d, x.spec[d]) for d in gi if x.spec[d]]
        if not sharded:
            continue
        nontrivial = [d for d in gi if in_shape[d] != 1]
        lead = nontrivial[0] if nontrivial else gi[0]
        ent = tuple(ax for _, e in sharded for ax in e)
        ok = (len(sharded) == 1 and sharded[0][0] == lead) or \
            all(d == nontrivial[k] for k, (d, _) in enumerate(sharded))
        if ok and gj and out_shape[gj[0]] % ctx.div(ent) == 0:
            out[gj[0]] = ent
        else:
            ctx.diag(
                "reshard",
                f"'{ctx.op_name}' {in_shape} -> {out_shape} cannot carry "
                f"the sharding {_spec_str(x.spec)} through (sharded dim "
                "does not map to a divisible output dim) — implicit "
                "all-gather", var=var, axis=",".join(ent))
            ctx.collective("all_gather", ent,
                           ctx.payload(x.aval, x.spec, exclude=ent),
                           var=var, aval=x.aval)
    return [tuple(out)]


for _n in ("reshape", "flatten", "squeeze", "unsqueeze"):
    SPMD_RULES[_n] = _reshape_like_rule


@register_spmd_rule("transpose")
def _transpose_rule(ctx, ins, kw, out_avals, var):
    x = ins[0]
    out_aval = out_avals[0]
    if not isinstance(x, _AV) or x.aval is None:
        return [_repl(out_aval)]
    nd = len(x.aval.shape)
    perm = kw.get("perm", None)
    if perm is None and len(ins) > 1:
        perm = _lit(ins[1])
    if perm is None:
        perm = list(range(nd))[::-1]
    return [tuple(x.spec[int(p) % nd] for p in perm)]


@register_spmd_rule("concat", "stack")
def _concat_rule(ctx, ins, kw, out_avals, var):
    out_aval = out_avals[0]
    tens = _tensors(ins)
    if not tens:
        return [_repl(out_aval)]
    nd_out = len(out_aval.shape)
    axis = int(_lit(kw.get("axis", 0), 0)) % max(nd_out, 1)
    stacked = ctx.op_name == "stack"
    out = [()] * nd_out
    used: Dict[str, int] = {}
    for v in tens:
        for d_in, ent in enumerate(v.spec):
            if not ent:
                continue
            d = d_in + 1 if stacked and d_in >= axis else d_in
            if not stacked and d == axis:
                ctx.diag(
                    "reshard",
                    f"concat along sharded dim {d} ({','.join(ent)}) — "
                    "the pieces must be all-gathered to concatenate",
                    var=var, axis=",".join(ent))
                ctx.collective("all_gather", ent,
                               ctx.payload(v.aval, v.spec, exclude=ent),
                               var=var, aval=v.aval)
                continue
            if not out[d] and all(used.get(ax, d) == d for ax in ent):
                out[d] = ent
                for ax in ent:
                    used[ax] = d
            elif out[d] != ent:
                ctx.diag(
                    "reshard",
                    f"'{ctx.op_name}' inputs disagree on dim {d} sharding "
                    f"({_spec_str((out[d],))} vs {_spec_str((ent,))}) — "
                    "implicit all-gather", var=var, axis=",".join(ent))
                ctx.collective("all_gather", ent,
                               ctx.payload(v.aval, v.spec, exclude=ent),
                               var=var, aval=v.aval)
    return [tuple(out)]


@register_spmd_rule("split_op", "unbind_op")
def _split_rule(ctx, ins, kw, out_avals, var):
    x = ins[0]
    if not isinstance(x, _AV) or x.aval is None:
        return [_repl(oa) for oa in out_avals]
    nd = len(x.aval.shape)
    axis = ins[2] if len(ins) > 2 else kw.get("axis", 0)
    axis = int(_lit(axis, 0)) % max(nd, 1)
    outs = []
    for oa in out_avals:
        spec = list(x.spec)
        if ctx.op_name == "unbind_op":
            spec = spec[:axis] + spec[axis + 1:]
        elif spec[axis]:
            ent = spec[axis]
            if len(oa.shape) > axis and oa.shape[axis] % ctx.div(ent):
                ctx.diag(
                    "non-divisible",
                    f"split section of size {oa.shape[axis]} on dim "
                    f"{axis} is not divisible by axis {','.join(ent)} "
                    f"(size {ctx.div(ent)})", var=var, axis=",".join(ent))
                spec[axis] = ()
        spec = (spec + [()] * len(oa.shape))[:len(oa.shape)]
        outs.append(tuple(spec))
    return outs


@register_spmd_rule("sum", "mean", "max", "min", "prod", "all", "any")
def _reduce_rule(ctx, ins, kw, out_avals, var):
    x = ins[0]
    out_aval = out_avals[0]
    if not isinstance(x, _AV) or x.aval is None:
        return [_repl(out_aval)]
    nd = len(x.aval.shape)
    axis = _lit(kw.get("axis", None))
    keepdim = bool(_lit(kw.get("keepdim", False), False))
    if axis is None:
        axes = tuple(range(nd))
    else:
        axes = axis if isinstance(axis, (tuple, list)) else [axis]
        axes = tuple(int(a) % nd for a in axes)
    red = set(axes)
    comm = tuple(ax for d in red for ax in (x.spec[d] if d < nd else ()))
    out = []
    for d in range(nd):
        if d in red:
            if keepdim:
                out.append(())
        else:
            out.append(x.spec[d])
    out = (out + [()] * len(out_aval.shape))[:len(out_aval.shape)]
    if comm:
        ctx.collective("all_reduce", comm,
                       ctx.payload(out_aval, tuple(out)), var=var,
                       aval=out_aval)
    return [tuple(out)]


@register_spmd_rule("softmax", "log_softmax")
def _softmax_rule(ctx, ins, kw, out_avals, var):
    x = ins[0]
    out_aval = out_avals[0]
    if not isinstance(x, _AV) or x.aval is None:
        return [_repl(out_aval)]
    nd = len(x.aval.shape)
    axis = int(_lit(kw.get("axis", -1), -1)) % max(nd, 1)
    spec = list(x.spec)
    if spec[axis]:
        # the online max/sum reduce across the sharded softmax dim
        ctx.collective("all_reduce", spec[axis],
                       ctx.payload(out_aval, tuple(
                           e for d, e in enumerate(spec) if d != axis)),
                       var=var, aval=out_aval)
    return [tuple(spec)]


@register_spmd_rule("layer_norm")
def _layer_norm_rule(ctx, ins, kw, out_avals, var):
    x = ins[0]
    out_aval = out_avals[0]
    if not isinstance(x, _AV) or x.aval is None:
        return [_repl(out_aval)]
    nd = len(x.aval.shape)
    bna = int(_lit(kw.get("begin_norm_axis", -1), -1))
    axes = tuple(range(bna % nd, nd)) if bna != -1 else (nd - 1,)
    spec = list(x.spec)
    for d in axes:
        if spec[d]:
            ctx.diag(
                "reshard",
                f"layer_norm normalizes over sharded dim {d} "
                f"({','.join(spec[d])}) — the moments need an implicit "
                "all-gather/all-reduce", var=var, axis=",".join(spec[d]))
            ctx.collective("all_gather", spec[d],
                           ctx.payload(x.aval, x.spec, exclude=spec[d]),
                           var=var, aval=x.aval)
            spec[d] = ()
    return [tuple(spec)]


@register_spmd_rule("sdpa")
def _sdpa_rule(ctx, ins, kw, out_avals, var):
    q = ins[0]
    if isinstance(q, _AV) and q.aval is not None:
        return [tuple(q.spec)]
    return [_repl(out_avals[0])]


@register_spmd_rule("fused_ce_op", "ce_head_fallback")
def _fused_ce_rule(ctx, ins, kw, out_avals, var):
    hidden, weight = ins[0], ins[1]
    out_aval = out_avals[0]
    out_spec = _repl(out_aval)
    if isinstance(hidden, _AV) and hidden.aval is not None:
        out_spec = (tuple(hidden.spec[:len(out_aval.shape)])
                    + ((),) * len(out_aval.shape))[:len(out_aval.shape)]
    if isinstance(weight, _AV) and weight.aval is not None \
            and weight.spec and weight.spec[0]:
        # vocab-parallel head: the logsumexp reduces across the vocab axis
        ctx.collective("all_reduce", weight.spec[0],
                       ctx.payload(out_aval, out_spec), var=var,
                       aval=out_aval)
    return [out_spec]


def _moe_capacity(xv_aval, kw, e_total) -> int:
    """The capacity moe.MoELayer computes at run time, re-derived from
    the recorded avals: int(cap_factor * tokens / num_experts) + 1."""
    tokens = 1
    for s in xv_aval.shape[:-1]:
        tokens *= int(s)
    cap_factor = float(_lit(kw.get("cap_factor", 1.25), 1.25))
    return int(cap_factor * tokens / max(int(e_total), 1)) + 1


@register_spmd_rule("moe_layer")
def _moe_rule(ctx, ins, kw, out_avals, var):
    """Expert parallelism (distributed/moe.py MoELayer): the stacked
    expert weights ([E, d, h] / [E, h] — dim 0 is the expert dim) may
    shard over the layer's `axis` kwarg (conventionally 'ep'); routed
    tokens then move through TWO all-to-alls (dispatch and combine) of
    the [E, capacity, d] dispatch tensor. Tokens/output keep the input's
    sharding. Expert weights disagreeing on the expert-dim axis, or an
    expert axis that also shards the tokens, are conflicts (reshard)."""
    out_aval = out_avals[0]
    if len(ins) < 6 or not isinstance(ins[0], _AV) or ins[0].aval is None:
        return [_repl(out_aval)]
    xv, gate_w = ins[0], ins[1]
    experts = [v for v in ins[2:6] if isinstance(v, _AV)
               and v.aval is not None]
    x_spec = tuple(xv.spec)
    token_axes = {ax for e in x_spec for ax in e}

    # the expert-dim sharding all four stacked weights must agree on
    ep_ent: tuple = ()
    for w in experts:
        ent = w.spec[0] if w.spec else ()
        if ent and not ep_ent:
            ep_ent = ent
        elif ent and ent != ep_ent:
            ctx.diag(
                "reshard",
                f"moe expert weights disagree on the expert-dim sharding "
                f"({_spec_str((ep_ent,))} vs {_spec_str((ent,))}) — the "
                "divergent weight is implicitly all-gathered", var=var,
                axis=",".join(ent))
            ctx.collective("all_gather", ent,
                           ctx.payload(w.aval, w.spec, exclude=ent),
                           var=var, aval=w.aval)
    if ep_ent and any(ax in token_axes for ax in ep_ent):
        drop = tuple(ax for ax in ep_ent if ax in token_axes)
        ctx.diag(
            "reshard",
            f"moe expert axis {','.join(drop)} also shards the tokens — "
            "the all-to-all dispatch cannot route across it; the expert "
            "stacks are implicitly all-gathered", var=var,
            axis=",".join(drop))
        for w in experts:
            if w.spec and w.spec[0]:
                ctx.collective("all_gather", drop,
                               ctx.payload(w.aval, w.spec, exclude=drop),
                               var=var, aval=w.aval)
        ep_ent = ()
    if isinstance(gate_w, _AV) and gate_w.aval is not None \
            and any(gate_w.spec):
        gent = tuple(ax for e in gate_w.spec for ax in e)
        ctx.diag(
            "reshard",
            "moe gate weight is sharded — the router runs replicated, so "
            "the gate is implicitly all-gathered", var=var,
            axis=",".join(gent))
        ctx.collective("all_gather", gent,
                       ctx.payload(gate_w.aval, gate_w.spec, exclude=gent),
                       var=var, aval=gate_w.aval)

    if ep_ent:
        # dispatch + combine: each device exchanges its slice of the
        # [E, capacity, d] routed-token tensor with every peer on the
        # expert axis — per-device wire bytes = tensor * (ep-1)/ep
        e_total = int(_lit(kw.get("e_total", 0), 0)) \
            or int(experts[0].aval.shape[0])
        cap = _moe_capacity(xv.aval, kw, e_total)
        d_model = int(xv.aval.shape[-1])
        payload = jax.ShapeDtypeStruct((e_total, cap, d_model),
                                       xv.aval.dtype)
        ep = ctx.div(ep_ent)
        wire = (_nbytes(payload) * max(ep - 1, 0)) // max(ep, 1)
        for _ in ("dispatch", "combine"):
            ctx.collective("all_to_all", ep_ent, wire, var=var,
                           aval=payload)
    out_spec = (x_spec + ((),) * len(out_aval.shape))[:len(out_aval.shape)]
    return [out_spec]


def _default_rule(ctx, ins, kw, out_avals, var):
    """Shape-matching pass-through: each output adopts the spec of the
    first input with the same shape (covers unary/activation/cast/dropout
    ops without bespoke rules); otherwise replicated, and the op is noted
    when that silently drops a sharding."""
    tens = _tensors(ins) + [v for v in kw.values() if isinstance(v, _AV)
                            and v.aval is not None]
    outs = []
    for oa in out_avals:
        pick = None
        for v in tens:
            if tuple(v.aval.shape) == tuple(oa.shape):
                pick = tuple(v.spec)
                if any(v.spec):
                    break
        if pick is None:
            pick = _repl(oa)
            if any(any(e) for v in tens for e in v.spec):
                ctx.report.unknown_ops.add(ctx.op_name)
        outs.append(pick)
    return outs


# ---------------------------------------------------------------------------
# per-op FLOPs model — the compute half of the cost model. Closed forms
# over recorded avals (no tracing), the way analyze_memory estimates
# bytes: exact for the matmul-class ops that dominate, nelems-scale for
# everything else. Forward-pass numbers; training backward is a uniform
# ~2x on the same ops, so stage-BALANCE (what the pipeline planner
# optimizes) is unchanged by the factor.
# ---------------------------------------------------------------------------

FLOP_RULES: Dict[str, Any] = {}


def register_flop_rule(*names):
    """Register a FLOPs rule: fn(in_avals, kw, out_avals) -> float.
    `in_avals` are the op's positional inputs (avals or raw literals),
    `kw` the kwargs dict with tensor leaves as avals."""
    def deco(fn):
        for n in names:
            FLOP_RULES[n] = fn
        return fn
    return deco


def _numel(aval) -> int:
    if aval is None or not hasattr(aval, "shape"):
        return 0
    n = 1
    for s in aval.shape:
        n *= int(s)
    return n


def _is_shaped(x) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


@register_flop_rule("matmul")
def _matmul_flops(ins, kw, out_avals):
    x = ins[0] if ins and _is_shaped(ins[0]) else None
    if x is None or not x.shape:
        return float(_numel(out_avals[0]))
    k = x.shape[-2] if (kw.get("transpose_x", False) and len(x.shape) > 1) \
        else x.shape[-1]
    return 2.0 * _numel(out_avals[0]) * int(k)


@register_flop_rule("sdpa")
def _sdpa_flops(ins, kw, out_avals):
    q = ins[0] if ins and _is_shaped(ins[0]) else None
    k = ins[1] if len(ins) > 1 and _is_shaped(ins[1]) else None
    if q is None:
        return float(_numel(out_avals[0]))
    s_kv = int(k.shape[-2]) if k is not None and len(k.shape) >= 2 \
        else int(q.shape[-2])
    return 4.0 * _numel(q) * s_kv  # QK^T + AV, 2 flops/MAC each


@register_flop_rule("fused_ce_op", "ce_head_fallback")
def _ce_flops(ins, kw, out_avals):
    hidden = ins[0] if ins and _is_shaped(ins[0]) else None
    w = ins[1] if len(ins) > 1 and _is_shaped(ins[1]) else None
    if hidden is None or w is None:
        return float(_numel(out_avals[0]))
    rows = _numel(hidden) // max(int(hidden.shape[-1]), 1)
    vocab = int(w.shape[0])
    return 2.0 * rows * int(hidden.shape[-1]) * vocab


@register_flop_rule("embedding")
def _embedding_flops(ins, kw, out_avals):
    return float(_numel(out_avals[0]))  # a gather: ~1 op per element


@register_flop_rule("moe_layer")
def _moe_flops(ins, kw, out_avals):
    xv = ins[0] if ins and _is_shaped(ins[0]) else None
    w_up = ins[2] if len(ins) > 2 and _is_shaped(ins[2]) else None
    if xv is None or w_up is None:
        return float(_numel(out_avals[0]))
    d = int(xv.shape[-1])
    tokens = _numel(xv) // max(d, 1)
    e_total = int(_lit(kw.get("e_total", 0), 0)) or int(w_up.shape[0])
    h = int(w_up.shape[-1])
    cap = _moe_capacity(xv, kw, e_total)
    gate = 2.0 * tokens * d * e_total
    route = 2.0 * 2.0 * tokens * e_total * cap * d  # dispatch + combine
    ffn = 2.0 * 2.0 * e_total * cap * d * h         # up + down
    return gate + route + ffn


def analyze_flops(program: Program) -> dict:
    """Per-top-level-op forward FLOPs from the recorded avals.

    Returns {"per_op": [float, one per program.ops entry], "total"}.
    Ops without a dedicated rule price at the element count of their
    largest operand/output (the elementwise/normalization scale); the
    matmul-class rules above carry the balance signal the pipeline
    stage-cut planner (static/spmd_planner.plan_pipeline) optimizes.
    """
    import jax.tree_util as jtu

    env: Dict[int, Any] = {}
    for v in program.data_vars.values():
        env[v.var_id] = v.aval
    for scope_name, vid in program.persist_ids.items():
        pv = program.persistable_vars.get(scope_name)
        if pv is not None:
            env[vid] = pv.aval

    per_op: List[float] = []
    for op in program.ops:
        vals = []
        for x in op.flat:
            if isinstance(x, _Ref):
                vals.append(env.get(x.var_id))
            else:
                vals.append(_aval_of(x) if _aval_of(x) is not None else x)
        ins = vals[:op.n_args]
        try:
            kw = jtu.tree_unflatten(op.kw_tree, vals[op.n_args:])
        except Exception:
            kw = {}
        if not isinstance(kw, dict):
            kw = {}
        out_avals = [v.aval for v in op.out_vars]
        rule = FLOP_RULES.get(op.name)
        if rule is not None:
            fl = float(rule(ins, kw, out_avals))
        else:
            ops_scale = [_numel(a) for a in out_avals]
            ops_scale += [_numel(v) for v in ins if _is_shaped(v)]
            fl = float(max(ops_scale or [0]))
        per_op.append(fl)
        for oid, oaval in zip(op.out_ids, out_avals):
            env[oid] = oaval
    return {"per_op": per_op, "total": float(sum(per_op))}


# ---------------------------------------------------------------------------
# the walker
# ---------------------------------------------------------------------------

def _walk(ops, env_spec, env_aval, ctx: _Ctx, names: Dict[int, str]):
    import jax.tree_util as jtu
    from .control_flow import _CondFn, _WhileFn

    for i, op in enumerate(ops):
        # inside a sub-block, op_index counts WITHIN the block and the
        # label carries the path ("cond#5/true/matmul"), so a finding
        # points at the actual inner op, not the enclosing cond
        ctx.op_index = i
        ctx.op_name = ctx.label + op.name if ctx.label else op.name
        vals = []
        for x in op.flat:
            if isinstance(x, _Ref):
                aval = env_aval.get(x.var_id)
                spec = env_spec.get(x.var_id,
                                    _repl(aval) if aval is not None else ())
                vals.append(_AV(spec, aval))
            else:
                aval = _aval_of(x)
                vals.append(_AV(_repl(aval), aval) if aval is not None
                            else x)
        out_avals = [v.aval for v in op.out_vars]
        out_var_names = [v.name for v in op.out_vars]
        var0 = out_var_names[0] if out_var_names else None

        if isinstance(op.fn, (_CondFn, _WhileFn)):
            out_specs = _control_flow(ctx, op, vals, env_aval, names)
        else:
            ins = vals[:op.n_args]
            kw_leaves = vals[op.n_args:]
            try:
                kw = jtu.tree_unflatten(op.kw_tree, kw_leaves)
            except Exception:
                kw = {}
            if not isinstance(kw, dict):
                kw = {}
            rule = SPMD_RULES.get(op.name, _default_rule)
            out_specs = rule(ctx, ins, kw, out_avals, var0)

        for oid, oname, oaval, ospec in zip(op.out_ids, out_var_names,
                                            out_avals, out_specs):
            # rule outputs re-validated: divisibility of the produced
            # sharding against the actual output shape
            ospec = tuple(ospec) + ((),) * (len(oaval.shape) - len(ospec))
            checked = []
            for d, ent in enumerate(ospec[:len(oaval.shape)]):
                ent = _norm_entry(ent)
                if ent and oaval.shape[d] % ctx.div(ent):
                    ctx.diag(
                        "non-divisible",
                        f"dim {d} of '{oname}' (size {oaval.shape[d]}) "
                        f"is not divisible by axis {','.join(ent)} "
                        f"(size {ctx.div(ent)})", var=oname,
                        axis=",".join(ent))
                    ent = ()
                checked.append(ent)
            env_spec[oid] = tuple(checked)
            env_aval[oid] = oaval
            names[oid] = oname


def _control_flow(ctx: _Ctx, op, vals, env_aval, names):
    """cond / while_loop: propagate into the sub-blocks and enforce the
    single-program-SPMD invariant — both cond branches must imply the
    SAME collective sequence (pipeline.py documents this; GSPMD cannot
    partition rank-divergent collective orders)."""
    from .control_flow import _CondFn, _WhileFn
    fn = op.fn
    out_avals = [v.aval for v in op.out_vars]

    def run_block(blk, carried, label):
        es: Dict[int, tuple] = {}
        ea: Dict[int, Any] = {}
        for vid, av in zip(blk.in_ids, carried):
            # a carry initial may be a plain Python literal (int step
            # counters are legal loop vars) — not an _AV
            es[vid] = av.spec if isinstance(av, _AV) \
                and av.aval is not None else ()
            ea[vid] = av.aval if isinstance(av, _AV) else None
        n_free = len(blk.free_ids)
        free = vals[op.n_args - n_free:op.n_args] if n_free else []
        for vid, av in zip(blk.free_ids, free):
            if isinstance(av, _AV):
                es[vid] = av.spec
                ea[vid] = av.aval
        sub = ctx.child(label=label)
        _walk(blk.ops, es, ea, sub, names)
        out_specs = [es.get(oid, ()) for oid in blk.out_ids]
        return sub.collectives, out_specs

    if isinstance(fn, _CondFn):
        t_coll, t_out = run_block(fn.true_block, [],
                                  f"{op.name}#{ctx.op_index}/true/")
        f_coll, f_out = run_block(fn.false_block, [],
                                  f"{op.name}#{ctx.op_index}/false/")
        t_sig = [(c.kind, c.axis) for c in t_coll]
        f_sig = [(c.kind, c.axis) for c in f_coll]
        if t_sig != f_sig:
            ctx.op_name = op.name
            ctx.diag(
                "collective-divergence",
                "cond branches imply different collective sequences "
                f"(true: {t_sig or '[]'}, false: {f_sig or '[]'}) — under "
                "single-program SPMD every rank traces ONE program, so "
                "branch-divergent collectives cannot be partitioned",
                var=op.out_vars[0].name if op.out_vars else None)
        ctx.op_name = op.name
        ctx.collectives.extend(t_coll)
        out_specs = []
        for ts, fs, oa in zip(t_out, f_out, out_avals):
            ts = tuple(ts) + ((),) * (len(oa.shape) - len(ts))
            fs = tuple(fs) + ((),) * (len(oa.shape) - len(fs))
            out_specs.append(tuple(t if t == f else ()
                                   for t, f in zip(ts, fs)))
        return out_specs

    # while_loop: body collectives repeat per iteration (count them once
    # — trip counts are dynamic); the carry spec must be loop-stable
    carried = vals[:fn.n_loop]
    b_coll, b_out = run_block(fn.body_block, carried,
                              f"{op.name}#{ctx.op_index}/body/")
    ctx.op_name = op.name
    ctx.collectives.extend(b_coll)
    out_specs = []
    for av, bs, oa in zip(carried, b_out, out_avals):
        ins = av.spec if isinstance(av, _AV) and av.aval is not None else ()
        ins = tuple(ins) + ((),) * (len(oa.shape) - len(ins))
        bs = tuple(bs) + ((),) * (len(oa.shape) - len(bs))
        out_specs.append(tuple(i if i == b else ()
                               for i, b in zip(ins, bs)))
    return out_specs


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _derive_param_specs(program: Program, axes: Dict[str, int]):
    """Fallback spec source: the sharding-rule name patterns applied to
    each persistable's var name (sharding.named_param_specs supplies
    dotted-name specs when a Layer is available)."""
    if not axes:
        return {}
    from ..distributed import sharding as sharding_mod
    meshlike = sharding_mod.mesh_like(dict(axes))
    out = {}
    for scope_name, pv in program.persistable_vars.items():
        out[scope_name] = sharding_mod.param_spec_for(
            pv.name, len(pv.aval.shape), meshlike)
    return out


def analyze_program(program: Program, mesh=None, param_specs=None,
                    data_specs=None) -> SpmdReport:
    """Propagate PartitionSpecs over a static Program.

    mesh: a jax Mesh, an {axis: size} dict (device-free — lint a pod
    layout anywhere), or None for the registered default mesh.
    param_specs: {scope_name | var name: PartitionSpec} for persistables
    (default: sharding-rule patterns against var names).
    data_specs: {data var name: PartitionSpec} for feeds (default
    replicated; shard the batch dim along 'dp' for dp analysis).

    Returns an SpmdReport: resolved specs per var, the implied collective
    set, the diagnostic list, and per-device/replicated HBM estimates.
    """
    axes, tiers = _mesh_topology(mesh)
    report = SpmdReport(mesh_axes=dict(axes), mesh_tiers=tiers)
    ctx = _Ctx(axes, report)
    if param_specs is None:
        param_specs = _derive_param_specs(program, axes)
    param_specs = dict(param_specs or {})
    data_specs = dict(data_specs or {})

    env_spec: Dict[int, tuple] = {}
    env_aval: Dict[int, Any] = {}
    names: Dict[int, str] = {}
    for name, v in program.data_vars.items():
        ctx.op_name = None
        ctx.op_index = None
        spec = data_specs.get(name)
        env_spec[v.var_id] = _validate_spec(ctx, spec, v.aval.shape, name) \
            if spec is not None else _repl(v.aval)
        env_aval[v.var_id] = v.aval
        names[v.var_id] = name
    for scope_name, vid in program.persist_ids.items():
        pv = program.persistable_vars.get(scope_name)
        if pv is None:
            continue
        ctx.op_name = None
        ctx.op_index = None
        spec = param_specs.get(scope_name, param_specs.get(pv.name))
        env_spec[vid] = _validate_spec(ctx, spec, pv.aval.shape,
                                       scope_name) \
            if spec is not None else _repl(pv.aval)
        env_aval[vid] = pv.aval
        names[vid] = scope_name

    _walk(program.ops, env_spec, env_aval, ctx, names)

    report.specs = env_spec
    report.var_names = names

    # Pure data-parallel axes: shard a feed but no persistable. Their
    # steady-state traffic is the gradient sync — the one flow that MAY
    # cross a slow tier (hierarchically); everything else that touches a
    # slow-tier link every step is a layout mistake, flagged below.
    data_axes: set = set()
    persist_axes: set = set()
    for v in program.data_vars.values():
        for e in env_spec.get(v.var_id, ()):
            data_axes.update(e)
    for vid in program.persist_ids.values():
        for e in env_spec.get(vid, ()):
            persist_axes.update(e)
    report.dp_axes = tuple(sorted(data_axes - persist_axes))

    if ctx.slow_axes:
        exempt = set(report.dp_axes)
        for c in report.collectives:
            for ax in str(c.axis).split(","):
                if ax in ctx.slow_axes and ax not in exempt:
                    report.diagnostics.append(SpmdDiagnostic(
                        code="cross-tier",
                        message=f"{c.kind} of '{c.var}' rides slow-tier "
                                f"axis '{ax}' "
                                f"({ctx.tiers[ax]['tier']}) every step — "
                                "keep model parallelism intra-pod; only "
                                "the dp gradient sync should cross the "
                                "slow tier, and hierarchically",
                        op_name=c.op_name, op_index=c.op_index,
                        var=c.var, axis=ax))

    divisors = {vid: ctx.spec_div(spec) for vid, spec in env_spec.items()}
    from .shape_infer import analyze_memory
    try:
        report.hbm = analyze_memory(program, env=env_aval,
                                    shard_divisors=divisors)
        report.hbm_replicated = analyze_memory(program, env=env_aval)
    except Exception:
        report.hbm = None  # memory estimate is best-effort decoration
    return report


def analyze_params(params, mesh=None, specs=None, tokens_per_step=None,
                   zero_dp=False) -> SpmdReport:
    """The dygraph/hapi half: validate a param tree's specs and estimate
    the TP collective set from the sharding-rule name patterns, without a
    recorded Program.

    params: {dotted_name: array | aval | Variable} (e.g. from
    `dict(layer.named_parameters())`). specs: {dotted_name:
    PartitionSpec} (default: sharding.param_spec_for per name).
    tokens_per_step: activation row count (batch*seq) for the step —
    prices each row-parallel all-reduce / vocab-parallel gather; bytes
    are 0 when omitted (counts still reported).
    """
    from ..distributed import sharding as sharding_mod

    axes, tiers = _mesh_topology(mesh)
    report = SpmdReport(mesh_axes=dict(axes), mesh_tiers=tiers)
    ctx = _Ctx(axes, report)
    meshlike = sharding_mod.mesh_like(dict(axes))
    param_bytes = 0
    for name, p in params.items():
        aval = _aval_of(p) or _aval_of(getattr(p, "aval", None))
        if aval is None:
            continue
        spec = (specs or {}).get(name)
        if spec is None:
            spec = sharding_mod.param_spec_for(name, len(aval.shape),
                                               meshlike, zero_dp=zero_dp)
        ctx.op_name = None
        norm = _validate_spec(ctx, spec, aval.shape, name)
        report.specs[id(p)] = norm
        report.var_names[id(p)] = name
        param_bytes += _nbytes(aval) // max(ctx.spec_div(norm), 1)
        itemsize = np.dtype(aval.dtype).itemsize
        rows = int(tokens_per_step or 0)
        if len(aval.shape) >= 2 and norm[0]:
            if sharding_mod._match(name, sharding_mod.VOCAB_PARALLEL):
                ctx.collective("all_reduce", norm[0],
                               rows * aval.shape[1] * itemsize, var=name,
                               aval=aval)
            elif sharding_mod._match(name, sharding_mod.ROW_PARALLEL):
                ctx.collective("all_reduce", norm[0],
                               rows * aval.shape[1] * itemsize, var=name,
                               aval=aval)
    report.hbm = {"peak_bytes": param_bytes, "param_bytes": param_bytes,
                  "feed_bytes": 0, "activation_peak_bytes": 0,
                  "timeline": [], "peak_op": None}
    return report


# ---------------------------------------------------------------------------
# the PADDLE_TPU_VERIFY_SPMD hook (mirrors passes.py VERIFY_PASSES)
# ---------------------------------------------------------------------------

_verify_override = None


def verify_spmd_enabled() -> bool:
    if _verify_override is not None:
        return _verify_override
    return os.environ.get("PADDLE_TPU_VERIFY_SPMD", "0").strip().lower() \
        not in ("0", "false", "off", "")


def set_verify_spmd(enabled):
    """Force the hook on/off from code (None restores the env-var
    default); returns the previous override."""
    global _verify_override
    old = _verify_override
    _verify_override = None if enabled is None else bool(enabled)
    return old


def maybe_verify_spmd(program: Program, mesh=None) -> Optional[SpmdReport]:
    """Run the analyzer when PADDLE_TPU_VERIFY_SPMD is on; raise
    SpmdLintError on any finding — BEFORE the program reaches jit, where
    the same mistake surfaces as an opaque XLA error or a silent
    replication. Publishes the spmd.* monitor gauges either way."""
    if not verify_spmd_enabled():
        return None
    param_specs = getattr(program, "spmd_param_specs", None)
    if mesh is None:
        from ..distributed import mesh as mesh_mod
        mesh = mesh_mod.get_mesh()
    if mesh is None and not param_specs:
        return None  # nothing declares sharding; nothing to lint
    report = analyze_program(
        program, mesh=mesh, param_specs=param_specs,
        data_specs=getattr(program, "spmd_data_specs", None))
    report.publish()
    if report.diagnostics:
        report.raise_on_findings()
    return report
