"""Static-graph AMP (reference python/paddle/static/amp ->
fluid/contrib/mixed_precision/decorator.py).

Design delta: no program rewriting with cast ops. The Program records
dtype-agnostic kernels; `decorate` tags the Program with an AMP policy and
the Executor applies per-op input casts (amp.policy_dtype over the same
white/black lists as eager auto_cast) while lowering the whole program into
one jitted step — the casts fuse away in XLA.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..amp import GradScaler, black_list, white_list  # noqa: F401
from .program import default_main_program

__all__ = ["decorate", "CustomOpLists", "AutoMixedPrecisionLists"]


class AutoMixedPrecisionLists:
    """reference fluid/contrib/mixed_precision/fp16_lists.py."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = white_list() | set(custom_white_list or ())
        self.black_list = (black_list() | set(custom_black_list or ())) \
            - set(custom_white_list or ())


CustomOpLists = AutoMixedPrecisionLists


class _AmpOptimizer:
    """Wraps an optimizer so minimize() tags the program with the policy
    (reference decorator.py OptimizerWithMixedPrecision)."""

    def __init__(self, optimizer, amp_lists, level, dtype,
                 use_dynamic_loss_scaling, init_loss_scaling,
                 scaling_hparams=None):
        self._opt = optimizer
        self._amp_lists = amp_lists
        self._level = level
        self._dtype = jnp.bfloat16 if str(dtype) in ("bfloat16", "bf16") \
            else jnp.float16
        # bf16 covers f32's exponent range: loss scaling is a no-op for it.
        # fp16 threads (scale, good_steps, bad_steps) through the compiled
        # step — the in-program form of the reference's
        # check_finite_and_unscale + update_loss_scaling op pair
        # (contrib/mixed_precision/decorator.py) — updates are skipped on
        # overflow steps and the scale adapts.
        self._dynamic = bool(use_dynamic_loss_scaling) \
            and self._dtype == jnp.float16
        self._init_loss_scaling = init_loss_scaling
        self._scaling_hparams = dict(scaling_hparams or {})

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        program = getattr(loss, "program", None) or default_main_program()
        program.amp_level = self._level
        program.amp_dtype = self._dtype
        if self._amp_lists is not None:
            program.amp_lists = (frozenset(self._amp_lists.white_list),
                                 frozenset(self._amp_lists.black_list))
        program.amp_dynamic_scaling = self._dynamic
        program.amp_scaling_hparams = dict(self._scaling_hparams,
                                           init=self._init_loss_scaling)
        return self._opt.minimize(loss, startup_program=startup_program,
                                  parameters=parameters,
                                  no_grad_set=no_grad_set)

    def __getattr__(self, item):
        return getattr(self._opt, item)


def decorate(optimizer, amp_lists=None, level="O1", dtype="bfloat16",
             init_loss_scaling=2.0 ** 15, use_dynamic_loss_scaling=True,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.5, **kwargs):
    """paddle.static.amp.decorate: returns an optimizer whose minimize()
    enables AMP for the whole program (fp16 adds in-program dynamic loss
    scaling)."""
    if level not in ("O1", "O2"):
        raise ValueError(f"amp level must be O1/O2, got {level!r}")
    hparams = {"incr_every_n_steps": incr_every_n_steps,
               "decr_every_n_nan_or_inf": decr_every_n_nan_or_inf,
               "incr_ratio": incr_ratio, "decr_ratio": decr_ratio}
    return _AmpOptimizer(optimizer, amp_lists, level, dtype,
                         use_dynamic_loss_scaling, init_loss_scaling,
                         scaling_hparams=hparams)
