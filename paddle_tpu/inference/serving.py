"""Continuous-batching decode serving — the online inference tier.

`GPT.generate` is one-model-one-call: a fixed batch prefills together,
decodes together, and every row that finishes early (or never existed)
still burns a slot until the longest row is done. This module turns the
same kernel-fast decode path into a SERVER:

- **paged KV pool** (nn/kv_pool.py): all in-flight requests share one
  physical block arena per layer; per-request block tables make ragged
  lengths free and retiring requests return their blocks to the pool
  immediately;
- **prefill/decode split with admission scheduling**: new requests are
  admitted when a slot AND enough pool blocks are free, prefilled as a
  bucketed single-request pass (logits read at the real last prompt
  token), then join the ONE fused decode batch that advances every
  active stream one token per step through the block-table Pallas
  kernel (per-step KV reads scale with live blocks, not max_seq_len);
- **async pipeline**: decode steps dispatch through the PR 5
  `InflightDriver` (static/pipeline_runner.py), so dispatch of step N+1
  overlaps sampling/detokenization-side bookkeeping of step N; failures
  surface as `PipelineStepError` naming the step;
- **backpressure + preemption**: when the pool is exhausted, admissions
  queue; when an ACTIVE stream cannot grow into a new block, the
  youngest active stream is evicted (blocks freed, request re-queued
  with its generated prefix — greedy/fold-in sampling makes the replay
  deterministic) so the oldest stream always completes.

Per-request sampling keys fold `PRNGKey(seed)` with the absolute token
position, so a stream's tokens do not depend on which batch it rides in
or whether it was preempted. Greedy (temperature=0) continuous-batched
decode is token-identical to per-request sequential `GPT.generate`
(tests/test_serving.py proves it bitwise).

Two seams close the serve→train→serve loop (docs/online_learning.md):

- **completion records**: every request that finishes cleanly emits a
  structured record (id, prompt/generated ids, pinned snapshot version,
  ttft/per-token timings) through the `on_complete` hook at retire —
  the input contract of `dataset/streaming.StreamingDataset`. A hook
  error is counted (`serve.completion_log_errors`) and swallowed; a
  logging bug never fails serving.
- **zero-downtime hot-swap**: `publish_weights(version, updates)`
  stages a versioned weight swap; the scheduler applies it between
  decode beats once every in-flight stream has retired. While a swap
  is staged admission pauses — queued requests WAIT (nothing is ever
  dropped) and each in-flight stream finishes on the version pinned at
  its first admission.

Observability: spans `serve/{admit,prefill,decode_step,retire,evict,
hot_swap}` with a per-request flow chain, gauges `serve.{queue_depth,
active_slots,kv_pool_used_blocks,kv_pool_free_blocks,model_version}`,
counters `serve.{preempted,tokens_generated,requests_completed,
requests_errored,hot_swaps,completion_log_errors}`, histograms
`serve/ttft_ms` and `serve/token_ms` — rendered by tools/obs_report.py's
serving section and snapshotted by BENCH_MODE=serve.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass

import numpy as np

__all__ = ["ServeConfig", "ServeRequest", "ServeLoop",
           "build_decode_step"]

GAUGES = ("serve.queue_depth", "serve.active_slots",
          "serve.kv_pool_used_blocks", "serve.kv_pool_free_blocks",
          "serve.model_version")
COUNTERS = ("serve.preempted", "serve.tokens_generated",
            "serve.requests_completed", "serve.requests_errored",
            "serve.hot_swaps", "serve.completion_log_errors",
            "serve.backpressure_waits")

_REQ_IDS = itertools.count()


@dataclass
class ServeConfig:
    """Knobs for one ServeLoop. Zeros mean "take the FLAGS_serve_*
    default" (core/flags.py) so a deployment can be tuned per-job via
    env without touching code."""

    max_active: int = 0     # decode slots (FLAGS_serve_max_active)
    kv_blocks: int = 0      # pool blocks (FLAGS_serve_kv_blocks)
    block_size: int = 0     # tokens/block (FLAGS_serve_block_size / auto)
    max_seq_len: int = 0    # per-request cap (0 = model max_seq_len)
    temperature: float = 0.0
    top_k: int = None
    eos_token_id: int = None   # default; per-request override wins
    max_inflight: int = 0      # decode pipeline depth (0 = executor flag)

    def resolve(self, net):
        from ..core import flags as _flags
        cfg = net.config
        max_active = int(self.max_active
                         or _flags.flag("FLAGS_serve_max_active"))
        kv_blocks = int(self.kv_blocks
                        or _flags.flag("FLAGS_serve_kv_blocks"))
        max_seq = int(self.max_seq_len or cfg.max_seq_len)
        max_seq = min(max_seq, cfg.max_seq_len)
        if self.block_size:
            block_size = int(self.block_size)
        else:
            from ..nn.kv_pool import pick_block_size
            block_size = pick_block_size(
                max_seq, cfg.num_heads, cfg.hidden_size // cfg.num_heads)
        max_inflight = int(self.max_inflight
                           or _flags.flag("FLAGS_executor_max_inflight"))
        return max_active, kv_blocks, block_size, max_seq, \
            max(1, max_inflight)


class ServeRequest:
    """One generate stream. Clients hold this as a future: `result()`
    blocks until the stream finishes (or raises its error)."""

    def __init__(self, prompt, max_new_tokens, eos_token_id, seed):
        self.rid = next(_REQ_IDS)
        self.prompt = np.asarray(prompt, np.int64).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_token_id = eos_token_id
        self.seed = int(seed)
        self.out = []            # generated token ids (host ints)
        self.error = None
        self.preemptions = 0
        self.snapshot_version = None  # model version pinned at 1st admit
        self.t_submit = time.perf_counter()
        self.t_first = None      # first generated token materialized
        self.t_done = None
        self._done = threading.Event()

    # -- future API ---------------------------------------------------------
    @property
    def done(self):
        return self._done.is_set()

    def wait(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.rid} still in flight")
        return self

    def result(self, timeout=None):
        """Generated tokens [n] (prompt excluded). Raises the request's
        error if serving failed it."""
        self.wait(timeout)
        if self.error is not None:
            raise self.error
        return np.asarray(self.out, np.int64)

    # -- latency metrics ----------------------------------------------------
    @property
    def ttft_s(self):
        return None if self.t_first is None else self.t_first - self.t_submit

    @property
    def per_token_s(self):
        if self.t_done is None or self.t_first is None or len(self.out) < 2:
            return None
        return (self.t_done - self.t_first) / (len(self.out) - 1)

    # -- completion record ---------------------------------------------------
    def completion_record(self):
        """Structured retire-time record — the StreamingDataset input
        contract (docs/online_learning.md). Host ints/floats only, so
        records serialize/queue without holding device buffers."""
        return {
            "rid": int(self.rid),
            "prompt": [int(t) for t in self.prompt.tolist()],
            "tokens": [int(t) for t in self.out],
            "version": self.snapshot_version,
            "preemptions": int(self.preemptions),
            "t_submit": self.t_submit,
            "t_first": self.t_first,
            "t_done": self.t_done,
            "ttft_s": self.ttft_s,
            "per_token_s": self.per_token_s,
        }


def _sampler(temperature, top_k):
    """Per-row sampler: greedy at temperature=0, else categorical keyed
    by fold_in(request_key, absolute token position) — batch-composition
    independent and preemption-replay stable."""
    import jax
    import jax.numpy as jnp

    if temperature == 0:
        def greedy(logits, keys, positions):
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return greedy

    def sample(logits, keys, positions):
        def one(lg, key, pos):
            k = jax.random.fold_in(key, pos)
            lg = lg.astype(jnp.float32) / temperature
            if top_k is not None:
                kth = jax.lax.top_k(lg, int(top_k))[0][-1]
                lg = jnp.where(lg < kth, -1e9, lg)
            return jax.random.categorical(k, lg).astype(jnp.int32)
        return jax.vmap(one)(logits, keys, positions)
    return sample


def build_decode_step(net, temperature=0.0, top_k=None):
    """The UN-jitted fused decode step: every active stream advances one
    token. (params, buffers, arenas, block_tables, lengths, tokens,
    keys) -> (new_arenas, next_tokens). Exposed at module level so
    tools/hlo_evidence.py can AOT-lower the PRODUCTION step — the
    evidence cannot drift from the loop."""
    import jax.numpy as jnp

    from ..core import tape as _tape
    from ..nn.kv_pool import PagedKVCache

    samp = _sampler(temperature, top_k)

    def decode_step(params, buffers, arenas, block_tables, lengths,
                    tokens, keys):
        with _tape.no_grad():
            net.load_functional_state(params, buffers)
            caches = [PagedKVCache(k, v, block_tables, lengths)
                      for (k, v) in arenas]
            logits, new_caches = net._forward_paged(tokens[:, None],
                                                    caches)
            nxt = samp(logits, keys, lengths + jnp.int32(1))
        return [(c.k, c.v) for c in new_caches], nxt

    return decode_step


def _build_prefill(net, temperature, top_k):
    """The UN-jitted bucketed prefill: one request's (padded) prompt
    writes its k/v into the pool blocks and samples the first token,
    which is also spliced into the fused batch's token carry at `slot`.
    (params, buffers, arenas, tokens, bt_row, ids, real_len, key, slot)
    -> ((new_arenas, new_tokens), first_token)."""
    import jax.numpy as jnp

    from ..core import tape as _tape
    from ..nn.kv_pool import PagedKVCache

    samp = _sampler(temperature, top_k)

    def prefill(params, buffers, arenas, tokens, bt_row, ids, real_len,
                key, slot):
        with _tape.no_grad():
            net.load_functional_state(params, buffers)
            caches = [PagedKVCache(k, v, bt_row, jnp.zeros((1,),
                                                           jnp.int32))
                      for (k, v) in arenas]
            logits, new_caches = net._forward_paged(
                ids, caches, last_index=jnp.reshape(real_len, (1,)) - 1)
            first = samp(logits, key[None], jnp.reshape(real_len,
                                                        (1,)))[0]
            tokens = tokens.at[slot].set(first)
        return ([(c.k, c.v) for c in new_caches], tokens), first

    return prefill


class _Slot:
    __slots__ = ("req", "length", "blocks", "version", "admit_seq",
                 "key")

    def __init__(self, req, blocks, version, admit_seq, key):
        self.req = req
        self.length = 0          # tokens written into the cache
        self.blocks = blocks     # physical block ids (pool-owned)
        self.version = version
        self.admit_seq = admit_seq
        self.key = key           # raw uint32[2] PRNGKey data


class ServeLoop:
    """Continuous-batching server over one (eval-mode) GPT-style model.

    Batch use:  `ServeLoop(net).serve(prompts)` drives the caller thread.
    Server use: `start()` spawns the scheduler thread; any number of
    client threads `submit(...).result()`. `stop()` drains and joins.
    """

    def __init__(self, net, config=None, on_complete=None, **overrides):
        import jax
        import jax.numpy as jnp

        from ..core import flags as _flags  # noqa: F401 (resolve below)
        from ..nn.kv_pool import KVBlockPool
        from ..static.pipeline_runner import _FLOW_NS, InflightDriver

        self.net = net
        self.config = config or ServeConfig(**overrides)
        if overrides and config is not None:
            raise ValueError("pass either a ServeConfig or kwargs")
        (self._A, n_blocks, self._bs, self._cap,
         self._max_inflight) = self.config.resolve(net)
        cfg = net.config
        if net.training:
            net.eval()  # decode kernels are eval-only; serving never drops
        self._pool = KVBlockPool(n_blocks, self._bs)
        self._MB = -(-self._cap // self._bs)     # block-table width
        self._params, self._buffers = net.functional_state()
        self._dtype = jnp.bfloat16 if any(
            v.dtype == jnp.bfloat16 for v in self._params.values()) \
            else jnp.float32
        heads, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        self._arenas = self._pool.arenas(cfg.num_layers, heads, hd,
                                         self._dtype)
        self._tokens = jnp.zeros((self._A,), jnp.int32)
        self._driver = InflightDriver("serve",
                                      max_inflight=self._max_inflight)
        self._flow_base = next(_FLOW_NS) << 42  # per-request flow chain

        step = build_decode_step(net, self.config.temperature,
                                 self.config.top_k)
        # donate the big arenas only: the [A] token carry is ALSO step
        # N's fetch, and donating it into step N+1 would delete the
        # buffer out from under the in-flight FetchHandle
        self._step_jit = jax.jit(step, donate_argnums=(2,))
        pf = _build_prefill(net, self.config.temperature,
                            self.config.top_k)
        self._prefill_jit = jax.jit(pf, donate_argnums=(2,))
        self._traced = set()   # (kind, bucket) keys already traced

        self._slots = [None] * self._A
        self._queue: deque = deque()
        self._pending: deque = deque()  # settle entries, driver order
        self._on_complete = on_complete  # completion-record hook
        self.model_version = 0           # published weight version
        self._staged_swap = None         # (version, {name: np rows})
        self._version = 0
        self._admit_seq = 0
        self._step_count = 0
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._thread = None
        self._stopping = False

    # -- public API ----------------------------------------------------------
    def submit(self, prompt, max_new_tokens=32, eos_token_id=None,
               seed=0):
        """Enqueue one generate stream; returns its ServeRequest
        future. Thread-safe."""
        eos = self.config.eos_token_id if eos_token_id is None \
            else eos_token_id
        req = ServeRequest(prompt, max_new_tokens, eos, seed)
        total = req.prompt.size + req.max_new_tokens
        if total > self._cap:
            raise ValueError(
                f"request needs {total} tokens > serving cap {self._cap}")
        if self._pool.blocks_for(total) > self._pool.n_blocks:
            raise ValueError(
                f"request needs {self._pool.blocks_for(total)} blocks > "
                f"pool size {self._pool.n_blocks}")
        with self._work:
            self._queue.append(req)
            self._work.notify_all()
        return req

    def serve(self, prompts, **kw):
        """Batch convenience: submit every prompt, drive the scheduler
        on the caller thread until idle, return the generated-token
        arrays in order."""
        if self._thread is not None:
            raise RuntimeError("serve() on a started loop; use submit()")
        reqs = [self.submit(p, **kw) for p in prompts]
        self.run_until_idle()
        return [r.result(timeout=0) for r in reqs]

    def run_until_idle(self):
        """Drive scheduler ticks on the caller thread until no queued,
        active, or in-flight work remains."""
        while self._has_work():
            self._tick()
        self._drain()

    def start(self):
        """Background-server mode: scheduler runs on its own thread."""
        if self._thread is not None:
            return self
        self._stopping = False
        self._thread = threading.Thread(target=self._serve_forever,
                                        daemon=True, name="serve-loop")
        self._thread.start()
        return self

    def stop(self, timeout=30):
        """Finish in-flight + queued work, then stop the thread. Raises
        on timeout instead of orphaning the scheduler — clearing
        `_thread` while it still runs would let a later start() race a
        second scheduler over the (single-owner) pool and slots."""
        t = self._thread
        if t is None:
            return
        with self._work:
            self._stopping = True
            self._work.notify_all()
        t.join(timeout)
        if t.is_alive():
            raise TimeoutError(
                f"serve loop did not drain within {timeout}s "
                f"({self.stats()})")
        self._thread = None

    def stats(self):
        return {
            "queue_depth": len(self._queue),
            "active_slots": sum(s is not None for s in self._slots),
            "kv_pool_used_blocks": self._pool.used_blocks,
            "kv_pool_free_blocks": self._pool.free_blocks,
            "steps": self._step_count,
            "block_size": self._bs,
            "max_active": self._A,
            "model_version": self.model_version,
            "swap_staged": self._staged_swap is not None,
        }

    def publish_weights(self, version, updates):
        """Stage a versioned weight hot-swap: `updates` maps functional-
        state param names (see `net.functional_state()`) to replacement
        arrays. Validated (name + shape) on the caller thread; APPLIED
        by the scheduler between decode beats once every in-flight
        stream has retired. While a swap is staged, admission pauses —
        queued requests wait (the pool never drops a request) and each
        in-flight stream finishes on the version pinned at its first
        admission. Staging a second swap before the first applies
        replaces it (last publish wins). Thread-safe."""
        staged = {}
        for name, arr in dict(updates).items():
            if name not in self._params:
                raise KeyError(f"unknown param {name!r} "
                               f"(not in functional_state)")
            arr = np.asarray(arr)
            want = tuple(self._params[name].shape)
            if tuple(arr.shape) != want:
                raise ValueError(f"shape {tuple(arr.shape)} for "
                                 f"{name!r} != served {want}")
            staged[name] = arr
        with self._work:
            self._staged_swap = (int(version), staged)
            self._work.notify_all()
        return self

    # -- scheduler ----------------------------------------------------------
    def _has_work(self):
        return bool(self._queue or self._pending
                    or self._staged_swap is not None
                    or any(s is not None for s in self._slots))

    def _serve_forever(self):
        while True:
            with self._work:
                while not self._has_work() and not self._stopping:
                    self._work.wait(timeout=0.05)
                if self._stopping and not self._has_work():
                    return
            self._tick()

    def _tick(self):
        """One scheduler beat: settle enough of the pipeline to bound
        the window, admit, grow/preempt, dispatch the next fused decode
        step (N+1 overlapping the settle of step N)."""
        # testing/faults.py ("serve", "beat") boundary: a scripted STALL
        # here models a hung scheduler beat (the latency fault the SLO
        # drill scripts a TTFT breach against). Transport-shaped chaos
        # (RESET/DROP) has no meaning at a scheduler beat and is
        # absorbed — the streaming deliver boundary does the same.
        try:
            from ..distributed.ps.rpc import _fault
            _fault("serve", "beat", "tick")
        except ConnectionError:
            pass
        while len(self._pending) >= self._max_inflight:
            self._settle_one()
        if self._staged_swap is not None:
            # drain barrier: no admission while a swap is staged —
            # active streams run to retirement on the pinned version,
            # then the swap applies and admission resumes
            if any(s is not None for s in self._slots):
                self._grow_or_preempt()
                self._dispatch_decode()
            elif self._pending:
                self._settle_one()
            else:
                self._apply_swap()
            self._publish_gauges()
            return
        self._admit()
        if any(s is not None for s in self._slots):
            self._grow_or_preempt()
            self._dispatch_decode()
        elif self._pending:
            self._settle_one()
        self._publish_gauges()

    def _drain(self):
        while self._pending:
            self._settle_one()
        self._publish_gauges()

    def _apply_swap(self):
        """The hot-swap itself, between beats with nothing in flight:
        rebind the published params in the functional state. No arena /
        block state is touched — the KV pool is version-agnostic (only
        FUTURE prefills/decodes read the new weights, and the drain
        barrier guarantees there are no other kind)."""
        import jax.numpy as jnp

        from ..core import monitor as _monitor
        from ..core import trace as _trace
        version, updates = self._staged_swap
        self._staged_swap = None
        with _trace.span("serve/hot_swap", version=version,
                         params=len(updates)):
            for name, arr in updates.items():
                self._params[name] = jnp.asarray(
                    arr, self._params[name].dtype)
            self.net.load_functional_state(self._params, self._buffers)
            self.model_version = int(version)
            _monitor.stat_add("serve.hot_swaps")

    # -- admission / prefill -------------------------------------------------
    def _free_slot(self):
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _admit(self):
        from ..core import monitor
        from ..core import trace as _trace
        while True:
            with self._lock:
                req = self._queue[0] if self._queue else None
            if req is None:
                return
            idx = self._free_slot()
            if idx is None:
                monitor.stat_add("serve.backpressure_waits")
                return
            prompt = np.concatenate(
                [req.prompt, np.asarray(req.out, np.int64)]) \
                if req.out else req.prompt
            remaining = req.max_new_tokens - len(req.out)
            need_total = self._pool.blocks_for(prompt.size + remaining)
            # BACKPRESSURE: the head of the queue waits (FCFS — no
            # starvation of long requests) until retiring streams free
            # enough blocks for its whole worst case
            if not self._pool.can_alloc(need_total):
                monitor.stat_add("serve.backpressure_waits")
                return
            with self._lock:
                self._queue.popleft()
            blocks = self._pool.alloc(self._pool.blocks_for(prompt.size))
            with _trace.span("serve/admit", req=req.rid, slot=idx,
                             prompt_len=int(prompt.size),
                             blocks=len(blocks)) as sp:
                sp.flow(self._flow_base + req.rid, "s")
                import jax
                if req.snapshot_version is None:
                    req.snapshot_version = self.model_version
                self._version += 1
                self._admit_seq += 1
                key = np.asarray(jax.random.PRNGKey(req.seed),
                                 np.uint32)
                slot = _Slot(req, blocks, self._version,
                             self._admit_seq, key)
                self._slots[idx] = slot
                self._dispatch_prefill(idx, slot, prompt)

    def _bucket(self, n):
        b = 8
        while b < n:
            b *= 2
        return b

    def _call_traced(self, fn, key, *args):
        """Call a jitted fn; after its FIRST trace (which rebinds the
        live layers' parameters to tracers) restore the real arrays so
        eager use of the net keeps working (same contract as
        GPT._generate_cached)."""
        if key in self._traced:
            return fn(*args)
        try:
            return fn(*args)
        finally:
            self.net.load_functional_state(self._params, self._buffers)
            self._traced.add(key)

    def _dispatch_prefill(self, idx, slot, prompt):
        import jax.numpy as jnp

        from ..core import trace as _trace
        req = slot.req
        s_real = int(prompt.size)
        bucket = self._bucket(s_real)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :s_real] = prompt
        bt_row = np.zeros((1, self._MB), np.int32)
        bt_row[0, :len(slot.blocks)] = slot.blocks
        with _trace.span("serve/prefill", req=req.rid, slot=idx,
                         prompt_len=s_real, bucket=bucket) as sp:
            sp.flow(self._flow_base + req.rid, "t")

            def thunk():
                carry, first = self._call_traced(
                    self._prefill_jit, ("prefill", bucket),
                    self._params, self._buffers, self._arenas,
                    self._tokens, jnp.asarray(bt_row), jnp.asarray(ids),
                    jnp.int32(s_real), jnp.asarray(slot.key),
                    jnp.int32(idx))
                return carry, [first]

            carry, handles = self._driver.submit(thunk, kind="prefill",
                                                 req=req.rid)
        if carry is not None:
            self._arenas, self._tokens = carry
        slot.length = s_real
        self._pending.append(("prefill", handles, req, idx,
                              slot.version))

    # -- growth / preemption -------------------------------------------------
    def _youngest_active(self):
        best = None
        for i, s in enumerate(self._slots):
            if s is not None and (best is None
                                  or s.admit_seq
                                  > self._slots[best].admit_seq):
                best = i
        return best

    def _grow_or_preempt(self):
        """Every active slot writes its next token at position `length`
        this step; make sure the covering block exists, evicting the
        youngest stream when the pool is dry (oldest always wins)."""
        order = sorted((i for i, s in enumerate(self._slots)
                        if s is not None),
                       key=lambda i: self._slots[i].admit_seq)
        for idx in order:
            slot = self._slots[idx]
            if slot is None:          # evicted by an earlier iteration
                continue
            need_blk = slot.length // self._bs
            while need_blk >= len(slot.blocks):
                got = self._pool.alloc(1)
                if got is not None:
                    slot.blocks.extend(got)
                    continue
                victim = self._youngest_active()
                self._preempt(victim)
                if victim == idx:
                    break             # preempted ourselves; slot is gone

    def _preempt(self, idx):
        from ..core import monitor as _monitor
        from ..core import trace as _trace
        slot = self._slots[idx]
        req = slot.req
        with _trace.span("serve/evict", req=req.rid, slot=idx,
                         generated=len(req.out),
                         blocks=len(slot.blocks)) as sp:
            sp.flow(self._flow_base + req.rid, "t")
            self._pool.free(slot.blocks)
            self._slots[idx] = None
            req.preemptions += 1
            _monitor.stat_add("serve.preempted")
            with self._lock:
                # back to the head: it is older than everything queued,
                # and its re-prefill (prompt + generated prefix) replays
                # the same token stream
                self._queue.appendleft(req)

    # -- decode dispatch -----------------------------------------------------
    def _dispatch_decode(self):
        import jax.numpy as jnp

        from ..core import trace as _trace
        A, MB = self._A, self._MB
        lengths = np.zeros((A,), np.int32)
        bt = np.zeros((A, MB), np.int32)
        keys = np.zeros((A, 2), np.uint32)
        snapshot = []
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            lengths[i] = s.length
            bt[i, :len(s.blocks)] = s.blocks
            keys[i] = s.key
            snapshot.append((i, s.req, s.version))
        step_idx = self._step_count
        self._step_count += 1
        with _trace.span("serve/decode_step", step=step_idx,
                         active=len(snapshot)):

            def thunk():
                arenas, nxt = self._call_traced(
                    self._step_jit, ("decode",),
                    self._params, self._buffers, self._arenas,
                    jnp.asarray(bt), jnp.asarray(lengths), self._tokens,
                    jnp.asarray(keys))
                return (arenas, nxt), [nxt]

            carry, handles = self._driver.submit(thunk, kind="decode",
                                                 active=len(snapshot))
        if carry is not None:
            self._arenas, self._tokens = carry
        for i, _req, _ver in snapshot:
            self._slots[i].length += 1
        self._pending.append(("decode", handles, snapshot))

    # -- settlement / retirement --------------------------------------------
    def _settle_one(self):
        from ..static.pipeline_runner import PipelineStepError
        entry = self._pending.popleft()
        try:
            toks = np.asarray(entry[1][0])
        except PipelineStepError as exc:
            self._fail_inflight(exc)
            return
        now = time.perf_counter()
        if entry[0] == "prefill":
            _kind, _h, req, idx, version = entry
            slot = self._slots[idx]
            if slot is None or slot.version != version:
                return               # preempted before its first token
            self._append_token(idx, slot, int(toks), now, first=True)
            return
        _kind, _h, snapshot = entry
        for idx, req, version in snapshot:
            slot = self._slots[idx]
            if slot is None or slot.version != version \
                    or slot.req is not req:
                continue             # retired/preempted mid-flight
            self._append_token(idx, slot, int(toks[idx]), now)

    def _append_token(self, idx, slot, token, now, first=False):
        from ..core import monitor as _monitor
        req = slot.req
        if first and req.t_first is None and not req.out:
            req.t_first = now
        req.out.append(token)
        _monitor.stat_add("serve.tokens_generated")
        if (req.eos_token_id is not None and token == req.eos_token_id) \
                or len(req.out) >= req.max_new_tokens:
            self._retire(idx, slot)

    def _retire(self, idx, slot):
        """Finished stream: free its blocks IMMEDIATELY (they are the
        admission currency for whoever is queued) and complete the
        future. In-flight steps that still carry this slot are ignored
        at settle via the slot version."""
        from ..core import monitor as _monitor
        from ..core import trace as _trace
        req = slot.req
        with _trace.span("serve/retire", req=req.rid, slot=idx,
                         generated=len(req.out),
                         blocks=len(slot.blocks)) as sp:
            sp.flow(self._flow_base + req.rid, "f")
            self._pool.free(slot.blocks)
            self._slots[idx] = None
            req.t_done = time.perf_counter()
            _monitor.stat_add("serve.requests_completed")
            if req.ttft_s is not None:
                _monitor.observe("serve/ttft_ms", req.ttft_s * 1e3)
            if req.per_token_s is not None:
                _monitor.observe("serve/token_ms", req.per_token_s * 1e3)
            if self._on_complete is not None:
                # the record is emitted BEFORE the future resolves, so
                # a client that saw result() knows its record was
                # offered; a hook error never fails serving
                try:
                    self._on_complete(req.completion_record())
                except Exception:
                    _monitor.stat_add("serve.completion_log_errors")
            req._done.set()

    def _fail_inflight(self, exc):
        """A decode/prefill step died (XLA-level, past run_guarded): the
        donated device chain is poisoned. Fail every in-flight stream,
        rebuild the device state, keep serving the queue."""
        import jax.numpy as jnp

        from ..core import monitor as _monitor
        from ..static.pipeline_runner import InflightDriver
        self._pending.clear()
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            slot.req.error = exc
            slot.req.t_done = time.perf_counter()
            slot.req._done.set()
            self._pool.free(slot.blocks)
            self._slots[i] = None
            _monitor.stat_add("serve.requests_errored")
        cfg = self.net.config
        heads, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        self._arenas = self._pool.arenas(cfg.num_layers, heads, hd,
                                         self._dtype)
        self._tokens = jnp.zeros((self._A,), jnp.int32)
        self._driver = InflightDriver("serve",
                                      max_inflight=self._max_inflight)

    # -- gauges --------------------------------------------------------------
    def _publish_gauges(self):
        from ..core import monitor as _monitor
        _monitor.stat_set_many({
            "serve.queue_depth": len(self._queue),
            "serve.active_slots": sum(s is not None
                                      for s in self._slots),
            "serve.kv_pool_used_blocks": self._pool.used_blocks,
            "serve.kv_pool_free_blocks": self._pool.free_blocks,
            "serve.model_version": self.model_version,
        })
