"""Python side of the C-ABI predictor (reference
paddle/fluid/inference/capi/ pd_predictor.cc; go/paddle/predictor.go and
r/ bind the same C surface).

The C library (_native/src/predictor_capi.c) embeds CPython and calls the
two functions here. Inputs arrive as raw memoryviews over the caller's C
buffers (zero-copy into numpy); outputs are returned as contiguous f32
bytes + shapes for the C side to hand out.
"""
from __future__ import annotations

import numpy as np

_DTYPES = {0: np.float32, 1: np.int32, 2: np.int64}


def create(prefix: str, cipher_key_hex: str = ""):
    from . import Config, Predictor
    cfg = Config(prefix)
    if cipher_key_hex:
        cfg.set_cipher_key(bytes.fromhex(cipher_key_hex))
    return Predictor(cfg)


def run(predictor, inputs):
    """inputs: list of (memoryview, dtype_code, shape_tuple). Returns list
    of (f32_bytes, shape_tuple)."""
    args = []
    for mv, code, shape in inputs:
        arr = np.frombuffer(mv, dtype=_DTYPES[int(code)]).reshape(
            tuple(int(s) for s in shape))
        args.append(arr)
    outs = predictor.run(args)
    packed = []
    for o in outs:
        a = np.ascontiguousarray(np.asarray(o, np.float32))
        packed.append((a.tobytes(), tuple(int(s) for s in a.shape)))
    return packed
