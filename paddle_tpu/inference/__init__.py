"""paddle.inference — deployment predictor over exported artifacts.

Analog of the reference inference engine (inference/api/
analysis_predictor.cc:1056 CreatePaddlePredictor, api/paddle_api.h zero-copy
tensor API, fluid/io.py:1198 save_inference_model).

TPU-native design delta: the reference freezes a ProgramDesc and replays it
op-by-op through a NaiveExecutor after ~30 IR fuse passes; here `jit.save`
freezes the traced forward (parameters baked as constants) into a
**StableHLO artifact via jax.export** — the compiler owns every fusion the
reference's pass pipeline hand-rolled, and the artifact is loadable in a
fresh process without the model's Python class (and without this framework:
any StableHLO runtime can consume it). The `.pdmodel` Program pickle is the
fallback path and keeps fine-tuning parity.
"""
from __future__ import annotations

import json
import os

import numpy as np

__all__ = ["Config", "Predictor", "PredictorTensor", "create_predictor",
           "encrypt_model"]

# the continuous-batching serve tier (serving.py) pulls in jax at import;
# PEP-562 lazy exports keep `import paddle_tpu.inference` light for the
# predictor-only deployment path (declared in __all_lazy__ so the API.spec
# sweep still sees them — tools/gen_api_spec.py)
__all_lazy__ = ["ServeLoop", "ServeConfig", "ServeRequest"]


def __getattr__(name):
    if name in __all_lazy__:
        from . import serving
        return getattr(serving, name)
    raise AttributeError(
        f"module 'paddle_tpu.inference' has no attribute {name!r}")


def encrypt_model(prefix, key):
    """Encrypt the weight-bearing artifact at rest (reference model
    encryption, framework/io/crypto + mkldnn_quantizer-adjacent deploy
    flow): {prefix}.stablehlo -> {prefix}.stablehlo.enc (AES-256-GCM),
    plaintext removed. Metadata (names/shapes only) stays readable."""
    from ..framework.crypto import Cipher
    c = Cipher(key)
    src = prefix + ".stablehlo"
    c.encrypt_file(src, src + ".enc")
    os.remove(src)


class Config:
    """AnalysisConfig analog (reference api/analysis_config.cc). GPU/IR
    knobs are accepted for API parity; XLA owns optimization here."""

    def __init__(self, model_path=None, params_path=None):
        # accept either a path prefix ("model" for model.stablehlo /
        # model.pdmodel) or explicit file paths
        self._prefix = None
        self._cipher_key = None
        if model_path is not None:
            self.set_model(model_path, params_path)
        self._ir_optim = True
        self._glog_info = True

    def set_cipher_key(self, key: bytes):
        """Key for encrypted artifacts (reference analysis_config crypto
        flow over framework/io/crypto)."""
        self._cipher_key = key

    def set_model(self, model_path, params_path=None):
        for suffix in (".stablehlo", ".pdmodel", ".pdinfer.json"):
            if model_path.endswith(suffix):
                model_path = model_path[: -len(suffix)]
                break
        self._prefix = model_path

    def model_path(self):
        return self._prefix

    # -- parity no-ops ------------------------------------------------------
    def enable_use_gpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def disable_glog_info(self):
        self._glog_info = False

    def enable_memory_optim(self):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass


class PredictorTensor:
    """Zero-copy handle (reference api/paddle_api.h ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, array):
        self._value = np.asarray(array)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.shape(self._value))


class Predictor:
    """Runs a jit.save artifact: StableHLO (jax.export) when present,
    Program-pickle fallback otherwise."""

    def __init__(self, config):
        if isinstance(config, str):
            config = Config(config)
        prefix = config.model_path()
        if prefix is None:
            raise ValueError("Config has no model path; call set_model()")
        meta_path = prefix + ".pdinfer.json"
        if not os.path.exists(meta_path):
            raise FileNotFoundError(
                f"{meta_path} not found — save the model with "
                "paddle_tpu.jit.save first")
        with open(meta_path) as f:
            self._meta = json.load(f)
        self._input_names = list(self._meta["input_names"])
        self._output_names = list(self._meta["output_names"])
        self._inputs = {n: PredictorTensor(n) for n in self._input_names}
        self._outputs = {n: PredictorTensor(n) for n in self._output_names}

        hlo_path = prefix + ".stablehlo"
        self._exported = None
        self._translated = None
        key = getattr(config, "_cipher_key", None)
        if os.path.exists(hlo_path + ".enc"):
            if key is None:
                raise PermissionError(
                    f"{hlo_path}.enc is encrypted; pass the key via "
                    "Config.set_cipher_key")
            import jax.export
            from ..framework.crypto import Cipher
            blob = Cipher(key).decrypt_from_file(hlo_path + ".enc")
            self._exported = jax.export.deserialize(bytearray(blob))
        elif os.path.exists(hlo_path):
            import jax.export
            with open(hlo_path, "rb") as f:
                self._exported = jax.export.deserialize(
                    bytearray(f.read()))
        else:  # fallback: Program pickle through the Executor
            from ..jit import load as _jit_load
            self._translated = _jit_load(prefix)

    # -- reference predictor API -------------------------------------------
    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        return list(self._output_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        """Zero-copy style: stage inputs via handles, then run(); or pass a
        list of arrays positionally (legacy Run)."""
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        args = [self._inputs[n].copy_to_cpu() for n in self._input_names]
        outs = self._call(args)
        for n, o in zip(self._output_names, outs):
            self._outputs[n].copy_from_cpu(o)
        return [self._outputs[n].copy_to_cpu() for n in self._output_names]

    def _call(self, args):
        if self._exported is not None:
            import jax.numpy as jnp
            dtypes = self._meta.get("input_dtypes")
            jargs = [jnp.asarray(a, dtype=dtypes[i] if dtypes else None)
                     for i, a in enumerate(args)]
            outs = self._exported.call(*jargs)
            outs = outs if isinstance(outs, (tuple, list)) else [outs]
            return [np.asarray(o) for o in outs]
        outs = self._translated(*args)
        outs = outs if isinstance(outs, (tuple, list)) else [outs]
        return [np.asarray(o.numpy()) for o in outs]


def create_predictor(config):
    """reference CreatePaddlePredictor (analysis_predictor.cc:1056)."""
    return Predictor(config)
