"""Industrial dataset pipeline: InMemoryDataset / QueueDataset.

Analog of the reference's C++ Dataset tier (reference
framework/data_set.h:157 InMemoryDataset + GlobalShuffle :205,
framework/data_feed.h:663 MultiSlotDataFeed, framework/channel.h) and its
Python face (fluid/dataset.py DatasetFactory). The parse hot path is C++
(_native/multislot_parser.cc, called with the GIL released so the
thread_num pool gets real parallelism); samples live in the packed ragged
form and batches materialize as dense/padded arrays matching the declared
feed variables.

Shuffle story on the single-controller runtime: local_shuffle permutes
this process's samples; global_shuffle uses a seed shared through the
coordination service so every process draws the SAME permutation of the
sample-id space and takes its own strided shard — the reference reached
the same end state by physically exchanging samples through the PS
(data_set.cc GlobalShuffle); here shards are cheap because loading is
lazy per-host.
"""
from __future__ import annotations

import os
import queue
import threading

import numpy as np

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset",
           "DatasetFactory"]


def _slot_type(var):
    dt = str(getattr(var, "dtype", "float32"))
    return "uint64" if ("int" in dt) else "float"


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._use_var = []
        self._filelist = []
        self._seed = 0

    # -- reference fluid/dataset.py configuration surface -------------------
    def init(self, batch_size=1, thread_num=1, use_var=None, **kwargs):
        self.set_batch_size(batch_size)
        self.set_thread_num(thread_num)
        if use_var is not None:
            self.set_use_var(use_var)
        return self

    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread_num(self, thread_num):
        self._thread_num = max(1, int(thread_num))

    def set_use_var(self, var_list):
        self._use_var = list(var_list)

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def get_filelist(self):
        return list(self._filelist)

    def _slot_types(self):
        if not self._use_var:
            raise ValueError("set_use_var() before loading: slot types come "
                             "from the feed variables' dtypes")
        return [_slot_type(v) for v in self._use_var]

    def _parse_files(self, files):
        """Parse files on a thread pool — C++ does the work GIL-free."""
        from concurrent.futures import ThreadPoolExecutor
        from .._native import parse_multislot_file
        types = self._slot_types()
        results = [None] * len(files)
        with ThreadPoolExecutor(max_workers=self._thread_num) as pool:
            futs = {pool.submit(parse_multislot_file, f, types): i
                    for i, f in enumerate(files)}
            for fut, i in futs.items():
                results[i] = fut.result()
        return results

    def _rows_to_feed(self, order, values, splits):
        """Materialize a batch: per slot, rows `order` padded/reshaped to
        the declared var shape (dense slots reshape; ragged slots pad or
        truncate to shape[1])."""
        feed = {}
        for s, var in enumerate(self._use_var):
            vals, spl = values[s], splits[s]
            want = list(getattr(var, "shape", ()))[1:]
            rows = [vals[spl[i]:spl[i + 1]] for i in order]
            dt = np.float32 if _slot_type(var) == "float" else np.int64
            if want and all(len(r) == int(np.prod(want)) for r in rows):
                arr = np.stack(rows).reshape([len(rows)] + want).astype(dt)
            else:  # ragged -> pad/truncate to the declared width
                width = want[0] if want else max(
                    (len(r) for r in rows), default=1)
                arr = np.zeros([len(rows), width], dt)
                for i, r in enumerate(rows):
                    n = min(len(r), width)
                    arr[i, :n] = r[:n]
            feed[var.name] = arr
        return feed


class InMemoryDataset(DatasetBase):
    """reference framework/data_set.h:157."""

    def __init__(self):
        super().__init__()
        self._values = None   # per slot: np values
        self._splits = None   # per slot: np row_splits
        self._rows = 0
        self._order = None
        self._pending_order = None   # restored before load_into_memory

    def load_into_memory(self):
        types_n = len(self._slot_types())
        per_file = self._parse_files(self._filelist)
        values = [[] for _ in range(types_n)]
        splits = [[np.zeros(1, np.int64)] for _ in range(types_n)]
        rows = 0
        for n_rows, slots in per_file:
            for s, (vals, spl) in enumerate(slots):
                base = splits[s][-1][-1]
                values[s].append(vals)
                splits[s].append(base + spl[1:])
            rows += n_rows
        self._values = [np.concatenate(v) if v else np.zeros(0)
                        for v in values]
        self._splits = [np.concatenate(s) for s in splits]
        self._rows = rows
        self._order = np.arange(rows)
        if self._pending_order is not None:
            order, self._pending_order = self._pending_order, None
            self._check_order(order)
            self._order = order

    def get_memory_data_size(self):
        return self._rows

    def release_memory(self):
        self._values = self._splits = self._order = None
        self._rows = 0

    def local_shuffle(self):
        rng = np.random.RandomState(self._seed)
        self._seed += 1
        self._order = rng.permutation(self._rows)

    def global_shuffle(self, fleet=None, thread_num=None):
        """Same permutation on every process (shared seed), strided shard
        per rank — see module docstring for the design delta vs the
        reference's PS-exchange (data_set.h:205)."""
        import jax
        rng = np.random.RandomState(7919 + self._seed)
        self._seed += 1
        perm = rng.permutation(self._rows)
        nproc = jax.process_count()
        if nproc > 1:
            perm = perm[jax.process_index()::nproc]
        self._order = perm

    def batches(self, drop_last=True, start_batch=0):
        """`start_batch` skips the first N batches at the index level (no
        parse/pad work) — the exact-resume entry point
        Executor.train_from_dataset threads its start_batch through."""
        if self._values is None:
            raise RuntimeError("call load_into_memory() first")
        bs = self._batch_size
        n = len(self._order)
        stop = (n // bs) * bs if drop_last else n
        for lo in range(int(start_batch) * bs, stop, bs):
            order = self._order[lo:lo + bs]
            yield self._rows_to_feed(order, self._values, self._splits)

    # -- exact resume --------------------------------------------------------
    def state_dict(self):
        """Shuffle position for the checkpoint's `data` section: the
        seed counter and, when a shuffle has been drawn, the current
        sample order itself (exact — no re-derivation assumptions)."""
        sd = {"seed": int(self._seed)}
        if self._order is not None:
            sd["order"] = np.asarray(self._order, np.int64)
        return sd

    def _check_order(self, order):
        if len(order) != self._rows:
            raise ValueError(
                f"dataset state has {len(order)} samples but "
                f"{self._rows} are loaded — resume state belongs to "
                "a different filelist")

    def load_state_dict(self, sd):
        self._seed = int(sd.get("seed", 0))
        order = sd.get("order")
        if order is None:
            return
        order = np.asarray(order, np.int64)
        if not self._rows:
            # restored before load_into_memory: DEFER the order (applied
            # when rows load) rather than silently dropping it — a
            # later shuffle from seed+1 would walk a different
            # permutation than the killed run
            self._pending_order = order
            return
        self._check_order(order)
        self._order = order


class QueueDataset(DatasetBase):
    """Streaming variant (reference QueueDataset): files parse in a
    background thread into a bounded queue — the framework/channel.h
    analog — while training consumes batches."""

    QUEUE_CAPACITY = 8

    def batches(self, drop_last=True):
        q = queue.Queue(maxsize=self.QUEUE_CAPACITY)
        SENTINEL = object()

        def producer():
            try:
                carry_vals, carry_spl, carry_rows = None, None, 0
                for f in self._filelist:
                    from .._native import parse_multislot_file
                    n_rows, slots = parse_multislot_file(
                        f, self._slot_types())
                    values = [v for v, _ in slots]
                    splits = [s for _, s in slots]
                    for lo in range(0, (n_rows // self._batch_size)
                                    * self._batch_size, self._batch_size):
                        order = np.arange(lo, lo + self._batch_size)
                        q.put(self._rows_to_feed(order, values, splits))
            finally:
                q.put(SENTINEL)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is SENTINEL:
                break
            yield item


class DatasetFactory:
    """reference fluid/dataset.py DatasetFactory."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")
