"""Samplers (reference python/paddle/fluid/dataloader/sampler.py and
batch_sampler.py; DistributedBatchSampler from distributed training path).

Exact-resume support (ISSUE 8): shuffling samplers snapshot their RNG
state at the START of each epoch's draw, and `state_dict()` /
`load_state_dict()` round-trip it — a restarted trainer re-draws the SAME
permutation the killed one was walking, so a mid-epoch resume replays
identical batches (the checkpoint's `data` section; see
incubate/checkpoint.py and docs/fault_tolerance.md "Trainer recovery").
"""
from __future__ import annotations

import math

import numpy as np


def _rng_state_dict(state):
    """np.random RandomState tuple -> checkpointable {key, pos} (arrays
    and ints only: orbax-serializable, hash-stable)."""
    if state is None:
        return None
    _, key, pos, _, _ = state
    return {"key": np.asarray(key, np.uint32), "pos": int(pos)}


def _rng_state_tuple(sd):
    return ("MT19937", np.asarray(sd["key"], np.uint32), int(sd["pos"]),
            0, 0.0)

__all__ = ["Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler", "BatchSampler",
           "DistributedBatchSampler"]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    """`generator` may be an int seed or a np.random.RandomState: the
    sampler then owns a PRIVATE stream (required for exact mid-epoch
    resume — the global np.random stream is consumed by model init and
    cannot be replayed). Default None keeps the legacy global-stream
    draw; resume support still snapshots the state it drew from."""

    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        if isinstance(generator, (int, np.integer)):
            generator = np.random.RandomState(int(generator))
        self._rng = generator
        self._pending_state = None   # installed by load_state_dict
        self._epoch_state = None     # state the CURRENT epoch drew from

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def _draw_rng(self):
        """The stream this epoch draws from, with its start-state
        snapshotted (and a pending resume state installed first)."""
        rng = self._rng if self._rng is not None else np.random
        if self._pending_state is not None:
            if self._rng is None:
                # resuming a global-stream sampler: replay through a
                # private stream so the global chain is left alone
                self._rng = rng = np.random.RandomState()
            rng.set_state(_rng_state_tuple(self._pending_state))
            self._pending_state = None
        self._epoch_state = _rng_state_dict(rng.get_state())
        return rng

    def __iter__(self):
        n = len(self.data_source)
        rng = self._draw_rng()
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples

    # -- exact resume --------------------------------------------------------
    def state_dict(self):
        return {} if self._epoch_state is None \
            else {"rng": self._epoch_state}

    def load_state_dict(self, sd):
        if sd and sd.get("rng") is not None:
            self._pending_state = sd["rng"]


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype="float64")
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if sampler is None:
            sampler = (RandomSampler(dataset) if shuffle
                       else SequenceSampler(dataset))
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    # -- exact resume (delegates to the index sampler) -----------------------
    def state_dict(self):
        if hasattr(self.sampler, "state_dict"):
            return {"sampler": self.sampler.state_dict()}
        return {}

    def load_state_dict(self, sd):
        if sd.get("sampler") and hasattr(self.sampler, "load_state_dict"):
            self.sampler.load_state_dict(sd["sampler"])


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler (reference:
    python/paddle/fluid/dataloader/batch_sampler.py DistributedBatchSampler).
    On the TPU single-controller model this shards the HOST batch stream by
    data-parallel rank for multi-host input pipelines.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import env as dist_env
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas or dist_env.get_world_size()
        self.local_rank = rank if rank is not None else dist_env.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]  # pad to even shards
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch

    # -- exact resume: the epoch IS the rng seed here ------------------------
    def state_dict(self):
        return {"epoch": int(self.epoch)}

    def load_state_dict(self, sd):
        if "epoch" in sd:
            self.epoch = int(sd["epoch"])
