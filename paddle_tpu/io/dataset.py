"""Dataset containers (reference python/paddle/fluid/dataloader/dataset.py)."""
from __future__ import annotations

import bisect

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lens = {len(t) for t in tensors}
        if len(lens) != 1:
            raise ValueError("tensors must share dim 0")
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            sample = ds[idx]
            out.extend(sample if isinstance(sample, (list, tuple)) else [sample])
        return tuple(out)

    def __len__(self):
        return min(len(ds) for ds in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        i = bisect.bisect_right(self.cum, idx)
        prev = self.cum[i - 1] if i > 0 else 0
        return self.datasets[i][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("lengths must sum to dataset size")
    perm = np.random.permutation(len(dataset))
    out, start = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[start:start + n].tolist()))
        start += n
    return out
