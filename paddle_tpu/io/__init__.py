"""paddle.io — Dataset / DataLoader / samplers.

Analog of reference python/paddle/fluid/dataloader/ (dataset.py,
batch_sampler.py, dataloader_iter.py) and fluid/reader.py DataLoader.
Design delta: the reference forks worker processes with shared-memory
queues (reader.py:147); on TPU the input pipeline is host-side numpy with a
background prefetch thread overlapping H2D with the device step — the
BufferedReader double-buffering idea (operators/reader/buffered_reader.h:33)
without per-op readers. A C++ channel-based feeder (paddle_tpu/_native) is
the planned industrial path (framework/channel.h analog).
"""
from .dataset import (ChainDataset, ComposeDataset, ConcatDataset, Dataset,  # noqa: F401
                      IterableDataset, Subset, TensorDataset, random_split)
from .sampler import (BatchSampler, DistributedBatchSampler, RandomSampler,  # noqa: F401
                      Sampler, SequenceSampler, WeightedRandomSampler)
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .fleet_dataset import (DatasetBase, DatasetFactory,  # noqa: F401
                            InMemoryDataset, QueueDataset)
