"""DataLoader with background prefetch.

Analog of reference python/paddle/fluid/reader.py DataLoader (:147) +
dataloader/dataloader_iter.py. Two worker models, like the reference:

- `use_shared_memory=True` (default): FORKED worker processes pulling
  index lists from a task queue and pushing collated numpy batches back
  (the reference's _DataLoaderIterMultiProcess, reader.py:147) — real
  parallelism for Python-heavy transforms the GIL would serialize.
  Workers should produce numpy (not device arrays): they run before the
  host->device transfer.
- `use_shared_memory=False`: a thread pool — enough when __getitem__ is
  numpy-bound (numpy releases the GIL), zero fork hazards.

Either way a double-buffer queue keeps one batch ahead so host collation
overlaps the device step (BufferedReader analog,
operators/reader/buffered_reader.h:47).
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..core import trace as _trace
from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn"]


class _WorkerFailure:
    """Pickled across the result queue to re-raise in the parent."""

    def __init__(self, exc):
        self.type_name = type(exc).__name__
        self.message = str(exc)
        import traceback
        self.tb = traceback.format_exc()


def _worker_loop(dataset, collate_fn, index_q, result_q, init_fn, wid):
    if init_fn is not None:
        init_fn(wid)
    while True:
        task = index_q.get()
        if task is None:
            return
        bid, indices = task
        try:
            batch = collate_fn([dataset[i] for i in indices])
            result_q.put((bid, batch))
        except BaseException as e:  # noqa: BLE001 — must reach the parent
            result_q.put((bid, _WorkerFailure(e)))


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 shuffle_seed=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        # exact-resume position: epoch count, next-batch cursor, pending
        # load_state_dict payload (docs/fault_tolerance.md "Trainer
        # recovery")
        self._epoch = 0
        self._pos_batch = 0
        self._resume = None
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif shuffle and shuffle_seed is not None:
            # a PRIVATE seeded shuffle stream: every epoch's permutation
            # is derivable from the checkpointed rng state alone, so a
            # restarted trainer replays the exact batch schedule
            from .sampler import RandomSampler
            self.batch_sampler = BatchSampler(
                sampler=RandomSampler(dataset, generator=shuffle_seed),
                batch_size=batch_size, drop_last=drop_last)
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    # -- exact mid-epoch resume ---------------------------------------------
    def state_dict(self):
        """Data-pipeline position for the checkpoint's `data` section:
        epoch, next-batch cursor, and the sampler's shuffle-rng state.
        None for IterableDataset loaders (no index space to cursor)."""
        if self._iterable_mode:
            return None
        if self._resume is not None:
            # armed-but-unconsumed resume: the pending position IS the
            # current position (a grace save taken before the first
            # resumed batch must re-save the restored cursor, not a
            # stale local one)
            return {k: v for k, v in self._resume.items()}
        sd = {"epoch": int(self._epoch), "batch": int(self._pos_batch)}
        if hasattr(self.batch_sampler, "state_dict"):
            sd["sampler"] = self.batch_sampler.state_dict()
        return sd

    def load_state_dict(self, sd):
        """Arm the NEXT iteration to resume at the saved position: the
        sampler re-draws the saved epoch's permutation from its
        checkpointed rng state and the first `batch` index-batches are
        skipped at the sampler level (no dataset/collate work). A cursor
        at end-of-epoch advances the shuffle stream past that epoch and
        falls through to a fresh one."""
        if sd is None or self._iterable_mode:
            return
        self._resume = {k: v for k, v in sd.items()}

    def roll_resumed_epoch(self):
        """Treat the armed resume position as end-of-epoch. The caller's
        epoch was truncated at a batch count the loader can't see (hapi
        fit's steps= cap): the next iteration must draw AND DISCARD that
        epoch's permutation — advancing the shuffle stream exactly as
        the uninterrupted run's next epoch would — and start the
        following epoch fresh, not replay the truncated epoch's tail."""
        if self._resume is None or self._iterable_mode:
            return
        try:
            self._resume["batch"] = len(self.batch_sampler)
        except TypeError:
            self._resume = None   # unsized sampler: start fresh

    def _epoch_indices(self):
        """The index-batch iterable for this iteration, resume applied."""
        import itertools
        skip = 0
        if self._resume is not None:
            sd, self._resume = self._resume, None
            if sd.get("sampler") is not None \
                    and hasattr(self.batch_sampler, "load_state_dict"):
                self.batch_sampler.load_state_dict(sd["sampler"])
            self._epoch = int(sd.get("epoch", 0))
            skip = int(sd.get("batch", 0))
            try:
                total = len(self.batch_sampler)
            except TypeError:
                total = None
            if total is not None and skip >= total:
                # the saved epoch was complete: draw (and discard) its
                # permutation so the shuffle stream advances exactly as
                # the uninterrupted run's would, then start fresh
                for _ in self.batch_sampler:
                    pass
                self._epoch += 1
                skip = 0
        it = iter(self.batch_sampler)
        if skip:
            it = itertools.islice(it, skip, None)
        return it, skip

    def _batches(self, index_batches=None):
        if self._iterable_mode:
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []
            if buf and not self.drop_last:
                yield self.collate_fn(buf)
            return
        if index_batches is None:
            index_batches = iter(self.batch_sampler)
        for indices in index_batches:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def _batches_threaded(self, index_batches):
        """Fetch batches with a worker pool; keep `prefetch_factor` in flight."""
        pool = ThreadPoolExecutor(max_workers=self.num_workers)
        sentinel = object()
        stop = threading.Event()
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_factor * self.num_workers)

        parent_ctx = _trace.current()

        def fetch(indices):
            # worker-pool span: joins the loader's ambient trace so a
            # slow transform shows up next to the step that starved
            with _trace.attach(parent_ctx), \
                    _trace.span("io/collate", n=len(indices)):
                return self.collate_fn([self.dataset[i] for i in indices])

        def producer():
            try:
                for indices in index_batches:
                    try:
                        fut = pool.submit(fetch, indices)
                    except RuntimeError:
                        # consumer abandoned the iterator and its finally
                        # block shut the pool down between our iterations
                        return
                    while not stop.is_set():  # bounded put that can abort
                        try:
                            q.put(fut, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        fut.cancel()
                        return
            finally:
                while not stop.is_set():  # sentinel must arrive or be moot
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item.result()
        finally:
            stop.set()  # unblock producer if the consumer bailed early
            try:  # drop buffered futures so queued work doesn't run
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            # an abandoned iterator (GeneratorExit) must not leak the
            # pool: cancel queued fetches and JOIN the workers — with
            # wait=False the pool threads lived until process exit
            pool.shutdown(wait=True, cancel_futures=True)
            t.join(timeout=5)

    def _batches_multiprocess(self, index_batches):
        """Forked worker processes; batches re-ordered by index so epoch
        order matches the sampler regardless of worker timing."""
        import multiprocessing as mp
        ctx = mp.get_context("fork")
        tasks = list(enumerate(index_batches))
        index_q = ctx.Queue()
        result_q = ctx.Queue(
            maxsize=max(2, self.prefetch_factor) * self.num_workers)
        workers = [
            ctx.Process(target=_worker_loop,
                        args=(self.dataset, self.collate_fn, index_q,
                              result_q, self.worker_init_fn, wid),
                        daemon=True)
            for wid in range(self.num_workers)]
        for w in workers:
            w.start()
        try:
            for t in tasks:
                index_q.put(t)
            for _ in workers:
                index_q.put(None)
            expected, cache, received = 0, {}, 0
            while received < len(tasks):
                bid, payload = result_q.get()
                received += 1
                if isinstance(payload, _WorkerFailure):
                    raise RuntimeError(
                        f"DataLoader worker failed: {payload.type_name}: "
                        f"{payload.message}\n{payload.tb}")
                cache[bid] = payload
                while expected in cache:
                    yield cache.pop(expected)
                    expected += 1
        finally:
            for w in workers:
                if w.is_alive():
                    w.terminate()
                w.join(timeout=5)

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_stream(self._batches())
            return
        index_batches, skip = self._epoch_indices()
        if self.num_workers > 0:
            if self.use_shared_memory:
                gen = self._batches_multiprocess(index_batches)
            else:
                gen = self._batches_threaded(index_batches)
        else:
            gen = self._batches(index_batches)
        # track the consumed-batch cursor so state_dict() taken at any
        # step names the exact next batch; a full epoch rolls the epoch
        # counter so multi-epoch resumes re-derive later permutations
        self._pos_batch = skip
        for b in self._iter_stream(gen):
            self._pos_batch += 1
            yield b
        self._epoch += 1
        self._pos_batch = 0

    def _iter_stream(self, gen):
        if not self.use_buffer_reader:
            yield from gen
            return
        # double-buffer: keep one batch ahead so host collation overlaps
        # the device step (BufferedReader semantics)
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_factor)
        sentinel = object()
        stop = threading.Event()
        err = []
        parent_ctx = _trace.current()

        def _next_batch(it, seq):
            # spans the PRODUCTION of one batch (collate/worker wait),
            # the host-side cost the double-buffer exists to hide
            sp = _trace.begin("io/produce_batch", seq=seq)
            try:
                return next(it)
            except StopIteration:
                _trace.end(sp, discard=True)
                raise
            finally:
                if sp.t1 is None:
                    _trace.end(sp)

        def producer():
            try:
                with _trace.attach(parent_ctx):
                    it, seq = iter(gen), 0
                    while True:
                        try:
                            b = _next_batch(it, seq)
                        except StopIteration:
                            break
                        seq += 1
                        while not stop.is_set():
                            try:
                                q.put(b, timeout=0.1)
                                break
                            except queue.Full:
                                continue
                        if stop.is_set():
                            gen.close() if hasattr(gen, "close") else None
                            return
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                while not stop.is_set():  # sentinel must arrive or be moot
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    if err:
                        raise err[0]
                    break
                yield item
        finally:
            stop.set()  # consumer abandoned mid-epoch: release the producer
            try:  # unblock a producer stuck on a full queue
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5)  # producer closes `gen` on its way out,
            if not t.is_alive():  # which shuts the worker pool down too
                try:
                    gen.close()  # no-op if already closed/exhausted
                except RuntimeError:
                    pass
