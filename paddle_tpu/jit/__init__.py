"""paddle.jit — dygraph-to-static + model export.

Analog of reference python/paddle/fluid/dygraph/dygraph_to_static/ (23 AST
transformer modules + program_translator.py) and jit.save/load
(dygraph/jit.py -> TranslatedLayer).

Design delta (SURVEY.md §7.3 "two frontends, one trace"): no AST rewriting.
`to_static` compiles the callable by functional extraction + jax.jit — the
same Python runs as the trace. `save` records the forward into a static
Program (parameters baked as constants for inference) and pickles it — op
kernels are module-level jnp functions, so the Program is serializable
without a proto IR; `load` returns a TranslatedLayer driving the Executor.
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional

import numpy as np

from ..core import tape as _tape
from ..core.tensor import Tensor
from ..hapi.model import InputSpec  # noqa: F401
from ..nn.layer.layers import Layer

__all__ = ["to_static", "save", "load", "TranslatedLayer", "not_to_static",
           "ignore_module"]


class StaticFunction:
    """Compiled wrapper over a dygraph callable (reference
    program_translator.py StaticFunction)."""

    def __init__(self, function, input_spec=None):
        from .dy2static import convert_function, convert_layer
        if isinstance(function, Layer):
            self._layer = convert_layer(function)
            self._fn = function
        else:
            self._layer = None
            self._fn = convert_function(function)
        self._input_spec = input_spec
        self._compiled = {}

    @staticmethod
    def _is_arraylike(v):
        import jax
        return isinstance(v, (Tensor, np.ndarray, jax.Array))

    def _key(self, args, kw_tree, kw_leaves):
        # arrays are keyed by (shape, dtype) — they are traced inputs, never
        # baked constants; only hashable non-array leaves key by value
        def one(a):
            if self._is_arraylike(a):
                shape = tuple(a.shape) if hasattr(a, "shape") \
                    else tuple(np.shape(a))
                return ("arr", shape, str(np.asarray(
                    a._value if isinstance(a, Tensor) else a).dtype))
            try:
                hash(a)
                return ("lit", a)
            except TypeError:
                return ("lit", repr(a))
        return (tuple(one(a) for a in args), kw_tree,
                tuple(one(v) for v in kw_leaves))

    def __call__(self, *args, **kwargs):
        import jax
        import jax.tree_util as jtu

        # Every array-like kwarg leaf (Tensor, np.ndarray, jax.Array — at
        # any nesting depth) is a traced input; anything else is a
        # compile-time literal captured in the cache key.
        is_t = lambda v: isinstance(v, Tensor)  # noqa: E731
        kw_leaves, kw_tree = jtu.tree_flatten(kwargs, is_leaf=is_t)
        traced_idx = tuple(i for i, v in enumerate(kw_leaves)
                           if self._is_arraylike(v))
        wrap_tensor = tuple(isinstance(kw_leaves[i], Tensor)
                            for i in traced_idx)
        key = self._key(args, kw_tree, kw_leaves)
        if key not in self._compiled:
            target = self._layer if self._layer is not None else self._fn
            is_layer = self._layer is not None
            lit_leaves = list(kw_leaves)  # traced slots overwritten per call

            def pure(params, buffers, raw_args, traced_vals):
                leaves = list(lit_leaves)
                for i, v, as_t in zip(traced_idx, traced_vals, wrap_tensor):
                    leaves[i] = Tensor(v, _internal=True) if as_t else v
                kw = jtu.tree_unflatten(kw_tree, leaves)
                with _tape.no_grad():
                    if is_layer:
                        target.load_functional_state(params, buffers)
                    tin = [Tensor(a, _internal=True) for a in raw_args]
                    out = target(*tin, **kw)
                    # thread mutated buffers (BN running stats) back out
                    new_bufs = ({n: b._value for n, b in
                                 target.named_buffers()} if is_layer else {})
                raw_out = jtu.tree_map(
                    lambda t: t._value if isinstance(t, Tensor) else t,
                    out, is_leaf=lambda t: isinstance(t, Tensor))
                return raw_out, new_bufs

            self._compiled[key] = jax.jit(pure)

        params, buffers = ({}, {}) if self._layer is None \
            else self._layer.functional_state()
        raw = tuple(a._value if isinstance(a, Tensor) else a for a in args)
        traced_vals = tuple(
            kw_leaves[i]._value if isinstance(kw_leaves[i], Tensor)
            else kw_leaves[i] for i in traced_idx)
        out, new_bufs = self._compiled[key](params, buffers, raw, traced_vals)
        if self._layer is not None:
            self._layer.load_functional_state(params, buffers)
            self._layer.load_functional_state(None, new_bufs)
        return jtu.tree_map(lambda v: Tensor(v, _internal=True), out)

    # passthroughs for layer-like usage
    def __getattr__(self, item):
        return getattr(self._fn, item)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None):
    if function is None:
        def deco(fn):
            return StaticFunction(fn, input_spec)
        return deco
    return StaticFunction(function, input_spec)


def not_to_static(fn):
    return fn


def ignore_module(modules):
    pass


class TranslatedLayer(Layer):
    """Deserialized inference program (reference dygraph/io.py
    TranslatedLayer)."""

    def __init__(self, program, feed_names):
        super().__init__()
        self._program = program
        self._feed_names = feed_names
        from ..static.executor import Executor
        self._exe = Executor()

    def forward(self, *args):
        feed = {}
        for name, a in zip(self._feed_names, args):
            feed[name] = a.numpy() if isinstance(a, Tensor) else np.asarray(a)
        fetch = self._program._jit_fetch_vars
        outs = self._exe.run(self._program, feed=feed, fetch_list=fetch)
        outs = [Tensor(o) for o in outs]
        return outs[0] if len(outs) == 1 else tuple(outs)


def save(layer, path, input_spec=None, **configs):
    """Trace `layer` into a Program (params baked as constants) + pickle.

    Produces {path}.pdmodel (program) and {path}.pdiparams (state_dict, for
    fine-tuning parity with the reference format split).
    """
    from .. import static as static_mod
    from ..framework.io import save as _save
    from ..static.program import Program, program_guard

    if isinstance(layer, StaticFunction):
        input_spec = input_spec or layer._input_spec
        if layer._layer is None:
            raise TypeError(
                "jit.save needs a Layer (or to_static-wrapped Layer); "
                "plain functions have no parameters to export — wrap the "
                "function in a Layer or save its outputs instead")
        layer = layer._layer
    if input_spec is None:
        raise ValueError("jit.save requires input_spec on first save")

    from .dy2static import convert_layer
    # convert Python if/while over tensors -> cond/while ops for the trace;
    # if save installed the converted forward itself, it removes it after —
    # export must not permanently mutate the caller's layer (a to_static-
    # wrapped layer keeps its conversion: the user opted in)
    installed = []
    try:
        convert_layer(layer, installed=installed)
        was_training = layer.training
        layer.eval()
        program = Program("inference")
        static_mod.enable_static_()
        try:
            with program_guard(program):
                feeds = []
                for i, spec in enumerate(input_spec):
                    shape = [1 if (s is None or s == -1) else s
                             for s in spec.shape]
                    feeds.append(static_mod.data(
                        spec.name or f"x{i}", shape,
                        str(np.dtype(spec.dtype)
                            if not isinstance(spec.dtype, str)
                            else spec.dtype)))
                with _tape.no_grad():
                    out = layer(*feeds)
        finally:
            static_mod.disable_static_()
            if was_training:
                layer.train()

        outs = out if isinstance(out, (tuple, list)) else [out]
        program._jit_fetch_vars = list(outs)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # versioned schema format (ops by registry name + version, JSON +
        # npz — survives internal module renames; framework/program_serde
        # .py); pickle only as a fallback for exotic non-registry kernels
        from ..framework.program_serde import save_program
        try:
            save_program(program, path, feed_names=[v.name for v in feeds])
        except TypeError as e:
            import warnings
            warnings.warn(
                f"falling back to pickle .pdmodel ({e}); this artifact "
                "will not be loadable across framework refactors")
            payload = {
                "program": program,
                "feed_names": [v.name for v in feeds],
            }
            with open(path + ".pdmodel", "wb") as f:
                pickle.dump(payload, f, protocol=4)
        _save(layer.state_dict(), path + ".pdiparams")
        _export_stablehlo(layer, input_spec, [v.name for v in feeds], path)
    finally:
        # export must not permanently mutate the caller's model: undo
        # every instance-level forward the conversion installed
        for lyr in installed:
            lyr.__dict__.pop("forward", None)


def _export_stablehlo(layer, input_spec, feed_names, path):
    """Freeze the eval-mode forward (parameters baked as constants) into a
    serialized jax.export/StableHLO artifact + a JSON metadata sidecar —
    the deployment artifact paddle_tpu.inference.Predictor consumes
    (reference save_inference_model, fluid/io.py:1198; the ~30-pass
    OptimizeInferenceProgram pipeline collapses into XLA compilation of
    the exported module)."""
    import json

    import jax
    import jax.export as jexport
    import jax.numpy as jnp

    from ..core import rng as _rng

    was_training = layer.training
    layer.eval()
    try:
        params, buffers = layer.functional_state()

        def fwd(*xs):
            with _tape.no_grad(), _rng.rng_state(jax.random.PRNGKey(0)):
                layer.load_functional_state(params, buffers)
                out = layer(*[Tensor(x, _internal=True) for x in xs])
            outs = out if isinstance(out, (tuple, list)) else [out]
            return tuple(o._value for o in outs)

        from ..core.dtype import to_jax_dtype
        example = []
        for i, spec in enumerate(input_spec):
            shape = [1 if (s is None or s == -1) else s for s in spec.shape]
            example.append(
                jnp.zeros(shape, to_jax_dtype(spec.dtype)))

        args = example
        try:  # symbolic batch dim where the spec left it open
            poly = [(", ".join(["b"] + ["_"] * (a.ndim - 1))
                     if (spec.shape and spec.shape[0] in (None, -1)
                         and a.ndim >= 1) else None)
                    for spec, a in zip(input_spec, example)]
            if any(p is not None for p in poly):
                args = jexport.symbolic_args_specs(example, poly)
        except Exception:
            args = example

        exported = jexport.export(jax.jit(fwd), platforms=("cpu", "tpu"))(
            *args)
        out_avals = exported.out_avals
        with open(path + ".stablehlo", "wb") as f:
            f.write(bytes(exported.serialize()))
        meta = {
            "input_names": list(feed_names),
            "input_dtypes": [str(np.dtype(a.dtype)) for a in example],
            "output_names": [f"fetch_{i}" for i in range(len(out_avals))],
            "output_shapes": [[int(d) if str(d).isdigit() else None
                               for d in a.shape] for a in out_avals],
            "format": "stablehlo+jax.export",
        }
        with open(path + ".pdinfer.json", "w") as f:
            json.dump(meta, f)
    finally:
        if was_training:
            layer.train()


def load(path, **configs):
    with open(path + ".pdmodel", "rb") as f:
        head = f.read(1)
    if head == b"{":  # versioned JSON schema (program_serde)
        from ..framework.program_serde import load_program
        program, feed_names = load_program(path)
        return TranslatedLayer(program, feed_names)
    with open(path + ".pdmodel", "rb") as f:  # legacy pickle artifacts
        payload = pickle.load(f)
    program = payload["program"]
    return TranslatedLayer(program, payload["feed_names"])
