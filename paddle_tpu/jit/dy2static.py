"""Dygraph-to-static conversion of Python control flow.

Analog of the reference's AST-transformer stack
(python/paddle/fluid/dygraph/dygraph_to_static/: program_translator.py,
ifelse_transformer.py, loop_transformer.py, logical_transformer.py — 23
modules). A function decorated with @to_static (or a Layer passed to
jit.save) gets its `if` / `while` / `for range(...)` statements rewritten so
that branching on *tensor* values works in all three execution regimes:

- eager values        -> plain Python control flow (semantics unchanged)
- jax tracers (jit)   -> lax.cond / lax.while_loop
- static Variables    -> static.control_flow.cond / while_loop (recorded
                         into the Program as sub-block ops, so jit.save
                         serializes them and the Executor replays them)

Design delta vs the reference: the reference needed 23 transformers because
every converted statement had to build ProgramDesc blocks by hand. Here one
transformer threads assigned-and-live-after locals through runtime
converters (`convert_ifelse` / `convert_while`) that dispatch on the
predicate's regime; the heavy lifting (sub-block tracing, shape-invariant
checks) is the existing static control-flow layer and XLA itself.

Restrictions (each falls back to untransformed Python, which still works
for non-tensor predicates): bare `break`/`continue` inside a converted
loop body (returns lift via the early-return fold), `global`/`nonlocal`
in the function, and functions whose source is unavailable.
`convert_layer` recurses into sublayers (the reference's convert_call),
so control flow anywhere in a Layer call tree converts; plain helper
FUNCTIONS called from a forward are not rewritten — decorate them with
@to_static if they branch on tensors.
"""
from __future__ import annotations

import ast
import functools
import inspect
import sys
import textwrap
import types
import warnings

import numpy as np

__all__ = ["convert_function", "convert_layer", "Dy2StaticError"]

_PREFIX = "__jst"


class Dy2StaticError(TypeError):
    pass


class _Undefined:
    """Placeholder for a local that is not yet bound when a branch/loop
    starts (the reference's UndefinedVar, return_transformer.py)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined local>"


UNDEF = _Undefined()


# ---------------------------------------------------------------------------
# runtime value helpers
# ---------------------------------------------------------------------------

def _tensor_cls():
    from ..core.tensor import Tensor
    return Tensor


def _variable_cls():
    from ..static.program import Variable
    return Variable


def _raw(v):
    return v._value if isinstance(v, _tensor_cls()) else v


def _is_symbolic_static(v):
    return isinstance(v, _variable_cls()) and v._value is None


def _is_tracer(v):
    import jax
    return isinstance(v, jax.core.Tracer)


def _is_carry(v):
    """Values that can ride a lax/static carry: tensors, arrays, numbers
    (python scalars are promoted to arrays); None/UNDEF/objects are aux."""
    import jax
    if isinstance(v, _Undefined) or v is None:
        return False
    return isinstance(v, (_tensor_cls(), jax.Array, np.ndarray,
                          bool, int, float, np.number, np.bool_))


def _truthy(v):
    return bool(np.asarray(v))


def _aux_equal(a, b):
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if x is y:
            continue
        try:
            if bool(np.asarray(x == y).all()):
                continue
        except Exception:
            return False
        return False
    return True


def pack(*getters):
    """Snapshot current values of the threaded locals; unbound locals
    become UNDEF (they may be bound inside a branch)."""
    out = []
    for g in getters:
        try:
            out.append(g())
        except (NameError, UnboundLocalError):
            out.append(UNDEF)
    return tuple(out)


# ---------------------------------------------------------------------------
# runtime converters
# ---------------------------------------------------------------------------

class _CarrySpec:
    """Partition a tuple of locals into flat carry arrays (pytrees whose
    leaves are all tensors/arrays/numbers) and opaque aux values. Two specs
    are compatible when their aux positions, pytree structures and leaf
    counts agree — shape/dtype agreement is the underlying lax primitive's
    contract."""

    def __init__(self, values):
        import jax.numpy as jnp
        import jax.tree_util as jtu
        Tensor = _tensor_cls()
        self.slots = []   # ("P", treedef, flavors, n) | ("X", value)
        self.leaves = []  # raw jax values, flattened across P slots
        for v in values:
            leaves, td = jtu.tree_flatten(
                v, is_leaf=lambda x: isinstance(x, Tensor))
            if leaves and all(_is_carry(l) for l in leaves):
                self.slots.append(
                    ("P", td, tuple(isinstance(l, Tensor) for l in leaves),
                     len(leaves)))
                self.leaves.extend(jnp.asarray(_raw(l)) for l in leaves)
            else:
                self.slots.append(("X", v))

    def aux(self):
        return [s[1] for s in self.slots if s[0] == "X"]

    def signature(self):
        return [(s[0], s[1], s[3]) if s[0] == "P" else "X"
                for s in self.slots]

    def rebuild(self, arrays, other=None):
        """Locals tuple from flat arrays; a leaf rewraps as Tensor when
        either this spec or `other` (the sibling branch) saw a Tensor."""
        import jax.tree_util as jtu
        Tensor = _tensor_cls()
        out, it = [], iter(arrays)
        oslots = other.slots if other is not None else self.slots
        for slot, oslot in zip(self.slots, oslots):
            if slot[0] == "X":
                out.append(slot[1])
                continue
            _, td, flavors, n = slot
            oflav = oslot[2] if oslot[0] == "P" else flavors
            vals = [next(it) for _ in range(n)]
            wrapped = [Tensor(v, _internal=True) if (f or of) else v
                       for v, f, of in zip(vals, flavors, oflav)]
            out.append(jtu.tree_unflatten(td, wrapped))
        return tuple(out)


def convert_ifelse(pred, true_fn, false_fn, args):
    args = tuple(args)
    if _is_symbolic_static(pred):
        return _static_ifelse(pred, true_fn, false_fn, args)
    p = _raw(pred)
    if _is_tracer(p):
        return _traced_ifelse(p, true_fn, false_fn, args)
    return tuple(true_fn(args)) if _truthy(p) else tuple(false_fn(args))


def _traced_ifelse(praw, true_fn, false_fn, args):
    import jax.numpy as jnp
    from jax import lax

    in_spec = _CarrySpec(args)
    rec = {}

    def mk(fn, tag):
        def g(ops_):
            out = list(fn(in_spec.rebuild(ops_)))
            spec = _CarrySpec(out)
            rec[tag] = spec
            return tuple(spec.leaves)
        return g

    pb = jnp.reshape(praw, ()).astype(bool)
    try:
        res = lax.cond(pb, mk(true_fn, "t"), mk(false_fn, "f"),
                       tuple(in_spec.leaves))
    except TypeError as e:
        raise Dy2StaticError(
            "converted `if` on a traced tensor: the two branches must "
            "produce the same shapes/dtypes/structure for every local they "
            f"assign ({e})") from e
    st, sf = rec["t"], rec["f"]
    if st.signature() != sf.signature():
        raise Dy2StaticError(
            "converted `if` on a traced tensor: a local has a different "
            "tensor structure per branch (tensor in one, non-tensor or "
            "unbound in the other); bind it compatibly in both branches")
    if not _aux_equal(st.aux(), sf.aux()):
        raise Dy2StaticError(
            "converted `if` on a traced tensor assigns different non-tensor "
            f"Python values per branch ({st.aux()!r} vs {sf.aux()!r}); make "
            "the value a tensor or hoist it out of the branch")
    return st.rebuild(res, other=sf)


class _StaticSpec:
    """Static-mode analog of _CarrySpec: carry leaves become sub-block
    Variables (eager constants are promoted via a recorded assign)."""

    def __init__(self, values):
        import jax.tree_util as jtu
        from .. import ops
        Tensor = _tensor_cls()
        self.slots = []
        self.vars = []
        for v in values:
            leaves, td = jtu.tree_flatten(
                v, is_leaf=lambda x: isinstance(x, Tensor))
            if leaves and all(_is_carry(l) for l in leaves):
                self.slots.append(("P", td, len(leaves)))
                for l in leaves:
                    self.vars.append(
                        l if _is_symbolic_static(l)
                        else ops.assign(l if isinstance(l, Tensor)
                                        else np.asarray(l)))
            else:
                self.slots.append(("X", v))

    def aux(self):
        return [s[1] for s in self.slots if s[0] == "X"]

    def signature(self):
        return [(s[0], s[1], s[2]) if s[0] == "P" else "X"
                for s in self.slots]

    def rebuild(self, variables):
        import jax.tree_util as jtu
        out, it = [], iter(variables)
        for slot in self.slots:
            if slot[0] == "X":
                out.append(slot[1])
            else:
                _, td, n = slot
                out.append(jtu.tree_unflatten(td,
                                              [next(it) for _ in range(n)]))
        return tuple(out)


def _static_ifelse(pred, true_fn, false_fn, args):
    from ..core.tape import record_op
    from ..static.control_flow import (SubBlock, _CondFn, _check_scalar_bool,
                                       _resolve_free, _trace_subblock)
    rec = {}

    def mk(fn, tag):
        def g():
            spec = _StaticSpec(list(fn(args)))
            rec[tag] = spec
            return tuple(spec.vars)
        return g

    _check_scalar_bool(pred, "converted `if` predicate")
    t_ops, _, t_outs, t_free = _trace_subblock(mk(true_fn, "t"), [],
                                               "dy2st_true")
    f_ops, _, f_outs, f_free = _trace_subblock(mk(false_fn, "f"), [],
                                               "dy2st_false")
    st, sf = rec["t"], rec["f"]
    if st.signature() != sf.signature():
        raise Dy2StaticError(
            "converted `if` on a static Variable: branches disagree on "
            "which locals are graph values; bind each assigned local as a "
            "tensor in both branches")
    if not _aux_equal(st.aux(), sf.aux()):
        raise Dy2StaticError(
            "converted `if` on a static Variable assigns different "
            f"non-tensor Python values per branch "
            f"({st.aux()!r} vs {sf.aux()!r})")
    if not t_outs:  # nothing graph-valued changes: branches were no-ops
        return st.rebuild([])
    for i, (t, f) in enumerate(zip(t_outs, f_outs)):
        if tuple(t.aval.shape) != tuple(f.aval.shape) \
                or t.aval.dtype != f.aval.dtype:
            raise Dy2StaticError(
                f"converted `if` branch output {i}: true branch is "
                f"{tuple(t.aval.shape)}/{t.aval.dtype} but false branch is "
                f"{tuple(f.aval.shape)}/{f.aval.dtype}")
    free_map = dict(t_free)
    free_map.update(f_free)
    free_vars = _resolve_free(free_map)
    free_ids = list(free_map)
    fn = _CondFn(SubBlock(t_ops, [], free_ids, [o.var_id for o in t_outs]),
                 SubBlock(f_ops, [], free_ids, [o.var_id for o in f_outs]))
    res = record_op(fn, (pred,) + tuple(free_vars), {}, "cond")
    res = list(res) if isinstance(res, (tuple, list)) else [res]
    return st.rebuild(res)


def convert_while(cond_fn, body_fn, args):
    import jax.tree_util as jtu
    args = tuple(args)
    # sniff the regime from the carried values first — evaluating the test
    # in static mode would record its ops into the outer Program as dead
    # code (they get re-traced into the while op's own sub-block)
    Tensor = _tensor_cls()
    leaves = [l for v in args
              for l in jtu.tree_flatten(
                  v, is_leaf=lambda x: isinstance(x, Tensor))[0]]
    if any(_is_symbolic_static(l) for l in leaves):
        return _static_while(cond_fn, body_fn, args)
    if any(_is_tracer(_raw(l)) for l in leaves):
        return _traced_while(cond_fn, body_fn, args)
    # no symbolic carry: the test may still be symbolic through closures
    first = cond_fn(args)
    if _is_symbolic_static(first):
        return _static_while(cond_fn, body_fn, args)
    fraw = _raw(first)
    if _is_tracer(fraw):
        return _traced_while(cond_fn, body_fn, args)
    vals = args
    ok = _truthy(fraw)
    while ok:
        vals = tuple(body_fn(vals))
        if len(vals) != len(args):
            raise Dy2StaticError("loop body changed the number of locals")
        ok = _truthy(_raw(cond_fn(vals)))
    return vals


def _traced_while(cond_fn, body_fn, args):
    import jax.numpy as jnp
    from jax import lax

    in_spec = _CarrySpec(args)
    # .astype(dtype) strips weak typing so python-int initials (i = 0)
    # match the body's strongly-typed outputs in the carry aval check
    init = tuple(a.astype(a.dtype) for a in in_spec.leaves)

    def c(ops_):
        r = cond_fn(in_spec.rebuild(ops_))
        return jnp.reshape(jnp.asarray(_raw(r)), ()).astype(bool)

    def b(ops_):
        out = list(body_fn(in_spec.rebuild(ops_)))
        if len(out) != len(args):
            raise Dy2StaticError("loop body changed the number of locals")
        spec = _CarrySpec(out)
        if spec.signature() != in_spec.signature():
            raise Dy2StaticError(
                "converted `while` on a traced tensor: a loop-carried "
                "local changed its tensor structure inside the body")
        if not _aux_equal(spec.aux(), in_spec.aux()):
            raise Dy2StaticError(
                "converted `while` on a traced tensor mutates a non-tensor "
                f"Python value per iteration ({in_spec.aux()!r} -> "
                f"{spec.aux()!r}); make it a tensor (appending to lists "
                "inside a traced loop is not convertible — use a "
                "preallocated tensor)")
        new = []
        for nv, iv in zip(spec.leaves, init):
            if nv.shape != iv.shape:
                raise Dy2StaticError(
                    f"converted `while`: loop-carried local changed shape "
                    f"{iv.shape} -> {nv.shape} (XLA While needs a fixed "
                    "carry; pad or restructure)")
            new.append(nv.astype(iv.dtype))
        return tuple(new)

    res = lax.while_loop(c, b, init)
    return in_spec.rebuild(res)


def _static_while(cond_fn, body_fn, args):
    from ..static import control_flow as cf

    in_spec = _StaticSpec(args)
    if not in_spec.vars:
        raise Dy2StaticError(
            "converted `while` with a graph-value predicate carries no "
            "tensor locals — the loop would be unobservable; thread a "
            "tensor through it")

    def c(*vs):
        return cond_fn(in_spec.rebuild(vs))

    def b(*vs):
        out = list(body_fn(in_spec.rebuild(vs)))
        spec = _StaticSpec(out)
        if spec.signature() != in_spec.signature():
            raise Dy2StaticError(
                "converted `while` in static mode: a loop-carried local "
                "changed its tensor structure inside the body")
        if not _aux_equal(spec.aux(), in_spec.aux()):
            raise Dy2StaticError(
                "converted `while` in static mode mutates a non-tensor "
                f"Python value per iteration ({in_spec.aux()!r} -> "
                f"{spec.aux()!r})")
        return tuple(spec.vars)

    res = cf.while_loop(c, b, list(in_spec.vars))
    return in_spec.rebuild(res)


def unpack_range(*rargs):
    if len(rargs) == 1:
        return 0, rargs[0], 1
    if len(rargs) == 2:
        return rargs[0], rargs[1], 1
    return rargs


def range_cond(i, stop, step):
    if isinstance(step, (int, float)) or isinstance(step, np.number):
        return i < stop if step > 0 else i > stop
    import jax.numpy as jnp
    sr, ir, pr = _raw(step), _raw(i), _raw(stop)
    return jnp.where(jnp.asarray(sr) > 0, jnp.asarray(ir) < jnp.asarray(pr),
                     jnp.asarray(ir) > jnp.asarray(pr))


def _symbolic(v):
    return _is_symbolic_static(v) or _is_tracer(_raw(v))


def and_(*fns):
    val = fns[0]()
    for f in fns[1:]:
        if _symbolic(val):
            from .. import ops
            val = ops.logical_and(val, f())
        elif not _truthy(_raw(val)):
            return val
        else:
            val = f()
    return val


def or_(*fns):
    val = fns[0]()
    for f in fns[1:]:
        if _symbolic(val):
            from .. import ops
            val = ops.logical_or(val, f())
        elif _truthy(_raw(val)):
            return val
        else:
            val = f()
    return val


def not_(v):
    if _symbolic(v):
        from .. import ops
        return ops.logical_not(v)
    return not _truthy(_raw(v))


# ---------------------------------------------------------------------------
# AST analysis
# ---------------------------------------------------------------------------

_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef, ast.ListComp, ast.SetComp, ast.DictComp,
                   ast.GeneratorExp)


def _assigned_names(stmts):
    """Names bound at statement level (descending into compound statements
    but not into nested scopes). Generated helper names are excluded."""
    names = set()

    def walk(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.add(node.name)
            return
        if isinstance(node, _SCOPE_BARRIERS):
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                     (ast.Store, ast.Del)):
            names.add(node.id)
        for child in ast.iter_child_nodes(node):
            walk(child)

    for st in stmts:
        walk(st)
    return names


def _loads(node_or_stmts):
    """All Name loads, including inside nested scopes (conservative for
    liveness)."""
    names = set()
    nodes = node_or_stmts if isinstance(node_or_stmts, list) \
        else [node_or_stmts]
    for n in nodes:
        if n is None:
            continue
        for sub in ast.walk(n):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                names.add(sub.id)
    return names


def _reads_before_write(stmts):
    """Names read before any definite write along a straight-line walk of
    `stmts` (loop-carried dependencies). Conservative: branch writes only
    count when both branches write; loop bodies contribute reads but no
    definite writes."""
    rbw = set()

    def expr_reads(node, written):
        if node is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id not in written:
                rbw.add(sub.id)

    def targets_of(t, acc):
        if isinstance(t, ast.Name):
            acc.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets_of(e, acc)
        elif isinstance(t, ast.Starred):
            targets_of(t.value, acc)

    def walk(sts, written):
        for st in sts:
            if isinstance(st, ast.Assign):
                expr_reads(st.value, written)
                for t in st.targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        expr_reads(t, written)
                    else:
                        targets_of(t, written)
            elif isinstance(st, ast.AugAssign):
                expr_reads(st.value, written)
                expr_reads(st.target, written)
                targets_of(st.target, written)
            elif isinstance(st, ast.AnnAssign):
                expr_reads(st.value, written)
                if st.value is not None:
                    targets_of(st.target, written)
            elif isinstance(st, ast.If):
                expr_reads(st.test, written)
                wb, wo = set(written), set(written)
                walk(st.body, wb)
                walk(st.orelse, wo)
                written |= (wb & wo)
            elif isinstance(st, ast.While):
                expr_reads(st.test, written)
                walk(st.body, set(written))
                walk(st.orelse, set(written))
            elif isinstance(st, ast.For):
                expr_reads(st.iter, written)
                inner = set(written)
                targets_of(st.target, inner)
                walk(st.body, inner)
                walk(st.orelse, set(written))
            elif isinstance(st, ast.With):
                for item in st.items:
                    expr_reads(item.context_expr, written)
                    if item.optional_vars is not None:
                        targets_of(item.optional_vars, written)
                walk(st.body, written)
            elif isinstance(st, ast.Try):
                walk(st.body, set(written))
                for h in st.handlers:
                    walk(h.body, set(written))
                walk(st.orelse, set(written))
                walk(st.finalbody, written)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                expr_reads(ast.Module(body=[st], type_ignores=[]), written)
                written.add(st.name)
            else:
                expr_reads(st, written)
        return written

    walk(list(stmts), set())
    return rbw


def _has_nodes(stmts, kinds, *, loop_level=False):
    """Whether `kinds` appear in stmts, not descending into nested scopes;
    with loop_level=True, also not into nested loops (break/continue bind
    to the nearest loop)."""
    barrier = _SCOPE_BARRIERS + ((ast.For, ast.While, ast.AsyncFor)
                                 if loop_level else ())

    def walk(node):
        if isinstance(node, kinds):
            return True
        if isinstance(node, barrier):
            # barrier applies to the node itself too: generated helper
            # FunctionDefs sit at statement level, and their internal
            # returns must not count as the enclosing function's
            return False
        for child in ast.iter_child_nodes(node):
            if walk(child):
                return True
        return False

    return any(walk(st) for st in stmts)


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _make_fdef(name, params, body):
    """Version-portable FunctionDef construction (py3.12 adds
    type_params as a required compile-time field)."""
    kw = {}
    if sys.version_info >= (3, 12):
        kw["type_params"] = []
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(posonlyargs=[],
                           args=[ast.arg(arg=p) for p in params],
                           kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=body, decorator_list=[], **kw)


def _jst_call(fn, *args):
    return ast.Call(
        func=ast.Attribute(value=_name("__jst__"), attr=fn, ctx=ast.Load()),
        args=list(args), keywords=[])


def _lambda0(body):
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                           kw_defaults=[], defaults=[]),
        body=body)


def _pack_call(varnames):
    return _jst_call("pack", *[_lambda0(_name(v)) for v in varnames])


def _unpack_stmt(varnames, src_name):
    return ast.Assign(
        targets=[ast.Tuple(elts=[_name(v, ast.Store()) for v in varnames],
                           ctx=ast.Store())],
        value=_name(src_name))


class _CtrlFlowTransformer:
    def __init__(self):
        self.n = 0
        self.changed = False

    def _fresh(self, tag):
        self.n += 1
        return f"{_PREFIX}_{tag}_{self.n}"

    # -- statement-block driver ---------------------------------------------
    def visit_block(self, stmts, live_after, at_func_tail=False):
        out = []
        stmts = list(stmts)
        i = 0
        while i < len(stmts):
            st = stmts[i]
            rest = stmts[i + 1:]
            # early-return folding (reference return_transformer.py): when
            # an `if` body ends in `return`, the trailing statements are the
            # de-facto else branch — fold them in so the both-branches-
            # return lift applies
            if (isinstance(st, ast.If) and st.body
                    and isinstance(st.body[-1], ast.Return)):
                if rest:
                    st = ast.If(test=st.test, body=st.body,
                                orelse=list(st.orelse) + rest)
                    out.extend(self._visit_stmt(st, live_after))
                    return out  # rest moved inside the else
                if not st.orelse and at_func_tail:
                    st = ast.If(test=st.test, body=st.body,
                                orelse=[ast.Return(
                                    value=ast.Constant(value=None))])
            live = _loads(rest) | live_after
            out.extend(self._visit_stmt(st, live))
            i += 1
        return out

    def _visit_stmt(self, st, live):
        if isinstance(st, ast.If):
            return self._transform_if(st, live)
        if isinstance(st, ast.While):
            return self._transform_while(st, live)
        if isinstance(st, ast.For):
            return self._transform_for(st, live)
        if isinstance(st, ast.With):
            st.body = self.visit_block(st.body, live)
        elif isinstance(st, ast.Try):
            st.body = self.visit_block(st.body, live)
            for h in st.handlers:
                h.body = self.visit_block(h.body, live)
            st.orelse = self.visit_block(st.orelse, live)
            st.finalbody = self.visit_block(st.finalbody, live)
        return [st]

    # -- `if` ---------------------------------------------------------------
    def _transform_if(self, node, live):
        node.body = self.visit_block(node.body, live)
        node.orelse = self.visit_block(node.orelse, live)
        return self._transform_if_visited(node, live)

    def _transform_if_visited(self, node, live):
        # lift `if c: ...; return e1 else: ...; return e2` into an
        # assignment + single return, so tensor-pred branches that return
        # still convert (reference return_transformer.py, the common case)
        if (node.body and isinstance(node.body[-1], ast.Return)
                and node.orelse and isinstance(node.orelse[-1], ast.Return)
                and not _has_nodes(node.body[:-1] + node.orelse[:-1],
                                   (ast.Return,))):
            rname = self._fresh("ret")

            def lift(stmts):
                val = stmts[-1].value
                if val is None:
                    val = ast.Constant(value=None)
                return stmts[:-1] + [ast.Assign(
                    targets=[_name(rname, ast.Store())], value=val)]

            new_if = ast.If(test=node.test, body=lift(node.body),
                            orelse=lift(node.orelse))
            out = self._transform_if_visited(new_if, set(live) | {rname})
            return out + [ast.Return(value=_name(rname))]
        both = node.body + node.orelse
        if _has_nodes(both, (ast.Return,)) \
                or _has_nodes(both, (ast.Break, ast.Continue),
                              loop_level=True):
            return [node]
        assigned = _assigned_names(node.body) | _assigned_names(node.orelse)
        thread = sorted(assigned & live)
        self.changed = True
        test = self._convert_test(node.test)
        tname, tdef = self._branch_fn(self._fresh("true"), node.body, thread)
        fname, fdef = self._branch_fn(self._fresh("false"), node.orelse,
                                      thread)
        args_name = self._fresh("args")
        if not thread:
            # branches assign nothing observable: keep the call for its
            # eager side effects; traced/static regimes no-op it
            return [tdef, fdef, ast.Expr(value=_jst_call(
                "convert_ifelse", test, _name(tname), _name(fname),
                ast.Tuple(elts=[], ctx=ast.Load())))]
        return [
            tdef, fdef,
            ast.Assign(targets=[_name(args_name, ast.Store())],
                       value=_pack_call(thread)),
            ast.Assign(
                targets=[ast.Tuple(
                    elts=[_name(v, ast.Store()) for v in thread],
                    ctx=ast.Store())],
                value=_jst_call("convert_ifelse", test, _name(tname),
                                _name(fname), _name(args_name))),
        ]

    def _branch_fn(self, name, body, thread):
        """def name(__jst_a): (v1,..) = __jst_a; <body>; return pack(..)
        For thread == [] returns a lambda-form function taking and
        returning an empty tuple."""
        param = self._fresh("a")
        stmts = ([_unpack_stmt(thread, param)] if thread else [])
        stmts += list(body)
        stmts.append(ast.Return(value=_pack_call(thread)))
        fdef = _make_fdef(name, [param], stmts)
        return name, fdef

    # -- `while` ------------------------------------------------------------
    def _transform_while(self, node, live):
        body_live = _loads(node.body) | _loads(node.test) | live
        node.body = self.visit_block(node.body, body_live)
        if _has_nodes(node.body, (ast.Break, ast.Continue), loop_level=True) \
                or _has_nodes(node.body, (ast.Return,)):
            node.orelse = self.visit_block(node.orelse, live)
            return [node]
        assigned = _assigned_names(node.body)
        thread = sorted(assigned & (_loads(node.test) | live
                                    | _reads_before_write(node.body)))
        if not thread:
            node.orelse = self.visit_block(node.orelse, live)
            return [node]
        self.changed = True
        param_c, param_b = self._fresh("a"), self._fresh("a")
        cname, bname = self._fresh("cond"), self._fresh("body")
        cdef = _make_fdef(cname, [param_c],
                          [_unpack_stmt(thread, param_c),
                           ast.Return(value=self._convert_test(node.test))])
        bdef = _make_fdef(bname, [param_b],
                          [_unpack_stmt(thread, param_b)] + list(node.body)
                          + [ast.Return(value=_pack_call(thread))])
        args_name = self._fresh("args")
        out = [
            cdef, bdef,
            ast.Assign(targets=[_name(args_name, ast.Store())],
                       value=_pack_call(thread)),
            ast.Assign(
                targets=[ast.Tuple(
                    elts=[_name(v, ast.Store()) for v in thread],
                    ctx=ast.Store())],
                value=_jst_call("convert_while", _name(cname), _name(bname),
                                _name(args_name))),
        ]
        out.extend(self.visit_block(node.orelse, live))
        return out

    # -- `for i in range(...)` ---------------------------------------------
    def _transform_for(self, node, live):
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.iter.keywords
                    and 1 <= len(node.iter.args) <= 3
                    and isinstance(node.target, ast.Name))
        if not is_range \
                or _has_nodes(node.body, (ast.Break, ast.Continue),
                              loop_level=True) \
                or _has_nodes(node.body, (ast.Return,)):
            body_live = _loads(node.body) | _loads(node.iter) | live
            node.body = self.visit_block(node.body, body_live)
            node.orelse = self.visit_block(node.orelse, live)
            return [node]
        i = node.target.id
        stop_n, step_n = self._fresh("stop"), self._fresh("step")
        start_n, ctr = self._fresh("start"), self._fresh("ctr")
        setup = ast.Assign(
            targets=[ast.Tuple(elts=[_name(start_n, ast.Store()),
                                     _name(stop_n, ast.Store()),
                                     _name(step_n, ast.Store())],
                               ctx=ast.Store())],
            value=_jst_call("unpack_range", *node.iter.args))
        # dedicated counter: the loop variable is assigned from it at the
        # top of each iteration, so body reassignment of `i` doesn't change
        # iteration, and post-loop `i` holds the last iterate (Python for
        # semantics)
        init = ast.Assign(targets=[_name(ctr, ast.Store())],
                          value=_name(start_n))
        bind = ast.Assign(targets=[_name(i, ast.Store())],
                          value=_name(ctr))
        incr = ast.Assign(
            targets=[_name(ctr, ast.Store())],
            value=ast.BinOp(left=_name(ctr), op=ast.Add(),
                            right=_name(step_n)))
        wh = ast.While(
            test=_jst_call("range_cond", _name(ctr), _name(stop_n),
                           _name(step_n)),
            body=[bind] + list(node.body) + [incr],
            orelse=list(node.orelse))
        return [setup, init] + self._transform_while(wh, live)

    # -- predicates ---------------------------------------------------------
    def _convert_test(self, e):
        if isinstance(e, ast.BoolOp):
            fn = "and_" if isinstance(e.op, ast.And) else "or_"
            return _jst_call(fn, *[_lambda0(self._convert_test(v))
                                   for v in e.values])
        if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.Not):
            return _jst_call("not_", self._convert_test(e.operand))
        return e


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def convert_function(fn):
    """Return a control-flow-converted version of `fn` (cached); `fn`
    itself when there is nothing to convert or conversion is unsupported."""
    cached = getattr(fn, "__dy2st_fn__", None)
    if cached is not None:
        return cached
    if getattr(fn, "__dy2st_is_converted__", False):
        return fn
    try:
        converted = _convert(fn)
    except (OSError, TypeError, SyntaxError, IndentationError, ValueError):
        # source unavailable (builtins, REPL, C ext) or empty closure
        # cells (self-referential nested defs) — run unconverted
        converted = fn
    try:
        fn.__dy2st_fn__ = converted
    except (AttributeError, TypeError):
        pass
    return converted


def _convert(fn):
    """AST-rewrite `fn`'s control flow; returns the converted function.

    Globals semantics (deliberate, pinned by
    tests/test_dy2static.py::test_monkeypatch_after_convert): the
    converted function executes against `fn.__globals__` ITSELF — the
    live module dict, not a snapshot — so monkeypatching a module global
    after conversion is seen by the converted function exactly as it
    would be by the original (reference program_translator.py builds its
    StaticFunction over the original function object for the same
    reason). The `__jst__` helper module is therefore NOT injected into
    the user's globals; it is passed as the first parameter of the
    compiled factory, so the rewritten body resolves `__jst__` through
    the factory's closure and a user global named `__jst__` is never
    read nor shadowed (see docs/dy2static.md)."""
    if not isinstance(fn, types.FunctionType):
        return fn
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, ast.FunctionDef):
        return fn
    if not any(isinstance(n, (ast.If, ast.While, ast.For))
               for n in ast.walk(fdef)):
        return fn
    if any(isinstance(n, (ast.Global, ast.Nonlocal, ast.Yield,
                          ast.YieldFrom, ast.Await))
           for n in ast.walk(fdef)):
        return fn
    fdef.decorator_list = []
    tr = _CtrlFlowTransformer()
    fdef.body = tr.visit_block(fdef.body, frozenset(), at_func_tail=True)
    if not tr.changed:
        return fn
    freevars = fn.__code__.co_freevars
    if "__jst__" in freevars:
        return fn  # would collide with the helper parameter; run as-is
    factory = ast.FunctionDef(
        name="__jst_factory__",
        args=ast.arguments(posonlyargs=[],
                           args=[ast.arg(arg="__jst__")]
                           + [ast.arg(arg=v) for v in freevars],
                           kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=[fdef, ast.Return(value=_name(fdef.name))],
        decorator_list=[])
    mod = ast.Module(body=[factory], type_ignores=[])
    ast.fix_missing_locations(mod)
    code = compile(mod, f"<dy2static:{getattr(fn, '__qualname__', '?')}>",
                   "exec")
    # exec against the LIVE globals (separate locals keep __jst_factory__
    # out of the user's module namespace)
    ns = {}
    exec(code, fn.__globals__, ns)
    cells = [c.cell_contents for c in (fn.__closure__ or ())]
    new_fn = ns["__jst_factory__"](sys.modules[__name__], *cells)
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    functools.update_wrapper(new_fn, fn, updated=())
    new_fn.__dy2st_is_converted__ = True
    return new_fn


def convert_layer(layer, recursive=True, installed=None):
    """Convert `layer`'s forward in place (instance-level override, so
    hooks/recompute in Layer.__call__ still apply), and — like the
    reference's convert_call (program_translator.py) — recurse into
    sublayers so control flow anywhere in the call tree converts.
    Conversion is semantics-preserving for concrete predicates, so
    converting every forward is safe; per-class function results are
    cached, so repeat conversions are free.

    `installed`: optional list collecting every (sub)layer that received
    an instance-level forward here — jit.save uses it to undo the
    overrides after tracing so export does not permanently mutate the
    caller's model."""
    targets = (layer.sublayers(include_self=True) if recursive
               else [layer])
    for lyr in targets:
        cls_fwd = type(lyr).forward
        conv = convert_function(cls_fwd)
        if conv is not cls_fwd and "forward" not in lyr.__dict__:
            object.__setattr__(lyr, "forward",
                               types.MethodType(conv, lyr))
            if installed is not None:
                installed.append(lyr)
    return layer
