"""paddle.vision.ops — detection operator namespace (the 2.x home of
roi_align/nms/yolo_box; reference python/paddle/vision/ops.py re-exports
over operators/detection/). Implementations live in
paddle_tpu/ops/detection.py."""
from ..ops.detection import (  # noqa: F401
    anchor_generator, bipartite_match, box_clip, box_coder,
    box_decoder_and_assign, collect_fpn_proposals, density_prior_box,
    distribute_fpn_proposals, iou_similarity, matrix_nms, mine_hard_examples,
    multiclass_nms, nms, polygon_box_transform, prior_box, roi_align,
    roi_pool, target_assign, yolo_box, yolov3_loss)
from ..ops.conv import deform_conv2d, psroi_pool  # noqa: F401
from ..ops.loss import sigmoid_focal_loss  # noqa: F401

__all__ = ["roi_align", "roi_pool", "nms", "multiclass_nms", "yolo_box",
           "prior_box", "box_coder", "box_clip", "iou_similarity",
           "bipartite_match", "anchor_generator", "density_prior_box",
           "matrix_nms", "target_assign", "polygon_box_transform",
           "distribute_fpn_proposals", "collect_fpn_proposals",
           "box_decoder_and_assign", "mine_hard_examples", "yolov3_loss",
           "deform_conv2d", "psroi_pool", "sigmoid_focal_loss"]
