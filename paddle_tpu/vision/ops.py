"""paddle.vision.ops — detection operator namespace (the 2.x home of
roi_align/nms/yolo_box; reference python/paddle/vision/ops.py re-exports
over operators/detection/). Implementations live in
paddle_tpu/ops/detection.py."""
from ..ops.detection import (  # noqa: F401
    bipartite_match, box_clip, box_coder, iou_similarity, multiclass_nms,
    nms, prior_box, roi_align, roi_pool, yolo_box)

__all__ = ["roi_align", "roi_pool", "nms", "multiclass_nms", "yolo_box",
           "prior_box", "box_coder", "box_clip", "iou_similarity",
           "bipartite_match"]
