"""Vision transforms (reference python/paddle/vision/transforms/transforms.py).

Numpy-based host-side transforms; CHW float32 in/out unless noted.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomHorizontalFlip",
           "RandomVerticalFlip", "RandomCrop", "CenterCrop", "Transpose",
           "RandomResizedCrop", "BrightnessTransform", "Pad"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    """HWC uint8 -> CHW float32 in [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype("float32") / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr.astype("float32")


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype="float32").reshape(-1, 1, 1)
        self.std = np.asarray(std, dtype="float32").reshape(-1, 1, 1)

    def __call__(self, img):
        return ((np.asarray(img, dtype="float32") - self.mean)
                / self.std).astype("float32")


def _resize_chw(img, size):
    c, h, w = img.shape
    oh, ow = size
    ri = (np.arange(oh) * h / oh).astype(int).clip(0, h - 1)
    ci = (np.arange(ow) * w / ow).astype(int).clip(0, w - 1)
    return img[:, ri][:, :, ci]


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        return _resize_chw(np.asarray(img), self.size)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return img


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1, :].copy()
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        img = np.asarray(img)
        if self.padding:
            p = self.padding
            img = np.pad(img, [(0, 0), (p, p), (p, p)])
        c, h, w = img.shape
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        c, h, w = img.shape
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[:, i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        img = np.asarray(img)
        c, h, w = img.shape
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if th <= h and tw <= w:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return _resize_chw(img[:, i:i + th, j:j + tw], self.size)
        return _resize_chw(img, self.size)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return (np.asarray(img) * alpha).astype("float32")


class Pad:
    def __init__(self, padding, fill=0):
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        p = self.padding
        return np.pad(np.asarray(img), [(0, 0), (p, p), (p, p)],
                      constant_values=self.fill)
