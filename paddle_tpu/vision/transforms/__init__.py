"""Vision transforms (reference python/paddle/vision/transforms/transforms.py).

Numpy-based host-side transforms; CHW float32 in/out unless noted.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomHorizontalFlip",
           "RandomVerticalFlip", "RandomCrop", "CenterCrop", "Transpose",
           "RandomResizedCrop", "BrightnessTransform", "Pad"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    """HWC uint8 -> CHW float32 in [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype("float32") / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr.astype("float32")


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype="float32").reshape(-1, 1, 1)
        self.std = np.asarray(std, dtype="float32").reshape(-1, 1, 1)

    def __call__(self, img):
        return ((np.asarray(img, dtype="float32") - self.mean)
                / self.std).astype("float32")


def _resize_chw(img, size):
    c, h, w = img.shape
    oh, ow = size
    ri = (np.arange(oh) * h / oh).astype(int).clip(0, h - 1)
    ci = (np.arange(ow) * w / ow).astype(int).clip(0, w - 1)
    return img[:, ri][:, :, ci]


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        return _resize_chw(np.asarray(img), self.size)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return img


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1, :].copy()
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        img = np.asarray(img)
        if self.padding:
            p = self.padding
            img = np.pad(img, [(0, 0), (p, p), (p, p)])
        c, h, w = img.shape
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        c, h, w = img.shape
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[:, i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        img = np.asarray(img)
        c, h, w = img.shape
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if th <= h and tw <= w:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return _resize_chw(img[:, i:i + th, j:j + tw], self.size)
        return _resize_chw(img, self.size)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return (np.asarray(img) * alpha).astype("float32")


class Pad:
    def __init__(self, padding, fill=0):
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        p = self.padding
        return np.pad(np.asarray(img), [(0, 0), (p, p), (p, p)],
                      constant_values=self.fill)


# -- round-4 breadth: color/rotation transforms (reference
#    transforms.py ColorJitter :838, RandomRotation :1012, Grayscale
#    :1104 and the Saturation/Contrast/Hue singles) ------------------------

__all__ += ["SaturationTransform", "ContrastTransform", "HueTransform",
            "ColorJitter", "RandomRotation", "Grayscale", "BaseTransform"]

_R, _G, _B = 0.299, 0.587, 0.114   # ITU-R 601 luma


class BaseTransform:
    """reference BaseTransform: keys-aware callable base; subclasses
    implement _apply_image."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


def _chw(img):
    arr = np.asarray(img, "float32")
    if arr.ndim == 2:
        return arr[None], True, False
    if arr.shape[0] in (1, 3, 4):
        return arr, False, False
    return arr.transpose(2, 0, 1), False, True     # HWC in


def _un_chw(arr, was2d, was_hwc):
    if was2d:
        return arr[0]
    if was_hwc:
        return arr.transpose(1, 2, 0)
    return arr


def _grayscale(chw):
    if chw.shape[0] < 3:
        return chw[:1]
    return (_R * chw[0] + _G * chw[1] + _B * chw[2])[None]


class SaturationTransform:
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        chw, a, b = _chw(img)
        f = 1.0 + np.random.uniform(-self.value, self.value)
        gray = _grayscale(chw)
        out = gray + (chw - gray) * f
        return _un_chw(out.astype("float32"), a, b)


class ContrastTransform:
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        chw, a, b = _chw(img)
        f = 1.0 + np.random.uniform(-self.value, self.value)
        mean = _grayscale(chw).mean()
        out = mean + (chw - mean) * f
        return _un_chw(out.astype("float32"), a, b)


class HueTransform:
    """Hue rotation in YIQ space (reference adjust_hue PIL path; this is
    the standard matrix formulation, exact for small angles)."""

    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def __call__(self, img):
        chw, a, b = _chw(img)
        if chw.shape[0] < 3:
            return _un_chw(chw, a, b)
        theta = np.random.uniform(-self.value, self.value) * 2 * np.pi
        cos, sin = np.cos(theta), np.sin(theta)
        t_yiq = np.array([[_R, _G, _B],
                          [0.596, -0.274, -0.322],
                          [0.211, -0.523, 0.312]], "float32")
        rot = np.array([[1, 0, 0], [0, cos, -sin], [0, sin, cos]],
                       "float32")
        m = np.linalg.inv(t_yiq) @ rot @ t_yiq
        flat = chw[:3].reshape(3, -1)
        out = (m @ flat).reshape(chw[:3].shape)
        if chw.shape[0] > 3:
            out = np.concatenate([out, chw[3:]], axis=0)
        return _un_chw(out.astype("float32"), a, b)


class ColorJitter:
    """Randomly-ordered brightness/contrast/saturation/hue jitter
    (reference ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def __call__(self, img):
        for i in np.random.permutation(len(self.ts)):
            img = self.ts[int(i)](img)
        return img


class Grayscale:
    def __init__(self, num_output_channels=1, keys=None):
        self.n = int(num_output_channels)

    def __call__(self, img):
        chw, a, b = _chw(img)
        g = _grayscale(chw)
        out = np.repeat(g, self.n, axis=0) if self.n > 1 else g
        return _un_chw(out.astype("float32"), a, b)


class RandomRotation:
    """Rotate by a uniform random angle (nearest-neighbor resampling about
    the image center — reference RandomRotation's cv2/PIL rotate)."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if np.isscalar(degrees):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            self.degrees = (-float(degrees), float(degrees))
        else:
            self.degrees = (float(degrees[0]), float(degrees[1]))
        self.fill = fill

    def __call__(self, img):
        chw, a, b = _chw(img)
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        c, h, w = chw.shape
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        # inverse map: output pixel -> source pixel
        cos, sin = np.cos(angle), np.sin(angle)
        sy = cy + (yy - cy) * cos - (xx - cx) * sin
        sx = cx + (yy - cy) * sin + (xx - cx) * cos
        iy = np.round(sy).astype(int)
        ix = np.round(sx).astype(int)
        inb = (iy >= 0) & (iy < h) & (ix >= 0) & (ix < w)
        out = np.full_like(chw, float(self.fill))
        src = chw[:, iy.clip(0, h - 1), ix.clip(0, w - 1)]
        out = np.where(inb[None], src, out)
        return _un_chw(out.astype("float32"), a, b)
