"""paddle.vision (reference python/paddle/vision/: models, transforms,
datasets, ops)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from .models import LeNet  # noqa: F401
