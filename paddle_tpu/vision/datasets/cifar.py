"""CIFAR datasets (reference python/paddle/vision/datasets/cifar.py).
Falls back to deterministic synthetic data when the pickle archives are
absent (zero-egress environments)."""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Cifar10", "Cifar100"]


class Cifar10(Dataset):
    NAME = "cifar-10-python.tar.gz"
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        path = data_file or os.path.expanduser(
            f"~/.cache/paddle_tpu/{self.NAME}")
        if os.path.exists(path):
            self._load_archive(path, mode)
        else:
            rng = np.random.RandomState(3 if mode == "train" else 5)
            n = 4096 if mode == "train" else 512
            self.labels = rng.randint(0, self.NUM_CLASSES, n).astype("int64")
            base = rng.randn(self.NUM_CLASSES, 3, 32, 32).astype("float32")
            self.images = (base[self.labels]
                           + rng.randn(n, 3, 32, 32).astype("float32") * 0.8)

    def _load_archive(self, path, mode):
        images, labels = [], []
        want = "data_batch" if mode == "train" else "test_batch"
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if want in m.name:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    images.append(d[b"data"].reshape(-1, 3, 32, 32))
                    key = b"labels" if b"labels" in d else b"fine_labels"
                    labels.extend(d[key])
        self.images = (np.concatenate(images).astype("float32") / 255.0)
        self.labels = np.asarray(labels, dtype="int64")

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NAME = "cifar-100-python.tar.gz"
    NUM_CLASSES = 100
