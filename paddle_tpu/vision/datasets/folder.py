"""DatasetFolder / ImageFolder (reference
python/paddle/vision/datasets/folder.py): directory-tree datasets —
`root/class_x/xxx.png` layout for DatasetFolder, flat recursive image
listing for ImageFolder. Fresh implementation over pathlib + PIL (the
reference uses cv2/PIL backends; TPU hosts only need PIL)."""
from __future__ import annotations

import os

import numpy as np

from ...io import Dataset

__all__ = ["DatasetFolder", "ImageFolder"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    from PIL import Image
    with Image.open(path) as img:
        return np.asarray(img.convert("RGB"))


def has_valid_extension(filename, extensions):
    return filename.lower().endswith(tuple(extensions))


class DatasetFolder(Dataset):
    """root/<class_name>/**/<image> -> (image, class_index) samples."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or default_loader
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(d.name for d in os.scandir(root) if d.is_dir())
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        check = is_valid_file or (
            lambda p: has_valid_extension(p, extensions))
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    p = os.path.join(dirpath, fn)
                    if check(p):
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(
                f"no valid files under {root} (extensions {extensions})")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat recursive listing: every image under root is one sample
    (no labels) — the reference's inference-time loader."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or default_loader
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        check = is_valid_file or (
            lambda p: has_valid_extension(p, extensions))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                p = os.path.join(dirpath, fn)
                if check(p):
                    self.samples.append(p)
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
