"""VOC2012 segmentation dataset (reference
python/paddle/vision/datasets/voc2012.py). Zero-egress: pass the local
VOCtrainval tar via data_file. Returns (image, segmentation label) pairs
parsed straight from the archive's ImageSets/Segmentation lists."""
from __future__ import annotations

import io
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["VOC2012"]

_LIST = {
    "train": "ImageSets/Segmentation/train.txt",
    "valid": "ImageSets/Segmentation/val.txt",
    "test": "ImageSets/Segmentation/trainval.txt",
}


class VOC2012(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if mode not in _LIST:
            raise ValueError(f"mode must be one of {list(_LIST)}")
        if download:
            raise RuntimeError(
                "paddle_tpu runs zero-egress: fetch VOCtrainval yourself "
                "and pass data_file")
        if not data_file:
            raise ValueError("data_file is required (download=False)")
        self.transform = transform
        self._tar_path = data_file
        self._tar = None
        self._keys = None
        self._mode = mode

    def _ensure(self):
        if self._tar is not None:
            return
        self._tar = tarfile.open(self._tar_path)
        names = self._tar.getnames()
        # archives may or may not carry the VOCdevkit/VOC2012 prefix
        prefix = ""
        for n in names:
            if n.endswith(_LIST[self._mode]):
                prefix = n[: -len(_LIST[self._mode])]
                break
        listing = self._tar.extractfile(
            prefix + _LIST[self._mode]).read().decode()
        self._keys = [ln.strip() for ln in listing.splitlines()
                      if ln.strip()]
        self._prefix = prefix

    def _read_image(self, rel):
        data = self._tar.extractfile(self._prefix + rel).read()
        from PIL import Image
        return Image.open(io.BytesIO(data))

    def __getitem__(self, idx):
        self._ensure()
        key = self._keys[idx]
        img = np.asarray(self._read_image(
            f"JPEGImages/{key}.jpg").convert("RGB"))
        label = np.asarray(self._read_image(
            f"SegmentationClass/{key}.png"))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        self._ensure()
        return len(self._keys)
