"""Oxford 102 Flowers (reference python/paddle/vision/datasets/flowers.py).

Zero-egress delta: the reference downloads three files
(102flowers.tgz / imagelabels.mat / setid.mat); here they must already
be on disk — pass data_file/label_file/setid_file. Same record layout:
images are read straight out of the tgz, labels via scipy loadmat,
train/valid/test splits from setid.mat."""
from __future__ import annotations

import io
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Flowers"]

_SPLIT_KEY = {"train": "trnid", "valid": "valid", "test": "tstid"}


class Flowers(Dataset):
    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None):
        if mode not in _SPLIT_KEY:
            raise ValueError(f"mode must be one of {list(_SPLIT_KEY)}")
        if download:
            raise RuntimeError(
                "paddle_tpu runs zero-egress: download the Flowers "
                "archives yourself and pass data_file/label_file/"
                "setid_file")
        if not (data_file and label_file and setid_file):
            raise ValueError("data_file, label_file and setid_file are "
                             "required (download=False)")
        import scipy.io
        self.transform = transform
        labels = scipy.io.loadmat(label_file)["labels"].ravel()
        ids = scipy.io.loadmat(setid_file)[_SPLIT_KEY[mode]].ravel()
        self.indexes = ids.astype(np.int64)          # 1-based image ids
        self.labels = labels
        self._tar_path = data_file
        self._tar = None
        self._names = None

    def _ensure_tar(self):
        if self._tar is None:
            self._tar = tarfile.open(self._tar_path)
            self._names = {n.rsplit("/", 1)[-1]: n
                           for n in self._tar.getnames()
                           if n.endswith(".jpg")}

    def __getitem__(self, idx):
        self._ensure_tar()
        img_id = int(self.indexes[idx])
        name = self._names[f"image_{img_id:05d}.jpg"]
        data = self._tar.extractfile(name).read()
        from PIL import Image
        img = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
        if self.transform is not None:
            img = self.transform(img)
        label = np.asarray([int(self.labels[img_id - 1])], np.int64)
        return img, label

    def __len__(self):
        return len(self.indexes)
