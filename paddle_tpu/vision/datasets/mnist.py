"""MNIST dataset (reference python/paddle/vision/datasets/mnist.py).

Zero-egress environments: if the idx-ubyte files are not present at
`image_path`/`label_path` (or ~/.cache/paddle_tpu/mnist), a deterministic
synthetic digit set with learnable structure is generated instead so
examples/tests/benches run anywhere.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST"]


def _synthetic_digits(n, seed):
    """Digits drawn as coarse 7-seg-style glyphs + noise: classifiable but
    non-trivial."""
    rng = np.random.RandomState(seed)
    images = np.zeros((n, 28, 28), dtype="float32")
    labels = rng.randint(0, 10, n).astype("int64")
    segs = {  # (r0, r1, c0, c1) strokes per digit
        0: [(4, 24, 6, 9), (4, 24, 19, 22), (4, 7, 6, 22), (21, 24, 6, 22)],
        1: [(4, 24, 13, 16)],
        2: [(4, 7, 6, 22), (4, 14, 19, 22), (11, 14, 6, 22), (14, 24, 6, 9),
            (21, 24, 6, 22)],
        3: [(4, 7, 6, 22), (11, 14, 6, 22), (21, 24, 6, 22), (4, 24, 19, 22)],
        4: [(4, 14, 6, 9), (11, 14, 6, 22), (4, 24, 19, 22)],
        5: [(4, 7, 6, 22), (4, 14, 6, 9), (11, 14, 6, 22), (14, 24, 19, 22),
            (21, 24, 6, 22)],
        6: [(4, 24, 6, 9), (11, 14, 6, 22), (14, 24, 19, 22), (21, 24, 6, 22)],
        7: [(4, 7, 6, 22), (4, 24, 19, 22)],
        8: [(4, 24, 6, 9), (4, 24, 19, 22), (4, 7, 6, 22), (11, 14, 6, 22),
            (21, 24, 6, 22)],
        9: [(4, 14, 6, 9), (4, 7, 6, 22), (11, 14, 6, 22), (4, 24, 19, 22)],
    }
    for i in range(n):
        for (r0, r1, c0, c1) in segs[int(labels[i])]:
            images[i, r0:r1, c0:c1] = 1.0
        # jitter: shift and noise
        sh, sw = rng.randint(-2, 3, 2)
        images[i] = np.roll(images[i], (sh, sw), axis=(0, 1))
        images[i] += rng.randn(28, 28).astype("float32") * 0.15
    return np.clip(images, 0.0, 1.0), labels


def _read_idx(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        nd = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(nd)]
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)


class MNIST(Dataset):
    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        images = labels = None
        cache = os.path.expanduser(f"~/.cache/paddle_tpu/{self.NAME}")
        prefix = "train" if mode == "train" else "t10k"
        img = image_path or os.path.join(cache, f"{prefix}-images-idx3-ubyte.gz")
        lab = label_path or os.path.join(cache, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(img) and os.path.exists(lab):
            images = _read_idx(img).astype("float32") / 255.0
            labels = _read_idx(lab).astype("int64")
        else:
            n = 8192 if mode == "train" else 1024
            images, labels = _synthetic_digits(n, seed=7 if mode == "train" else 11)
        self.images = images[:, None, :, :]  # NCHW
        self.labels = labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"
