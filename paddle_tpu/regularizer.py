"""Weight-decay regularizers (reference python/paddle/fluid/regularizer.py:
L1DecayRegularizer / L2DecayRegularizer appended as grad-modifying ops)."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def grad_term(self, param_value):
        return self.coeff * jnp.sign(param_value)


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def grad_term(self, param_value):
        return self.coeff * param_value
