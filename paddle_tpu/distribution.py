"""Probability distributions.

Analog of reference python/paddle/distribution.py (~v2.0-rc ships
Distribution/Uniform/Normal/Categorical; later releases add the rest).
Tensor-in/Tensor-out over the ambient PRNG chain (core/rng.py), sampling
via jax.random so jitted steps get reproducible per-step keys.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .core import rng as _rng
from .core.tensor import Tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "kl_divergence"]


def _raw(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(v):
    return Tensor(v, stop_gradient=True, _internal=True)


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _wrap(jnp.exp(_raw(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    """reference distribution.py Normal."""

    def __init__(self, loc, scale):
        self.loc = _raw(loc).astype(jnp.float32)
        self.scale = _raw(scale).astype(jnp.float32)

    @property
    def mean(self):
        return _wrap(self.loc)

    @property
    def variance(self):
        return _wrap(self.scale ** 2)

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                  self.scale.shape)
        eps = jax.random.normal(_rng.next_key(), shp)
        return _wrap(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _raw(value)
        var = self.scale ** 2
        return _wrap(-((v - self.loc) ** 2) / (2 * var)
                     - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return _wrap(0.5 + 0.5 * math.log(2 * math.pi)
                     + jnp.log(self.scale)
                     + jnp.zeros_like(self.loc))


class Uniform(Distribution):
    def __init__(self, low, high):
        self.low = _raw(low).astype(jnp.float32)
        self.high = _raw(high).astype(jnp.float32)

    def sample(self, shape=()):
        shp = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                  self.high.shape)
        u = jax.random.uniform(_rng.next_key(), shp)
        return _wrap(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        v = _raw(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return _wrap(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return _wrap(jnp.log(self.high - self.low))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None):
        if (logits is None) == (probs is None):
            raise ValueError("pass exactly one of logits/probs")
        if probs is not None:
            p = _raw(probs).astype(jnp.float32)
            self.logits = jnp.log(jnp.maximum(p, 1e-30))
        else:
            self.logits = _raw(logits).astype(jnp.float32)
        self.logits = self.logits - jax.scipy.special.logsumexp(
            self.logits, axis=-1, keepdims=True)

    @property
    def probs(self):
        return _wrap(jnp.exp(self.logits))

    def sample(self, shape=()):
        return _wrap(jax.random.categorical(_rng.next_key(), self.logits,
                                            shape=tuple(shape)
                                            + self.logits.shape[:-1]))

    def log_prob(self, value):
        v = _raw(value).astype(jnp.int32)
        return _wrap(jnp.take_along_axis(self.logits, v[..., None],
                                         axis=-1)[..., 0])

    def entropy(self):
        p = jnp.exp(self.logits)
        return _wrap(-jnp.sum(p * self.logits, axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs):
        self.probs_ = jnp.clip(_raw(probs).astype(jnp.float32), 1e-7,
                               1 - 1e-7)

    def sample(self, shape=()):
        shp = tuple(shape) + self.probs_.shape
        return _wrap(jax.random.bernoulli(_rng.next_key(), self.probs_,
                                          shp).astype(jnp.float32))

    def log_prob(self, value):
        v = _raw(value)
        return _wrap(v * jnp.log(self.probs_)
                     + (1 - v) * jnp.log1p(-self.probs_))

    def entropy(self):
        p = self.probs_
        return _wrap(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _raw(alpha).astype(jnp.float32)
        self.beta = _raw(beta).astype(jnp.float32)

    def sample(self, shape=()):
        shp = tuple(shape) + jnp.broadcast_shapes(self.alpha.shape,
                                                  self.beta.shape)
        return _wrap(jax.random.beta(_rng.next_key(), self.alpha,
                                     self.beta, shp))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        v = _raw(value)
        return _wrap((self.alpha - 1) * jnp.log(v)
                     + (self.beta - 1) * jnp.log1p(-v)
                     - betaln(self.alpha, self.beta))

    def entropy(self):
        from jax.scipy.special import betaln, digamma
        a, b = self.alpha, self.beta
        return _wrap(betaln(a, b) - (a - 1) * digamma(a)
                     - (b - 1) * digamma(b)
                     + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _raw(concentration).astype(jnp.float32)

    def sample(self, shape=()):
        return _wrap(jax.random.dirichlet(_rng.next_key(),
                                          self.concentration,
                                          tuple(shape)
                                          + self.concentration.shape[:-1]))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        a = self.concentration
        v = _raw(value)
        norm = jnp.sum(gammaln(a), -1) - gammaln(jnp.sum(a, -1))
        return _wrap(jnp.sum((a - 1) * jnp.log(v), -1) - norm)

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        lnB = jnp.sum(gammaln(a), -1) - gammaln(a0)
        return _wrap(lnB + (a0 - k) * digamma(a0)
                     - jnp.sum((a - 1) * digamma(a), -1))


def kl_divergence(p, q):
    """Closed-form KL for matching families (reference
    paddle.distribution.kl_divergence registry)."""
    if isinstance(p, Normal) and isinstance(q, Normal):
        vr = (p.scale / q.scale) ** 2
        return _wrap(0.5 * (vr + ((p.loc - q.loc) / q.scale) ** 2
                            - 1 - jnp.log(vr)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        pp = jnp.exp(p.logits)
        return _wrap(jnp.sum(pp * (p.logits - q.logits), axis=-1))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        a, b = p.probs_, q.probs_
        return _wrap(a * (jnp.log(a) - jnp.log(b))
                     + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return _wrap(jnp.log((q.high - q.low) / (p.high - p.low)))
    if isinstance(p, Beta) and isinstance(q, Beta):
        from jax.scipy.special import betaln, digamma
        a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
        return _wrap(betaln(a2, b2) - betaln(a1, b1)
                     + (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
                     + (a2 - a1 + b2 - b1) * digamma(a1 + b1))
    if isinstance(p, Dirichlet) and isinstance(q, Dirichlet):
        from jax.scipy.special import digamma, gammaln
        a, b = p.concentration, q.concentration
        a0 = jnp.sum(a, -1, keepdims=True)
        t1 = gammaln(jnp.sum(a, -1)) - gammaln(jnp.sum(b, -1))
        t2 = jnp.sum(gammaln(b) - gammaln(a), -1)
        t3 = jnp.sum((a - b) * (digamma(a) - digamma(a0)), -1)
        return _wrap(t1 + t2 + t3)
    raise NotImplementedError(
        f"no closed-form KL for {type(p).__name__} vs {type(q).__name__}")
