"""paddle.callbacks — hapi callback re-exports (reference
python/paddle/callbacks.py does exactly this over hapi/callbacks.py)."""
from .hapi.callbacks import (  # noqa: F401
    Callback, CallbackList, EarlyStopping, History, LRSchedulerCallback,
    ModelCheckpoint, ProfilerCallback, ProgBarLogger)

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRSchedulerCallback", "History",
           "ProfilerCallback"]
