"""Weight-averaging training utilities.

Reference analogs: fluid/optimizer.py ExponentialMovingAverage (:4316),
ModelAverage (:4790), LookaheadOptimizer (:5700). The reference rewrites
programs with accumulator ops; here each is a small functional state
machine over the layer's parameters — update() after each optimizer
step, apply()/restore() (or the context form) to evaluate with the
averaged weights.
"""
from __future__ import annotations

import contextlib

import numpy as np

__all__ = ["ExponentialMovingAverage", "ModelAverage", "LookAhead"]


def _named_params(obj):
    """Accept a Layer or an iterable of parameters."""
    if hasattr(obj, "named_parameters"):
        return list(obj.named_parameters())
    return [(getattr(p, "name", None) or f"param_{i}", p)
            for i, p in enumerate(obj)]


class ExponentialMovingAverage:
    """shadow = decay * shadow + (1 - decay) * param, with the reference's
    Adam-style bias correction (shadow / (1 - decay^t)).

    The shadow starts at zero (EMA_0 = 0), exactly as the reference defines
    it — the /(1 - decay^t) correction assumes that zero init; seeding with
    the live parameters instead would over-scale apply() by ~1/(1-decay^t)
    for small t.
    """

    def __init__(self, network, decay=0.999):
        import jax.numpy as jnp
        self._params = _named_params(network)
        self.decay = float(decay)
        self._t = 0
        self._shadow = {n: jnp.zeros_like(p._value)
                        for n, p in self._params}
        self._backup = None

    def update(self):
        self._t += 1
        d = self.decay
        for n, p in self._params:
            self._shadow[n] = d * self._shadow[n] + (1.0 - d) * p._value

    def apply(self):
        """Swap bias-corrected EMA weights in (call restore() after)."""
        if self._backup is not None:
            raise RuntimeError("EMA already applied; call restore() first")
        corr = 1.0 - self.decay ** max(self._t, 1)
        self._backup = {n: p._value for n, p in self._params}
        for n, p in self._params:
            p.set_value(self._shadow[n] / corr)
        return self

    def restore(self):
        if self._backup is None:
            return self
        for n, p in self._params:
            p.set_value(self._backup[n])
        self._backup = None
        return self

    @contextlib.contextmanager
    def average_weights(self):
        self.apply()
        try:
            yield
        finally:
            self.restore()

    def state_dict(self):
        return {"shadow": {n: np.asarray(v)
                           for n, v in self._shadow.items()},
                "t": self._t, "decay": self.decay}

    def set_state_dict(self, state):
        import jax.numpy as jnp
        self._shadow = {n: jnp.asarray(v)
                        for n, v in state["shadow"].items()}
        self._t = int(state["t"])
        self.decay = float(state["decay"])
        return self


class ModelAverage:
    """Running average of parameters over an update window (reference
    ModelAverage: accumulators restarted when the window exceeds
    max_average_window)."""

    def __init__(self, network, average_window_rate=0.15,
                 min_average_window=10000, max_average_window=10000):
        import jax.numpy as jnp
        self._params = _named_params(network)
        self.rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        self._sum = {n: jnp.zeros_like(p._value) for n, p in self._params}
        self._n = 0
        self._updates = 0
        self._backup = None

    def update(self):
        self._updates += 1
        window = max(self.min_window,
                     min(self.max_window,
                         int(self._updates * self.rate) or 1))
        if self._n >= window:
            # restart the accumulator, seeded with the current average
            for n, _ in self._params:
                self._sum[n] = self._sum[n] / self._n
            self._n = 1
        for n, p in self._params:
            self._sum[n] = self._sum[n] + p._value
        self._n += 1

    def apply(self):
        if self._backup is not None:
            raise RuntimeError("ModelAverage already applied")
        self._backup = {n: p._value for n, p in self._params}
        for n, p in self._params:
            p.set_value(self._sum[n] / max(self._n, 1))
        return self

    def restore(self):
        if self._backup is None:
            return self
        for n, p in self._params:
            p.set_value(self._backup[n])
        self._backup = None
        return self

    @contextlib.contextmanager
    def average_weights(self):
        self.apply()
        try:
            yield
        finally:
            self.restore()


class LookAhead:
    """Lookahead optimizer wrapper (reference LookaheadOptimizer; Zhang et
    al. 2019): the inner optimizer takes k fast steps, then slow weights
    move alpha of the way toward the fast weights and the fast weights
    reset to them. Wraps any paddle_tpu Optimizer; works through both the
    eager step() path and apply_gradients_pure (the blend itself is a
    host-side rebind, like the reference's program-inserted assign ops)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._steps = 0
        self._slow = None
        self._params = list(getattr(inner_optimizer, "_parameter_list",
                                    None) or [])

    # pass-throughs -------------------------------------------------------
    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _ensure_slow(self):
        if self._slow is None:
            import jax.numpy as jnp
            self._slow = [jnp.array(p._value) for p in self._params]

    def step(self):
        self._ensure_slow()
        self.inner.step()
        self._steps += 1
        if self._steps % self.k == 0:
            for i, p in enumerate(self._params):
                slow = self._slow[i] + self.alpha * (p._value
                                                     - self._slow[i])
                self._slow[i] = slow
                p.set_value(slow)

    def clear_grad(self, *a, **k):
        return self.inner.clear_grad(*a, **k)
