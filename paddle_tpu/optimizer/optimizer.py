"""Optimizers.

Analog of reference python/paddle/optimizer/ (optimizer.py Optimizer base,
adam.py, adamw.py, momentum.py, ...) backed by operators/optimizers/* CUDA
kernels (17 families: sgd, momentum+lars, adam/adamw/adamax/lamb,
adagrad/adadelta/rmsprop, ...).

TPU design delta (SURVEY.md §7): the whole update — regularizer terms, grad
clip, every parameter's rule — is ONE pure function over (params, grads,
slots, lr, t) pytrees, jitted with buffer donation. XLA fuses it into a few
kernels, which is the analog of the reference's fuse_optimizer_ops_pass
(ir/fuse_optimizer_ops_pass/) and fused_adam. The same pure function embeds
directly into hapi/static whole-step programs.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core import tape as _tape
from . import lr as lr_mod
from .clip import ClipGradBase
from ..regularizer import L1Decay, L2Decay

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
           "Adagrad", "Adadelta", "RMSProp", "Lamb", "Lars", "Ftrl",
           "Dpsgd"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if isinstance(weight_decay, float):
            weight_decay = L2Decay(weight_decay)
        self._weight_decay = weight_decay
        self._multi_precision = multi_precision
        self._slots: Dict[str, Dict[str, jnp.ndarray]] = {}
        self._step_count = 0
        self._step_fn = None
        self._step_fn_sig = None

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, lr_mod.LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, lr_mod.LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    @property
    def _lr_scheduler(self):
        lr = self._learning_rate
        return lr if isinstance(lr, lr_mod.LRScheduler) else None

    # -- slots ---------------------------------------------------------------
    @staticmethod
    def _slot_like(v):
        """Moment buffers stay float32 even for bf16/f16 params — reduced-
        precision moments diverge (the reference's multi_precision /
        master-weight path in adam_op.cu serves the same purpose)."""
        if jnp.issubdtype(v.dtype, jnp.floating):
            return jnp.zeros(v.shape, jnp.float32)
        return jnp.zeros_like(v)

    def _init_slots_for(self, name: str, value) -> dict:
        """Per-parameter optimizer state; override per optimizer."""
        return {}

    def _ensure_slots(self, params: Dict[str, jnp.ndarray]):
        for name, v in params.items():
            if name not in self._slots:
                s = self._init_slots_for(name, v)
                if self._multi_precision and v.dtype in (jnp.bfloat16,
                                                         jnp.float16):
                    # f32 master copy (reference multi_precision path,
                    # operators/optimizers/adam_op.cu MasterParam)
                    s["master"] = v.astype(jnp.float32)
                self._slots[name] = s

    # -- the pure update (embeddable in any jitted program) ------------------
    def _rule(self, p, g, slots, lr, t):
        raise NotImplementedError

    def apply_gradients_pure(self, params, grads, slots, lr, t, param_meta=None):
        """Pure: (params, grads, slots, lr_scalar, step) -> (new_params, new_slots).

        param_meta: {name: {"lr_ratio": float, "regularizer": obj|None,
                            "need_clip": bool}}
        """
        param_meta = param_meta or {}
        # 1) regularizer terms (reference: regularizer.py append_regularization_ops)
        reg_grads = {}
        for k, g in grads.items():
            meta = param_meta.get(k, {})
            reg = meta.get("regularizer", self._coupled_decay_default())
            if reg is not None:
                g = g + reg.grad_term(params[k]).astype(g.dtype)
            reg_grads[k] = g
        # 2) clip (reference: clip.py _append_clip_op)
        if self._grad_clip is not None:
            clippable = {k: g for k, g in reg_grads.items()
                         if param_meta.get(k, {}).get("need_clip", True)}
            clipped = self._grad_clip.apply(clippable)
            reg_grads.update(clipped)
        # 3) per-param rule (master-weight path: rule runs on the f32 master
        # slot, the low-precision param is re-derived from it)
        new_params, new_slots = {}, {}
        for k, p in params.items():
            g = reg_grads[k]
            lr_k = lr * param_meta.get(k, {}).get("lr_ratio", 1.0)
            sl = self._slots_of(slots, k)
            master = sl.get("master") if isinstance(sl, dict) else None
            if master is not None:
                rest = {kk: vv for kk, vv in sl.items() if kk != "master"}
                new_master, ns = self._rule(master, g.astype(jnp.float32),
                                            rest, lr_k, t)
                ns = dict(ns)
                ns["master"] = new_master
                new_params[k] = new_master.astype(p.dtype)
            else:
                new_params[k], ns = self._rule(p, g.astype(p.dtype), sl,
                                               lr_k, t)
            new_slots[k] = ns
        return new_params, new_slots

    def _coupled_decay_default(self):
        return self._weight_decay

    @staticmethod
    def _slots_of(slots, k):
        return slots.get(k, {})

    # -- eager step ----------------------------------------------------------
    def _collect(self):
        if self._parameter_list is None:
            raise ValueError("optimizer constructed without parameters=")
        out = OrderedDict()
        for i, p in enumerate(self._parameter_list):
            if p.stop_gradient or p.grad is None:
                continue
            name = p.name or f"param_{i}"
            out[name] = p
        return out

    def _param_meta(self, named):
        meta = {}
        for name, p in named.items():
            meta[name] = {
                "lr_ratio": getattr(p, "optimize_attr", {}).get("learning_rate", 1.0),
                "regularizer": getattr(p, "regularizer", None) or self._coupled_decay_default(),
                "need_clip": getattr(p, "need_clip", True),
            }
        return meta

    def _get_step_fn(self, named):
        sig = tuple(sorted(named))
        if self._step_fn is None or self._step_fn_sig != sig:
            meta = self._param_meta(named)

            def step_fn(params, grads, slots, lr, t):
                return self.apply_gradients_pure(params, grads, slots, lr, t,
                                                 param_meta=meta)

            # donate only the slots: a retained grad graph
            # (backward(retain_graph=True)) may still reference the live
            # parameter buffers, so donating argnum 0 would let a later
            # backward read deleted storage
            self._step_fn = jax.jit(step_fn, donate_argnums=(2,))
            self._step_fn_sig = sig
        return self._step_fn

    def _sparse_update(self, name, param, sr, lr):
        """Row-wise update for a SelectedRows grad; optimizers that can't
        (or shouldn't — adam without lazy_mode decays ALL moments) return
        False to take the densify path. Reference: sgd_op.cc /
        adam_op.cc SelectedRows kernels."""
        return False

    @_tape.no_grad()
    def step(self):
        from ..core.selected_rows import SelectedRows
        from ..core.tensor import Tensor as _T
        named = self._collect()
        if not named:
            return
        # sparse grads (eager embedding sparse=True): row-wise path where
        # the optimizer supports it, densify otherwise
        lr_now = jnp.asarray(self.get_lr(), jnp.float32)
        for k in list(named):
            p = named[k]
            if isinstance(p.grad._value, SelectedRows):
                self._ensure_slots({k: p._value})
                if self._sparse_update(k, p, p.grad._value.coalesce(),
                                       lr_now):
                    p.grad = None
                    del named[k]
                else:
                    p.grad = _T(p.grad._value.to_dense(),
                                stop_gradient=True, _internal=True)
        if not named:
            self._step_count += 1
            return
        params = {k: p._value for k, p in named.items()}
        grads = {k: p.grad._value for k, p in named.items()}
        self._ensure_slots(params)
        slots = {k: self._slots[k] for k in named}
        self._step_count += 1
        fn = self._get_step_fn(named)
        new_params, new_slots = fn(params, grads, slots,
                                   jnp.asarray(self.get_lr(), jnp.float32),
                                   jnp.asarray(self._step_count, jnp.int32))
        for k, p in named.items():
            p._value = new_params[k]
            p._node = None
        self._slots.update(new_slots)

    def clear_grad(self, set_to_zero=False):
        if self._parameter_list is not None:
            for p in self._parameter_list:
                p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Dygraph: backward + step. Static mode: record backward + update
        sections into the program (reference optimizer.py minimize /
        apply_gradients; executed by the static Executor as one compiled
        step)."""
        import sys
        smod = sys.modules.get("paddle_tpu.static.program")
        if smod is not None and isinstance(loss, smod.Variable):
            from ..static import append_backward
            plist = parameters or self._parameter_list
            pairs = append_backward(loss, parameter_list=plist)
            program = loss.program or smod.default_main_program()
            program.optimizer_section = (self, pairs)
            program._version += 1
            return [], pairs
        loss.backward()
        self.step()
        return [], []

    # -- state ---------------------------------------------------------------
    def state_dict(self):
        out = {"_step_count": self._step_count}
        for pname, slots in self._slots.items():
            for sname, v in slots.items():
                out[f"{pname}/{sname}"] = np.asarray(v)
        sched = self._lr_scheduler
        if sched is not None:
            out["LR_Scheduler"] = sched.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.get("_step_count", 0))
        sched = self._lr_scheduler
        if sched is not None and "LR_Scheduler" in state:
            sched.set_state_dict(state["LR_Scheduler"])
        for key, v in state.items():
            if key in ("_step_count", "LR_Scheduler"):
                continue
            if "/" not in key:
                continue
            pname, sname = key.rsplit("/", 1)
            arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
            self._slots.setdefault(pname, {})[sname] = jnp.asarray(arr)
        # force step fn rebuild (slot structure may have changed)
        self._step_fn = None

    set_dict = set_state_dict


class SGD(Optimizer):
    """reference: operators/optimizers/sgd_op.cc"""

    def _rule(self, p, g, slots, lr, t):
        return p - lr.astype(p.dtype) * g, {}

    def _sparse_update(self, name, param, sr, lr):
        # sgd_op.cc's SelectedRows kernel: touch only the looked-up rows
        param._value = param._value.at[sr.rows].add(
            (-lr * sr.values.astype(jnp.float32)).astype(param._value.dtype))
        param._node = None
        return True


class Momentum(Optimizer):
    """reference: operators/optimizers/momentum_op.cc (use_nesterov attr)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_slots_for(self, name, v):
        return {"velocity": self._slot_like(v)}

    def _rule(self, p, g, slots, lr, t):
        g32 = g.astype(jnp.float32)
        v = self._momentum * slots["velocity"] + g32
        if self._nesterov:
            upd = lr * (g32 + self._momentum * v)
        else:
            upd = lr * v
        new_p = (p.astype(jnp.float32) - upd).astype(p.dtype)
        return new_p, {"velocity": v}


class Adam(Optimizer):
    """reference: operators/optimizers/adam_op.cc (+ fused/fused_adam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy_mode = lazy_mode

    def _init_slots_for(self, name, v):
        return {"moment1": self._slot_like(v), "moment2": self._slot_like(v)}

    def _sparse_update(self, name, param, sr, lr):
        # adam_op.cc lazy_mode: moments update only for touched rows (a
        # non-lazy adam must decay every row's moments -> densify path)
        if not self._lazy_mode:
            return False
        slots = self._slots[name]
        m, v = slots["moment1"], slots["moment2"]
        rows = sr.rows
        g = sr.values.astype(jnp.float32)
        m_r = self._beta1 * m[rows] + (1 - self._beta1) * g
        v_r = self._beta2 * v[rows] + (1 - self._beta2) * jnp.square(g)
        t = jnp.float32(self._step_count + 1)
        bc1 = 1 - jnp.power(jnp.float32(self._beta1), t)
        bc2 = 1 - jnp.power(jnp.float32(self._beta2), t)
        upd = (lr * jnp.sqrt(bc2) / bc1) * m_r / (jnp.sqrt(v_r)
                                                  + self._epsilon)
        slots["moment1"] = m.at[rows].set(m_r)
        slots["moment2"] = v.at[rows].set(v_r)
        param._value = param._value.at[rows].add(
            (-upd).astype(param._value.dtype))
        param._node = None
        return True

    def _rule(self, p, g, slots, lr, t):
        # moment math in f32 regardless of param dtype (bf16-safe)
        g32 = g.astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * jnp.square(g32)
        tf = t.astype(jnp.float32)
        bc1 = 1 - jnp.power(jnp.float32(self._beta1), tf)
        bc2 = 1 - jnp.power(jnp.float32(self._beta2), tf)
        step_size = lr * jnp.sqrt(bc2) / bc1
        upd = step_size * m / (jnp.sqrt(v) + self._epsilon)
        new_p = (p.astype(jnp.float32) - upd).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py —
    decay applied to the parameter, not through the moments)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 grad_clip=None, lazy_mode=False, apply_decay_param_fun=None,
                 name=None, multi_precision=False, lr_ratio=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, name, multi_precision)
        self._decoupled_wd = weight_decay if isinstance(weight_decay, float) \
            else getattr(weight_decay, "coeff", 0.0)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _coupled_decay_default(self):
        return None  # decay is decoupled

    def apply_gradients_pure(self, params, grads, slots, lr, t,
                             param_meta=None):
        new_params, new_slots = super().apply_gradients_pure(
            params, grads, slots, lr, t, param_meta)
        wd = self._decoupled_wd
        if wd:
            for k in new_params:
                if (self._apply_decay_param_fun is not None
                        and not self._apply_decay_param_fun(k)):
                    continue
                p = params[k]
                sl = new_slots.get(k, {})
                if "master" in sl:
                    # decay must land on the f32 master (the param is
                    # re-derived from it next step — decaying only the bf16
                    # copy would silently discard the decay every step)
                    old_master = self._slots_of(slots, k).get(
                        "master", p.astype(jnp.float32))
                    sl["master"] = sl["master"] - lr * wd * old_master
                    new_params[k] = sl["master"].astype(p.dtype)
                else:
                    new_params[k] = new_params[k] \
                        - (lr * wd).astype(p.dtype) * p
        return new_params, new_slots


class Adamax(Optimizer):
    """reference: operators/optimizers/adamax_op.cc"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_slots_for(self, name, v):
        return {"moment": self._slot_like(v), "inf_norm": self._slot_like(v)}

    def _rule(self, p, g, slots, lr, t):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g32))
        bc1 = 1 - jnp.power(jnp.float32(self._beta1), t.astype(jnp.float32))
        upd = (lr / bc1) * m / (u + self._epsilon)
        new_p = (p.astype(jnp.float32) - upd).astype(p.dtype)
        return new_p, {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    """reference: operators/optimizers/adagrad_op.cc"""

    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_slots_for(self, name, v):
        return {"moment": jnp.full_like(v, self._init_acc)}

    def _rule(self, p, g, slots, lr, t):
        g32 = g.astype(jnp.float32)
        acc = slots["moment"] + jnp.square(g32)
        upd = lr * g32 / (jnp.sqrt(acc) + self._epsilon)
        new_p = (p.astype(jnp.float32) - upd).astype(p.dtype)
        return new_p, {"moment": acc}


class Adadelta(Optimizer):
    """reference: operators/optimizers/adadelta_op.cc"""

    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon, self._rho = epsilon, rho

    def _init_slots_for(self, name, v):
        return {"avg_squared_grad": self._slot_like(v),
                "avg_squared_update": self._slot_like(v)}

    def _rule(self, p, g, slots, lr, t):
        rho = self._rho
        eps = self._epsilon
        g32 = g.astype(jnp.float32)
        asg = rho * slots["avg_squared_grad"] + (1 - rho) * jnp.square(g32)
        update = -jnp.sqrt((slots["avg_squared_update"] + eps)
                           / (asg + eps)) * g32
        asu = rho * slots["avg_squared_update"] + (1 - rho) * jnp.square(update)
        new_p = (p.astype(jnp.float32) + lr * update).astype(p.dtype)
        return new_p, {"avg_squared_grad": asg, "avg_squared_update": asu}


class RMSProp(Optimizer):
    """reference: operators/optimizers/rmsprop_op.cc (centered variant)."""

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_slots_for(self, name, v):
        s = {"mean_square": self._slot_like(v),
             "momentum": self._slot_like(v)}
        if self._centered:
            s["mean_grad"] = self._slot_like(v)
        return s

    def _rule(self, p, g, slots, lr, t):
        rho = self._rho
        g32 = g.astype(jnp.float32)
        ms = rho * slots["mean_square"] + (1 - rho) * jnp.square(g32)
        out_slots = {"mean_square": ms}
        if self._centered:
            mg = rho * slots["mean_grad"] + (1 - rho) * g32
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
            out_slots["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * slots["momentum"] + lr * g32 / denom
        out_slots["momentum"] = mom
        new_p = (p.astype(jnp.float32) - mom).astype(p.dtype)
        return new_p, out_slots


class Lamb(Optimizer):
    """reference: operators/optimizers/lamb_op.cc (layer-adaptive Adam)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-06, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_slots_for(self, name, v):
        return {"moment1": self._slot_like(v), "moment2": self._slot_like(v)}

    def _rule(self, p, g, slots, lr, t):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * jnp.square(g32)
        tf = t.astype(jnp.float32)
        mhat = m / (1 - jnp.power(jnp.float32(self._beta1), tf))
        vhat = v / (1 - jnp.power(jnp.float32(self._beta2), tf))
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + self._wd * p32
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = (p32 - lr * trust * r).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v}


class Lars(Momentum):
    """LARS momentum (reference: operators/optimizers/lars_momentum_op.cc)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 lars_coeff=0.001, lars_weight_decay=0.0005, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, momentum, parameters, False, None,
                         grad_clip, name)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay

    def _rule(self, p, g, slots, lr, t):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm / (g_norm + self._lars_wd * w_norm + 1e-12),
            1.0)
        eff = g32 + self._lars_wd * p32
        v = self._momentum * slots["velocity"] + lr * local_lr * eff
        new_p = (p32 - v).astype(p.dtype)
        return new_p, {"velocity": v}


class Ftrl(Optimizer):
    """FTRL-proximal (reference: operators/optimizers/ftrl_op.cc —
    squared/linear accumulators, l1/l2 regularization, lr_power)."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _init_slots_for(self, name, v):
        return {"squared": self._slot_like(v), "linear": self._slot_like(v)}

    def _rule(self, p, g, slots, lr, t):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        n = slots["squared"]
        z = slots["linear"]
        new_n = n + jnp.square(g32)
        lp = -self._lr_power  # 0.5 for the default
        sigma = (jnp.power(new_n, lp) - jnp.power(n, lp)) / lr
        new_z = z + g32 - sigma * p32
        denom = jnp.power(new_n, lp) / lr + 2 * self._l2
        new_p = jnp.where(
            jnp.abs(new_z) > self._l1,
            (jnp.sign(new_z) * self._l1 - new_z) / denom, 0.0)
        return new_p.astype(p.dtype), {"squared": new_n, "linear": new_z}


class Dpsgd(Optimizer):
    """Differentially-private SGD (reference:
    operators/optimizers/dpsgd_op.cc — per-update gradient norm clipping
    plus calibrated gaussian noise). Noise is drawn from a key derived
    deterministically from (seed, step), so the update stays a pure
    jittable function."""

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, parameters=None, seed=0, name=None):
        super().__init__(learning_rate, parameters, None, None, name)
        self._clip = clip
        self._batch = batch_size
        self._sigma = sigma
        self._seed = seed

    def _rule(self, p, g, slots, lr, t):
        import jax as _jax
        g32 = g.astype(jnp.float32)
        norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
        g32 = g32 / jnp.maximum(1.0, norm / self._clip)
        key = _jax.random.fold_in(
            _jax.random.fold_in(_jax.random.PRNGKey(self._seed),
                                t.astype(jnp.uint32)),
            jnp.uint32(abs(hash(str(p.shape))) % (2 ** 31)))
        noise = self._sigma * self._clip / self._batch \
            * _jax.random.normal(key, p.shape)
        new_p = (p.astype(jnp.float32) - lr * (g32 + noise)).astype(p.dtype)
        return new_p, {}
