from . import lr  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .optimizer import (Adadelta, Adagrad, Adam, Adamax, AdamW, Dpsgd,  # noqa: F401
                        Ftrl, Lamb, Lars, Momentum, Optimizer, RMSProp, SGD)
from .averaging import (ExponentialMovingAverage, LookAhead,  # noqa: F401
                        ModelAverage)
