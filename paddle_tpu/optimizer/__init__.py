from . import lr  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .optimizer import (Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Lars,  # noqa: F401
                        Momentum, Optimizer, RMSProp, SGD)
