"""Gradient clipping.

Analog of reference python/paddle/fluid/clip.py (ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm). Clips operate on raw grad pytrees so
they fuse into the jitted optimizer step (the reference appends clip ops to
the program; here XLA fuses the global-norm reduction with the updates).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm"]


class ClipGradBase:
    def apply(self, grads_dict, params_meta=None):
        """grads_dict: {name: raw grad array} -> clipped dict."""
        raise NotImplementedError

    def __call__(self, params_grads):
        # paddle-style [(param, grad)] interface
        from ..core.tensor import Tensor
        names = [str(i) for i in range(len(params_grads))]
        gd = {n: g._value for n, (_, g) in zip(names, params_grads)}
        out = self.apply(gd)
        return [(p, Tensor(out[n], stop_gradient=True, _internal=True))
                for n, (p, _) in zip(names, params_grads)]


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def apply(self, grads, params_meta=None):
        return {k: jnp.clip(g, self.min, self.max) for k, g in grads.items()}


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply(self, grads, params_meta=None):
        out = {}
        for k, g in grads.items():
            norm = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.where(norm > self.clip_norm,
                              self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out[k] = g * scale.astype(g.dtype)
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def apply(self, grads, params_meta=None):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                 for g in grads.values())
        global_norm = jnp.sqrt(sq)
        scale = jnp.where(global_norm > self.clip_norm,
                          self.clip_norm / jnp.maximum(global_norm, 1e-12),
                          1.0)
        return {k: (g * scale).astype(g.dtype) for k, g in grads.items()}
