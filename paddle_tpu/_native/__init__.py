"""Native (C++) runtime pieces, built on demand.

The reference keeps its data-feed/channel tier in C++
(framework/data_feed.cc, framework/channel.h) because Python can't parse
fast enough to feed accelerators. Same split here: the MultiSlot parser is
C++ compiled once per machine into _native/lib/ and bound via ctypes (no
pybind dependency; ctypes calls release the GIL, so thread pools get real
file-level parallelism). A pure-Python fallback keeps the API alive when
no toolchain is present.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "lib", "libpaddle_tpu_native.so")
_SRC = os.path.join(_HERE, "src", "multislot_parser.cc")
_lock = threading.Lock()
_lib = None
_build_failed = False


def _build():
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    tmp = _SO + ".tmp"
    subprocess.run(
        ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
        check=True, capture_output=True)
    os.replace(tmp, _SO)


def native_lib():
    """The loaded ctypes library, building it first if needed; None when
    unavailable (callers fall back to Python)."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or (
                    os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
            lib.pt_parse_multislot_file.restype = ctypes.c_void_p
            lib.pt_parse_multislot_file.argtypes = [ctypes.c_char_p,
                                                    ctypes.c_char_p]
            lib.pt_ms_rows.restype = ctypes.c_longlong
            lib.pt_ms_rows.argtypes = [ctypes.c_void_p]
            lib.pt_ms_error.restype = ctypes.c_char_p
            lib.pt_ms_error.argtypes = [ctypes.c_void_p]
            lib.pt_ms_slot_total.restype = ctypes.c_longlong
            lib.pt_ms_slot_total.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.pt_ms_copy_splits.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                              ctypes.c_void_p]
            lib.pt_ms_copy_f32.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                           ctypes.c_void_p]
            lib.pt_ms_copy_i64.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                           ctypes.c_void_p]
            lib.pt_ms_free.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:
            _build_failed = True
            _lib = None
        return _lib


def parse_multislot_file(path, slot_types):
    """Parse one MultiSlot text file -> per-slot (values, row_splits)
    numpy arrays. slot_types: list of 'uint64' | 'float'."""
    lib = native_lib()
    if lib is None:
        return _parse_multislot_py(path, slot_types)
    h = lib.pt_parse_multislot_file(
        path.encode(), ",".join(slot_types).encode())
    if not h:
        raise IOError(f"cannot parse {path}")
    try:
        err = lib.pt_ms_error(h)
        if err:
            raise ValueError(f"{path}: {err.decode()}")
        rows = int(lib.pt_ms_rows(h))
        out = []
        for s, t in enumerate(slot_types):
            total = int(lib.pt_ms_slot_total(h, s))
            splits = np.empty(rows + 1, np.int64)
            lib.pt_ms_copy_splits(h, s, splits.ctypes.data_as(
                ctypes.c_void_p))
            if t == "float":
                vals = np.empty(total, np.float32)
                lib.pt_ms_copy_f32(h, s, vals.ctypes.data_as(
                    ctypes.c_void_p))
            else:
                vals = np.empty(total, np.int64)
                lib.pt_ms_copy_i64(h, s, vals.ctypes.data_as(
                    ctypes.c_void_p))
            out.append((vals, splits))
        return rows, out
    finally:
        lib.pt_ms_free(h)


def _parse_multislot_py(path, slot_types):
    """Pure-Python fallback (same format; reference
    MultiSlotDataFeed::ParseOneInstance semantics)."""
    per_slot_vals = [[] for _ in slot_types]
    per_slot_splits = [[0] for _ in slot_types]
    rows = 0
    with open(path) as f:
        for line in f:
            toks = line.split()
            if not toks:
                continue
            i = 0
            for s, t in enumerate(slot_types):
                n = int(toks[i])
                i += 1
                conv = float if t == "float" else int
                per_slot_vals[s].extend(conv(x) for x in toks[i:i + n])
                i += n
                per_slot_splits[s].append(len(per_slot_vals[s]))
            rows += 1
    out = []
    for s, t in enumerate(slot_types):
        dt = np.float32 if t == "float" else np.int64
        out.append((np.asarray(per_slot_vals[s], dt),
                    np.asarray(per_slot_splits[s], np.int64)))
    return rows, out


# ---- C-ABI predictor library (inference/capi analog) ---------------------

_CAPI_SO = os.path.join(_HERE, "lib", "libpaddle_tpu_capi.so")
_CAPI_SRCS = [os.path.join(_HERE, "src", "predictor_capi.c"),
              os.path.join(_HERE, "src", "train_capi.c")]


def _python_embed_flags():
    import sysconfig
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    flags = [f"-I{inc}"]
    if libdir:
        flags += [f"-L{libdir}", f"-Wl,-rpath,{libdir}"]
    flags += [f"-lpython{ver}", "-ldl", "-lm"]
    return flags


def build_capi():
    """Compile libpaddle_tpu_capi.so (embeds CPython over the StableHLO
    Predictor — see include/paddle_tpu_capi.h). Returns the .so path."""
    os.makedirs(os.path.dirname(_CAPI_SO), exist_ok=True)
    if os.path.exists(_CAPI_SO) and all(
            os.path.getmtime(_CAPI_SO) >= os.path.getmtime(src)
            for src in _CAPI_SRCS):
        return _CAPI_SO
    tmp = _CAPI_SO + ".tmp"
    cmd = ["gcc", "-O2", "-shared", "-fPIC", *_CAPI_SRCS, "-o", tmp] \
        + _python_embed_flags()
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, _CAPI_SO)
    return _CAPI_SO


def capi_header():
    return os.path.join(_HERE, "include", "paddle_tpu_capi.h")
