/* paddle_tpu C-ABI predictor.
 *
 * The reference ships C (inference/capi/), Go (go/paddle/predictor.go)
 * and R clients over its C++ AnalysisPredictor; this header is the
 * paddle_tpu analog over the StableHLO Predictor. Any language with a C
 * FFI (Go cgo, R .C, Rust, ...) can drive inference with it.
 *
 * Contract:
 *  - PD_NewPredictor loads a paddle_tpu.jit.save artifact by prefix
 *    ("model" -> model.stablehlo + model.pdinfer.json). cipher_key_hex
 *    may be "" or NULL; pass the AES key hex for .enc artifacts.
 *  - Inputs are caller-owned buffers described by dtype/shape
 *    (PD_DTYPE_*); they are only read during PD_PredictorRun.
 *  - Outputs are library-owned f32 buffers, valid until the next
 *    PD_PredictorRun or PD_DeletePredictor on the same handle.
 *  - All functions return 0 on success (pointers: non-NULL); on failure
 *    PD_GetLastError() describes the problem.
 *  - The library embeds a Python runtime; the first PD_NewPredictor
 *    initializes it (set PYTHONPATH so paddle_tpu is importable).
 */
#ifndef PADDLE_TPU_CAPI_H_
#define PADDLE_TPU_CAPI_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Predictor PD_Predictor;

enum {
  PD_DTYPE_FLOAT32 = 0,
  PD_DTYPE_INT32 = 1,
  PD_DTYPE_INT64 = 2,
};

PD_Predictor* PD_NewPredictor(const char* model_prefix,
                              const char* cipher_key_hex);
void PD_DeletePredictor(PD_Predictor* predictor);

/* Run inference: n_in caller buffers, each with dtype code, rank
 * in_ndims[i] and dims in_shapes[i][0..ndim). Returns 0 on success. */
int PD_PredictorRun(PD_Predictor* predictor, const void* const* in_bufs,
                    const int* in_dtypes, const int64_t* const* in_shapes,
                    const int* in_ndims, int n_in);

int PD_PredictorNumOutputs(PD_Predictor* predictor);
/* Borrowed pointers into library-owned storage for output i. */
int PD_PredictorOutput(PD_Predictor* predictor, int i, const float** data,
                       const int64_t** shape, int* ndim);

const char* PD_GetLastError(void);

/* ---- C train API (reference train/demo C++ training; N33) -------------
 * A trainer loads an artifact written by
 * paddle_tpu.static.capi_train.save_train_program (full training Program
 * + parameter snapshot) and steps it with caller-fed batches. */

typedef struct PD_Trainer PD_Trainer;

PD_Trainer* PD_NewTrainer(const char* artifact_path);
void PD_DeleteTrainer(PD_Trainer* trainer);
/* Feeds follow the program's data-var order; *loss receives the step's
 * loss mean. Returns 0 on success. */
int PD_TrainerRunStep(PD_Trainer* trainer, const void* const* in_bufs,
                      const int* in_dtypes,
                      const int64_t* const* in_shapes, const int* in_ndims,
                      int n_in, float* loss);
int PD_TrainerSave(PD_Trainer* trainer, const char* params_path);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_CAPI_H_ */
