/* C-ABI predictor over the paddle_tpu StableHLO Predictor.
 *
 * Reference tier being replaced: paddle/fluid/inference/capi/
 * (pd_predictor.cc C wrappers over AnalysisPredictor). Here the native
 * library embeds CPython and drives
 * paddle_tpu.inference.capi_bridge — the compute still runs through
 * XLA, so this is a thin marshalling layer, not a reimplementation.
 * Pure C, no pybind (not in the image); built by
 * paddle_tpu._native.capi_lib() with python3-config --embed flags.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "../include/paddle_tpu_capi.h"

static char pd_err[4096];

struct PD_Predictor {
  PyObject* pred;           /* paddle_tpu Predictor */
  PyObject* last_outputs;   /* list of (bytes, shape) from the bridge */
  int n_out;
  int64_t* shapes;          /* flattened shape storage */
  int64_t** shape_ptrs;
  int* ndims;
};

const char* PD_GetLastError(void) { return pd_err; }

void pd_capi_set_err(const char* msg) {
  snprintf(pd_err, sizeof pd_err, "%s", msg);
}

void pd_capi_set_err_from_py(void) {
  PyObject *t = NULL, *v = NULL, *tb = NULL;
  PyErr_Fetch(&t, &v, &tb);
  PyObject* s = v ? PyObject_Str(v) : NULL;
  const char* c = s ? PyUnicode_AsUTF8(s) : NULL;
  pd_capi_set_err(c ? c : "unknown python error");
  Py_XDECREF(s);
  Py_XDECREF(t);
  Py_XDECREF(v);
  Py_XDECREF(tb);
}

int pd_capi_ensure_python(void) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    /* release the GIL acquired by initialization so PyGILState_Ensure
     * works from any caller thread */
    PyEval_SaveThread();
  }
  return 0;
}

PD_Predictor* PD_NewPredictor(const char* model_prefix,
                              const char* cipher_key_hex) {
  pd_capi_ensure_python();
  PyGILState_STATE g = PyGILState_Ensure();
  PD_Predictor* h = NULL;
  PyObject *mod = NULL, *pred = NULL;
  mod = PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
  if (!mod) {
    pd_capi_set_err_from_py();
    goto done;
  }
  pred = PyObject_CallMethod(mod, "create", "ss", model_prefix,
                             cipher_key_hex ? cipher_key_hex : "");
  if (!pred) {
    pd_capi_set_err_from_py();
    goto done;
  }
  h = (PD_Predictor*)calloc(1, sizeof(PD_Predictor));
  h->pred = pred;
  pred = NULL;
done:
  Py_XDECREF(mod);
  Py_XDECREF(pred);
  PyGILState_Release(g);
  return h;
}

static void pd_clear_outputs(PD_Predictor* h) {
  Py_XDECREF(h->last_outputs);
  h->last_outputs = NULL;
  free(h->shapes);
  free(h->shape_ptrs);
  free(h->ndims);
  h->shapes = NULL;
  h->shape_ptrs = NULL;
  h->ndims = NULL;
  h->n_out = 0;
}

void PD_DeletePredictor(PD_Predictor* h) {
  if (!h) return;
  PyGILState_STATE g = PyGILState_Ensure();
  pd_clear_outputs(h);
  Py_XDECREF(h->pred);
  PyGILState_Release(g);
  free(h);
}

static Py_ssize_t pd_dtype_size(int code) {
  switch (code) {
    case PD_DTYPE_FLOAT32:
    case PD_DTYPE_INT32:
      return 4;
    case PD_DTYPE_INT64:
      return 8;
  }
  return 0;
}

int PD_PredictorRun(PD_Predictor* h, const void* const* in_bufs,
                    const int* in_dtypes, const int64_t* const* in_shapes,
                    const int* in_ndims, int n_in) {
  if (!h || !h->pred) {
    pd_capi_set_err("null predictor");
    return 1;
  }
  PyGILState_STATE g = PyGILState_Ensure();
  int rc = 1;
  PyObject *mod = NULL, *inputs = NULL, *outs = NULL;
  pd_clear_outputs(h);
  inputs = PyList_New(n_in);
  for (int i = 0; i < n_in; i++) {
    Py_ssize_t numel = 1;
    PyObject* shape = PyTuple_New(in_ndims[i]);
    for (int d = 0; d < in_ndims[i]; d++) {
      numel *= (Py_ssize_t)in_shapes[i][d];
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(in_shapes[i][d]));
    }
    Py_ssize_t itemsize = pd_dtype_size(in_dtypes[i]);
    if (itemsize == 0) {
      Py_DECREF(shape);
      pd_capi_set_err("bad input dtype code");
      goto done;
    }
    PyObject* mv = PyMemoryView_FromMemory((char*)in_bufs[i],
                                           numel * itemsize, PyBUF_READ);
    PyObject* item = PyTuple_Pack(3, mv, PyLong_FromLong(in_dtypes[i]),
                                  shape);
    Py_DECREF(mv);
    Py_DECREF(shape);
    PyList_SET_ITEM(inputs, i, item);
  }
  mod = PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
  if (!mod) {
    pd_capi_set_err_from_py();
    goto done;
  }
  outs = PyObject_CallMethod(mod, "run", "OO", h->pred, inputs);
  if (!outs) {
    pd_capi_set_err_from_py();
    goto done;
  }
  h->n_out = (int)PyList_Size(outs);
  h->last_outputs = outs;
  outs = NULL;
  /* pre-extract shape tables */
  Py_ssize_t total_dims = 0;
  for (int i = 0; i < h->n_out; i++) {
    PyObject* shp = PyTuple_GetItem(PyList_GetItem(h->last_outputs, i), 1);
    total_dims += PyTuple_Size(shp);
  }
  h->shapes = (int64_t*)malloc(sizeof(int64_t) * (size_t)(total_dims + 1));
  h->shape_ptrs = (int64_t**)malloc(sizeof(int64_t*) * (size_t)h->n_out);
  h->ndims = (int*)malloc(sizeof(int) * (size_t)h->n_out);
  Py_ssize_t off = 0;
  for (int i = 0; i < h->n_out; i++) {
    PyObject* shp = PyTuple_GetItem(PyList_GetItem(h->last_outputs, i), 1);
    Py_ssize_t nd = PyTuple_Size(shp);
    h->shape_ptrs[i] = h->shapes + off;
    h->ndims[i] = (int)nd;
    for (Py_ssize_t d = 0; d < nd; d++) {
      h->shapes[off++] =
          (int64_t)PyLong_AsLongLong(PyTuple_GetItem(shp, d));
    }
  }
  rc = 0;
done:
  Py_XDECREF(mod);
  Py_XDECREF(inputs);
  Py_XDECREF(outs);
  PyGILState_Release(g);
  return rc;
}

int PD_PredictorNumOutputs(PD_Predictor* h) {
  return h ? h->n_out : -1;
}

int PD_PredictorOutput(PD_Predictor* h, int i, const float** data,
                       const int64_t** shape, int* ndim) {
  if (!h || !h->last_outputs || i < 0 || i >= h->n_out) {
    pd_capi_set_err("no such output (run first?)");
    return 1;
  }
  PyGILState_STATE g = PyGILState_Ensure();
  PyObject* bytes = PyTuple_GetItem(PyList_GetItem(h->last_outputs, i), 0);
  *data = (const float*)PyBytes_AsString(bytes);
  *shape = h->shape_ptrs[i];
  *ndim = h->ndims[i];
  PyGILState_Release(g);
  return 0;
}
