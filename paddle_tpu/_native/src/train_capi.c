/* C-ABI trainer over the static Executor (reference train/demo/
 * demo_trainer.cc + fluid_train C++ API, N33): load a saved training
 * Program, step it with caller-fed batches, persist parameters — from
 * any C host, no Python authoring at train time. Same embed pattern as
 * predictor_capi.c; both objects link into libpaddle_tpu_capi.so.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include "../include/paddle_tpu_capi.h"

/* shared with predictor_capi.c */
extern const char* PD_GetLastError(void);
void pd_capi_set_err(const char* msg);
void pd_capi_set_err_from_py(void);
int pd_capi_ensure_python(void);

typedef struct PD_Trainer {
  PyObject* handle;
} PD_Trainer;

PD_Trainer* PD_NewTrainer(const char* artifact_path) {
  pd_capi_ensure_python();
  PyGILState_STATE g = PyGILState_Ensure();
  PD_Trainer* t = NULL;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.static.capi_train");
  if (!mod) {
    pd_capi_set_err_from_py();
    goto done;
  }
  PyObject* h = PyObject_CallMethod(mod, "create", "s", artifact_path);
  if (!h) {
    pd_capi_set_err_from_py();
    Py_DECREF(mod);
    goto done;
  }
  t = (PD_Trainer*)calloc(1, sizeof(PD_Trainer));
  t->handle = h;
  Py_DECREF(mod);
done:
  PyGILState_Release(g);
  return t;
}

void PD_DeleteTrainer(PD_Trainer* t) {
  if (!t) return;
  PyGILState_STATE g = PyGILState_Ensure();
  Py_XDECREF(t->handle);
  PyGILState_Release(g);
  free(t);
}

/* One training step: feeds in the program's feed-name order. The loss
 * (first backward target) mean is written to *loss. Returns 0 on ok. */
int PD_TrainerRunStep(PD_Trainer* t, const void* const* in_bufs,
                      const int* in_dtypes,
                      const int64_t* const* in_shapes, const int* in_ndims,
                      int n_in, float* loss) {
  if (!t || !t->handle) {
    pd_capi_set_err("null trainer");
    return 1;
  }
  PyGILState_STATE g = PyGILState_Ensure();
  int rc = 1;
  PyObject *mod = NULL, *inputs = NULL, *res = NULL;
  inputs = PyList_New(n_in);
  for (int i = 0; i < n_in; i++) {
    Py_ssize_t numel = 1;
    PyObject* shape = PyTuple_New(in_ndims[i]);
    for (int d = 0; d < in_ndims[i]; d++) {
      numel *= (Py_ssize_t)in_shapes[i][d];
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(in_shapes[i][d]));
    }
    Py_ssize_t itemsize = in_dtypes[i] == PD_DTYPE_INT64 ? 8 : 4;
    PyObject* mv = PyMemoryView_FromMemory((char*)in_bufs[i],
                                           numel * itemsize, PyBUF_READ);
    PyObject* item = PyTuple_Pack(3, mv, PyLong_FromLong(in_dtypes[i]),
                                  shape);
    Py_DECREF(mv);
    Py_DECREF(shape);
    PyList_SET_ITEM(inputs, i, item);
  }
  mod = PyImport_ImportModule("paddle_tpu.static.capi_train");
  if (!mod) {
    pd_capi_set_err_from_py();
    goto done;
  }
  res = PyObject_CallMethod(mod, "run_step", "OO", t->handle, inputs);
  if (!res) {
    pd_capi_set_err_from_py();
    goto done;
  }
  *loss = (float)PyFloat_AsDouble(res);
  rc = 0;
done:
  Py_XDECREF(mod);
  Py_XDECREF(inputs);
  Py_XDECREF(res);
  PyGILState_Release(g);
  return rc;
}

int PD_TrainerSave(PD_Trainer* t, const char* path) {
  if (!t || !t->handle) {
    pd_capi_set_err("null trainer");
    return 1;
  }
  PyGILState_STATE g = PyGILState_Ensure();
  int rc = 1;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.static.capi_train");
  if (mod) {
    PyObject* res =
        PyObject_CallMethod(mod, "save_params", "Os", t->handle, path);
    if (res) {
      rc = 0;
      Py_DECREF(res);
    } else {
      pd_capi_set_err_from_py();
    }
    Py_DECREF(mod);
  } else {
    pd_capi_set_err_from_py();
  }
  PyGILState_Release(g);
  return rc;
}
