// MultiSlot text parser — the native data-feed hot path.
//
// TPU-native analog of the reference's C++ DataFeed tier (reference
// paddle/fluid/framework/data_feed.cc MultiSlotDataFeed::ParseOneInstance,
// data_feed.h:663): training text where each line holds, per declared
// slot, a count followed by that many values (uint64 ids for sparse
// slots, floats for dense slots):
//
//   <n0> v v v <n1> v v <n2> v ...
//
// The reference parses this in DeviceWorker threads because Python-side
// parsing can't feed GPUs; the same holds for TPU input pipelines, so the
// parse happens here in C++ (called via ctypes — the call releases the
// GIL, so Python-level thread pools get real parallelism across files).
// Output is the packed ragged form (values + row_splits) that
// paddle_tpu.core.ragged consumes directly.
//
// Build: g++ -O3 -shared -fPIC (driven by paddle_tpu/_native/__init__.py).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct SlotBuf {
  bool is_float = false;
  std::vector<int64_t> ids;
  std::vector<float> floats;
  std::vector<int64_t> splits;  // rows + 1 offsets
};

struct ParseResult {
  std::vector<SlotBuf> slots;
  int64_t rows = 0;
  std::string error;
};

// strtod/strtoull-based scanner; one pass, no allocations per token.
bool parse_buffer(const char* data, size_t len,
                  const std::vector<bool>& slot_is_float, ParseResult* out) {
  const int n_slots = static_cast<int>(slot_is_float.size());
  out->slots.resize(n_slots);
  for (int s = 0; s < n_slots; ++s) {
    out->slots[s].is_float = slot_is_float[s];
    out->slots[s].splits.push_back(0);
  }
  const char* p = data;
  const char* end = data + len;
  while (p < end) {
    // skip blank lines
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
    if (p >= end) break;
    for (int s = 0; s < n_slots; ++s) {
      char* next = nullptr;
      long long n = strtoll(p, &next, 10);
      if (next == p || n < 0) {
        out->error = "bad slot count at row " + std::to_string(out->rows) +
                     " slot " + std::to_string(s);
        return false;
      }
      p = next;
      SlotBuf& sb = out->slots[s];
      for (long long i = 0; i < n; ++i) {
        if (sb.is_float) {
          float v = strtof(p, &next);
          if (next == p) {
            out->error = "bad float at row " + std::to_string(out->rows);
            return false;
          }
          sb.floats.push_back(v);
        } else {
          long long v = strtoll(p, &next, 10);
          if (next == p) {
            out->error = "bad id at row " + std::to_string(out->rows);
            return false;
          }
          sb.ids.push_back(static_cast<int64_t>(v));
        }
        p = next;
      }
      sb.splits.push_back(sb.is_float
                              ? static_cast<int64_t>(sb.floats.size())
                              : static_cast<int64_t>(sb.ids.size()));
    }
    out->rows += 1;
    while (p < end && *p != '\n') ++p;  // to end of line
  }
  return true;
}

}  // namespace

extern "C" {

// slot_types: comma-separated "uint64"/"float". Returns handle or null.
void* pt_parse_multislot_file(const char* path, const char* slot_types) {
  std::vector<bool> is_float;
  {
    std::string t(slot_types);
    size_t start = 0;
    while (start <= t.size()) {
      size_t comma = t.find(',', start);
      std::string tok = t.substr(
          start, comma == std::string::npos ? std::string::npos
                                            : comma - start);
      if (!tok.empty()) is_float.push_back(tok == "float");
      if (comma == std::string::npos) break;
      start = comma + 1;
    }
  }
  if (is_float.empty()) return nullptr;

  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(size), '\0');
  size_t got = fread(&buf[0], 1, static_cast<size_t>(size), f);
  fclose(f);

  auto* res = new ParseResult();
  if (!parse_buffer(buf.data(), got, is_float, res)) {
    // keep handle so the error is readable; rows stays partial
  }
  return res;
}

long long pt_ms_rows(void* h) {
  return static_cast<ParseResult*>(h)->rows;
}

const char* pt_ms_error(void* h) {
  return static_cast<ParseResult*>(h)->error.c_str();
}

long long pt_ms_slot_total(void* h, int slot) {
  SlotBuf& sb = static_cast<ParseResult*>(h)->slots[slot];
  return sb.is_float ? static_cast<long long>(sb.floats.size())
                     : static_cast<long long>(sb.ids.size());
}

void pt_ms_copy_splits(void* h, int slot, int64_t* out) {
  SlotBuf& sb = static_cast<ParseResult*>(h)->slots[slot];
  memcpy(out, sb.splits.data(), sb.splits.size() * sizeof(int64_t));
}

void pt_ms_copy_f32(void* h, int slot, float* out) {
  SlotBuf& sb = static_cast<ParseResult*>(h)->slots[slot];
  memcpy(out, sb.floats.data(), sb.floats.size() * sizeof(float));
}

void pt_ms_copy_i64(void* h, int slot, int64_t* out) {
  SlotBuf& sb = static_cast<ParseResult*>(h)->slots[slot];
  memcpy(out, sb.ids.data(), sb.ids.size() * sizeof(int64_t));
}

void pt_ms_free(void* h) { delete static_cast<ParseResult*>(h); }

}  // extern "C"
