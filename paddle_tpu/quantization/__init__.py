"""Quantization: QAT (fake-quant + straight-through) and PTQ calibration.

Analog of the reference's slim/quant stack (reference
python/paddle/fluid/contrib/slim/quantization/quantization_pass.py —
QuantizationTransformPass inserting fake_quantize/dequantize ops,
operators/fake_quantize_op.cc FakeQuantizeAbsMax/MovingAverageAbsMax, and
the ImperativeQuantAware dygraph wrapper). The 2.x API shape
(paddle.quantization QuantConfig/QAT/PTQ) is kept.

TPU-native design delta: the reference rewrites the Program, pairing each
quantized op with fake-quant ops; here quantization is a LAYER transform —
QuantedLinear/QuantedConv2D wrap the originals, applying fake-quant
(jax.custom_vjp straight-through estimator) to weights (per-channel
absmax) and activations (moving-average absmax observer) — and the whole
thing stays jittable, so QAT trains at full MXU speed.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer.layers import Layer
from ..ops._dispatch import defop, unwrap, wrap

__all__ = ["fake_quant", "QuantConfig", "QAT", "PTQ", "AbsmaxObserver",
           "HistogramObserver", "QuantedLinear", "QuantedConv2D",
           "weight_quantize"]


# -- fake quant with straight-through estimator -----------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fq(x, scale, bits):
    qmax = 2 ** (bits - 1) - 1
    s = jnp.maximum(scale, 1e-8)
    q = jnp.round(jnp.clip(x / s, -1.0, 1.0) * qmax)
    return q / qmax * s


def _fq_fwd(x, scale, bits):
    return _fq(x, scale, bits), (x, scale)


def _fq_bwd(bits, res, g):
    x, scale = res
    s = jnp.maximum(scale, 1e-8)
    inside = (jnp.abs(x) <= s).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale)  # STE; scale is calibration data


_fq.defvjp(_fq_fwd, _fq_bwd)


@defop
def fake_quant(x, scale, bits=8):
    """Quantize-dequantize with straight-through gradients (reference
    fake_quantize_dequantize ops, fake_quantize_op.cc)."""
    return _fq(x, jnp.asarray(scale, x.dtype), bits)


def _per_channel_scale(w, axis):
    red = tuple(i for i in range(w.ndim) if i != axis)
    return jnp.max(jnp.abs(w), axis=red, keepdims=True)


# -- observers --------------------------------------------------------------

class AbsmaxObserver(Layer):
    """Moving-average absmax activation observer (reference
    FakeQuantMovingAverageAbsMax, fake_quantize_op.cc)."""

    def __init__(self, momentum=0.9, bits=8):
        super().__init__()
        self._momentum = momentum
        self.bits = bits
        self.register_buffer("scale", wrap(jnp.ones((), jnp.float32)))
        self._calibrating = True

    def observe(self, x):
        cur = jnp.max(jnp.abs(unwrap(x))).astype(jnp.float32)
        old = unwrap(self.scale)
        new = jnp.where(old == 1.0, cur,
                        self._momentum * old + (1 - self._momentum) * cur)
        self.scale.set_value(np.asarray(jax.lax.stop_gradient(new)))

    def forward(self, x):
        if self.training or self._calibrating:
            self.observe(x)
        return fake_quant(x, unwrap(self.scale), bits=self.bits)


class HistogramObserver(Layer):
    """Percentile calibration over an accumulated |x| histogram (reference
    mkldnn_quantizer.cc KL/hist modes, slim PTQ 'hist' algo): outliers do
    not blow up the scale the way absmax lets them. The histogram range
    doubles on demand; the final scale is the `percentile` quantile of
    observed magnitudes."""

    def __init__(self, bins=2048, percentile=0.9999, bits=8):
        super().__init__()
        self.bits = bits
        if int(bins) < 2 or int(bins) % 2:
            raise ValueError(
                f"bins must be even and >= 2 (got {bins}): the histogram "
                "range grows by pair-merging bins")
        self._bins = int(bins)
        self._percentile = float(percentile)
        self._hist = np.zeros(self._bins, np.float64)
        self._hi = None  # current histogram upper bound
        self.register_buffer("scale", wrap(jnp.ones((), jnp.float32)))
        self._calibrating = True

    def observe(self, x):
        a = np.abs(np.asarray(unwrap(x), np.float32)).reshape(-1)
        amax = float(a.max()) if a.size else 0.0
        if amax == 0.0:
            return
        if self._hi is None:
            self._hi = amax
        while amax > self._hi:  # grow by doubling, pair-merging old bins
            merged = self._hist.reshape(-1, 2).sum(1)
            self._hist = np.concatenate(
                [merged, np.zeros(self._bins - merged.size, np.float64)])
            self._hi *= 2.0
        h, _ = np.histogram(a, bins=self._bins, range=(0.0, self._hi))
        self._hist += h
        total = self._hist.sum()
        cdf = np.cumsum(self._hist) / total
        idx = int(np.searchsorted(cdf, self._percentile))
        new_scale = (idx + 1) / self._bins * self._hi
        self.scale.set_value(np.asarray(new_scale, np.float32))

    def forward(self, x):
        if self.training or self._calibrating:
            if isinstance(unwrap(x), jax.core.Tracer):
                # histogram accumulation is host-side numpy; it cannot run
                # inside a traced step — calibrate eagerly (PTQ.calibrate)
                # or use absmax for QAT-under-jit
                if not HistogramObserver._warned_traced:
                    HistogramObserver._warned_traced = True
                    import warnings
                    warnings.warn(
                        "HistogramObserver saw a traced input: statistics "
                        "are NOT being collected inside jit. Calibrate "
                        "eagerly (PTQ.calibrate) or use "
                        "act_observer='absmax' for jitted QAT.")
            else:
                self.observe(x)
        return fake_quant(x, unwrap(self.scale), bits=self.bits)


HistogramObserver._warned_traced = False


# -- quantized layer wrappers ----------------------------------------------

def _make_observer(kind, bits):
    if kind == "histogram":
        return HistogramObserver(bits=bits)
    if kind == "absmax":
        return AbsmaxObserver(bits=bits)
    raise ValueError(
        f"unknown act_observer {kind!r}; use 'absmax' or 'histogram'")


class QuantedLinear(Layer):
    """Linear with fake-quant on weight (per-out-channel absmax) and
    input activation (observer)."""

    def __init__(self, inner, weight_bits=8, act_bits=8,
                 act_observer="absmax"):
        super().__init__()
        self.inner = inner
        self.weight_bits = weight_bits
        self.act_quanter = _make_observer(act_observer, act_bits)

    def forward(self, x):
        from ..nn import functional as F
        x = self.act_quanter(x)
        w = self.inner.weight
        scale = _per_channel_scale(unwrap(w), axis=1)  # [1, out]
        wq = fake_quant(w, scale, bits=self.weight_bits)
        return F.linear(x, wq, self.inner.bias)


class QuantedConv2D(Layer):
    def __init__(self, inner, weight_bits=8, act_bits=8,
                 act_observer="absmax"):
        super().__init__()
        self.inner = inner
        self.weight_bits = weight_bits
        self.act_quanter = _make_observer(act_observer, act_bits)

    def forward(self, x):
        x = self.act_quanter(x)
        inner = self.inner
        w = inner.weight
        scale = _per_channel_scale(unwrap(w), axis=0)  # [out,1,1,1]
        wq = fake_quant(w, scale, bits=self.weight_bits)
        from .. import ops
        return ops.conv2d(x, wq, inner.bias, stride=inner._stride,
                          padding=inner._padding, dilation=inner._dilation,
                          groups=inner._groups)


# -- user API ---------------------------------------------------------------

class QuantConfig:
    """2.x-style config: which layer types quantize, at what widths."""

    def __init__(self, activation=None, weight=None, weight_bits=8,
                 act_bits=8, act_observer="absmax"):
        self.weight_bits = weight_bits
        self.act_bits = act_bits
        self.act_observer = act_observer  # "absmax" | "histogram"
        self.layer_map = {}
        from ..nn.layer.common import Linear
        self.layer_map[Linear] = QuantedLinear
        try:
            from ..nn.layer.conv import Conv2D
            self.layer_map[Conv2D] = QuantedConv2D
        except ImportError:
            pass

    def add_layer_mapping(self, source_type, quanted_type):
        self.layer_map[source_type] = quanted_type


def _replace_layers(root, config):
    replaced = 0
    for name, child in list(root._sub_layers.items()):
        qcls = config.layer_map.get(type(child))
        if qcls is not None:
            root._sub_layers[name] = qcls(
                child, weight_bits=config.weight_bits,
                act_bits=config.act_bits,
                act_observer=getattr(config, "act_observer", "absmax"))
            replaced += 1
        else:
            replaced += _replace_layers(child, config)
    return replaced


class QAT:
    """Quantization-aware training (reference ImperativeQuantAware)."""

    def __init__(self, config=None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=True):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        n = _replace_layers(model, self.config)
        if n == 0:
            raise ValueError("no quantizable layers found")
        return model

    def convert(self, model, inplace=True):
        """Freeze observers for deployment (scales stop updating)."""
        for _, sub in model.named_sublayers(include_self=True):
            if isinstance(sub, (AbsmaxObserver, HistogramObserver)):
                sub._calibrating = False
        model.eval()
        return model


class PTQ(QAT):
    """Post-training quantization (reference mkldnn_quantizer.cc +
    slim PTQ): wrap, run calibration batches in eval mode (observers keep
    observing), then convert. `calibrate` is the whole pass:

        q = PTQ(QuantConfig(act_observer="histogram"))
        qmodel = q.quantize(model, inplace=False)
        q.calibrate(qmodel, sample_batches)   # any iterable of inputs
        q.convert(qmodel)
        jit.save(qmodel, path, input_spec=...)  # Predictor-loadable
    """

    def quantize(self, model, inplace=True):
        model = super().quantize(model, inplace)
        model.eval()
        for _, sub in model.named_sublayers(include_self=True):
            if isinstance(sub, (AbsmaxObserver, HistogramObserver)):
                sub._calibrating = True
        return model

    def calibrate(self, model, sample_data, max_batches=None):
        """Run calibration batches through the wrapped model so observers
        accumulate activation statistics. sample_data: iterable of inputs
        (a Tensor/array per batch, or a tuple of them)."""
        from ..core.tensor import Tensor
        model.eval()
        n = 0
        for batch in sample_data:
            args = batch if isinstance(batch, (tuple, list)) else (batch,)
            args = tuple(a if isinstance(a, Tensor) else Tensor(
                jnp.asarray(np.asarray(a)), _internal=True) for a in args)
            model(*args)
            n += 1
            if max_batches is not None and n >= max_batches:
                break
        if n == 0:
            raise ValueError("calibrate needs at least one sample batch")
        return model


def weight_quantize(model, bits=8):
    """Export int8 weights + scales for quantized Linear/Conv layers
    (reference WeightQuantization, slim/quantization/quantize.py)."""
    out = {}
    qmax = 2 ** (bits - 1) - 1
    for name, sub in model.named_sublayers():
        if isinstance(sub, (QuantedLinear, QuantedConv2D)):
            w = unwrap(sub.inner.weight)
            axis = 1 if isinstance(sub, QuantedLinear) else 0
            scale = _per_channel_scale(w, axis)
            q = np.asarray(jnp.round(jnp.clip(w / jnp.maximum(scale, 1e-8),
                                              -1, 1) * qmax), np.int8)
            out[name] = {"int8": q, "scale": np.asarray(scale),
                         "bits": bits}
    return out
