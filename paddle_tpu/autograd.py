"""paddle.autograd namespace.

Analog of reference python/paddle/autograd/ (backward via
imperative/basic_engine.cc, paddle.grad via partial_grad_engine.cc).
"""
from .core.tape import backward, grad, no_grad, enable_grad, is_grad_enabled, set_grad_enabled  # noqa: F401

__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer"]


class PyLayer:
    """Custom autograd op (reference python/paddle/autograd/py_layer.py).

    Subclass with static `forward(ctx, *args)` / `backward(ctx, *grads)`.
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        import jax
        from .core.tape import Node, is_grad_enabled, _wrap_outputs
        from .core.tensor import Tensor

        ctx = _PyLayerContext()
        raw = [a._value if isinstance(a, Tensor) else a for a in args]
        out_val = cls.forward(ctx, *raw, **kwargs)
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        any_diff = any(not a.stop_gradient for a in tensor_inputs)
        if not (is_grad_enabled() and any_diff):
            return _wrap_outputs(out_val, node=None, stop_gradient=True)

        multi = isinstance(out_val, (tuple, list))
        outs = list(out_val) if multi else [out_val]

        def vjp_fn(cot):
            grads = cls.backward(ctx, *(cot if multi else (cot,)))
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            if len(grads) != len(tensor_inputs):
                raise RuntimeError(
                    f"{cls.__name__}.backward returned {len(grads)} grads for "
                    f"{len(tensor_inputs)} tensor inputs")
            # engine drops entries for stop_gradient inputs, keeping alignment
            return tuple(g._value if isinstance(g, Tensor) else g for g in grads)

        node = Node(vjp_fn, tensor_inputs,
                    [(tuple(o.shape), o.dtype) for o in outs],
                    cls.__name__, multi)
        return _wrap_outputs(out_val, node=node, stop_gradient=False)

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError


class _PyLayerContext:
    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    saved_tensors = saved_tensor
