"""paddle.metric (reference python/paddle/metric/metrics.py: Metric base,
Accuracy, Precision, Recall, Auc; accuracy functional)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing run on (pred, label); default passthrough."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim:
            label = label.squeeze(-1)
        correct = idx == label[..., None]
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        accs = []
        n = correct[..., 0].size
        for i, k in enumerate(self.topk):
            c = correct[..., :k].any(-1).sum()
            self.total[i] += c
            self.count[i] += n
            accs.append(float(c) / n)
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        out = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return out[0] if len(out) == 1 else out

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype("int64").reshape(-1)
        labels = _np(labels).astype("int64").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype("int64").reshape(-1)
        labels = _np(labels).astype("int64").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Histogram AUC, matching the reference's bucketed implementation
    (operators/metrics/auc_op.cc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            scores = preds[:, 1]
        else:
            scores = preds.reshape(-1)
        buckets = np.clip((scores * self.num_thresholds).astype("int64"), 0,
                          self.num_thresholds)
        for b, l in zip(buckets, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = cum_pos = 0.0
        tot_neg = cum_neg = 0.0
        area = 0.0
        for i in range(self.num_thresholds, -1, -1):
            pos = self._stat_pos[i]
            neg = self._stat_neg[i]
            area += neg * (cum_pos + pos / 2.0)
            cum_pos += pos
            cum_neg += neg
        tot_pos, tot_neg = cum_pos, cum_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1):  # noqa: A002
    """Functional batch accuracy (reference metric/metrics.py accuracy)."""
    pred = _np(input)
    lab = _np(label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lab.ndim == pred.ndim:
        lab = lab.squeeze(-1)
    correct = (idx == lab[..., None]).any(-1)
    from ..ops._dispatch import wrap
    import jax.numpy as jnp
    return wrap(jnp.asarray(correct.mean(), jnp.float32))


class ChunkEvaluator(Metric):
    """Chunk-level precision/recall/F1 for sequence labeling (reference
    fluid/metrics.py ChunkEvaluator over chunk_eval_op.cc; IOB scheme via
    ops.chunk_eval)."""

    def __init__(self, num_chunk_types=1, chunk_scheme="IOB", name=None):
        self._name = name or "chunk"
        self.num_chunk_types = num_chunk_types
        self.chunk_scheme = chunk_scheme
        self.reset()

    def reset(self):
        self.num_infer = 0
        self.num_label = 0
        self.num_correct = 0

    def update(self, inferences, labels, seq_lengths=None):
        from ..ops import chunk_eval
        _, _, _, ni, nl, nc = chunk_eval(
            inferences, labels, chunk_scheme=self.chunk_scheme,
            num_chunk_types=self.num_chunk_types, seq_lengths=seq_lengths)
        self.num_infer += ni
        self.num_label += nl
        self.num_correct += nc

    def accumulate(self):
        p = self.num_correct / self.num_infer if self.num_infer else 0.0
        r = self.num_correct / self.num_label if self.num_label else 0.0
        f1 = 2 * p * r / (p + r) if (p + r) else 0.0
        return p, r, f1

    def name(self):
        return self._name


class EditDistance(Metric):
    """Streaming average edit distance (reference fluid/metrics.py
    EditDistance over edit_distance_op.cc)."""

    def __init__(self, normalized=True, name=None):
        self._name = name or "edit_distance"
        self.normalized = normalized
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0

    def update(self, hyps, refs):
        import numpy as np

        from ..ops import edit_distance
        d, n = edit_distance(hyps, refs, normalized=self.normalized)
        self.total += float(np.asarray(d.numpy()).sum())
        self.count += n

    def accumulate(self):
        return self.total / self.count if self.count else 0.0

    def name(self):
        return self._name


__all__ += ["ChunkEvaluator", "EditDistance"]
