"""paddle.metric (reference python/paddle/metric/metrics.py: Metric base,
Accuracy, Precision, Recall, Auc; accuracy functional)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing run on (pred, label); default passthrough."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim:
            label = label.squeeze(-1)
        correct = idx == label[..., None]
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        accs = []
        n = correct[..., 0].size
        for i, k in enumerate(self.topk):
            c = correct[..., :k].any(-1).sum()
            self.total[i] += c
            self.count[i] += n
            accs.append(float(c) / n)
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        out = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return out[0] if len(out) == 1 else out

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype("int64").reshape(-1)
        labels = _np(labels).astype("int64").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype("int64").reshape(-1)
        labels = _np(labels).astype("int64").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Histogram AUC, matching the reference's bucketed implementation
    (operators/metrics/auc_op.cc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            scores = preds[:, 1]
        else:
            scores = preds.reshape(-1)
        buckets = np.clip((scores * self.num_thresholds).astype("int64"), 0,
                          self.num_thresholds)
        for b, l in zip(buckets, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = cum_pos = 0.0
        tot_neg = cum_neg = 0.0
        area = 0.0
        for i in range(self.num_thresholds, -1, -1):
            pos = self._stat_pos[i]
            neg = self._stat_neg[i]
            area += neg * (cum_pos + pos / 2.0)
            cum_pos += pos
            cum_neg += neg
        tot_pos, tot_neg = cum_pos, cum_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1):  # noqa: A002
    """Functional batch accuracy (reference metric/metrics.py accuracy)."""
    pred = _np(input)
    lab = _np(label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lab.ndim == pred.ndim:
        lab = lab.squeeze(-1)
    correct = (idx == lab[..., None]).any(-1)
    from ..ops._dispatch import wrap
    import jax.numpy as jnp
    return wrap(jnp.asarray(correct.mean(), jnp.float32))


class ChunkEvaluator(Metric):
    """Chunk-level precision/recall/F1 for sequence labeling (reference
    fluid/metrics.py ChunkEvaluator over chunk_eval_op.cc; IOB scheme via
    ops.chunk_eval)."""

    def __init__(self, num_chunk_types=1, chunk_scheme="IOB", name=None):
        self._name = name or "chunk"
        self.num_chunk_types = num_chunk_types
        self.chunk_scheme = chunk_scheme
        self.reset()

    def reset(self):
        self.num_infer = 0
        self.num_label = 0
        self.num_correct = 0

    def update(self, inferences, labels, seq_lengths=None):
        from ..ops import chunk_eval
        _, _, _, ni, nl, nc = chunk_eval(
            inferences, labels, chunk_scheme=self.chunk_scheme,
            num_chunk_types=self.num_chunk_types, seq_lengths=seq_lengths)
        self.num_infer += ni
        self.num_label += nl
        self.num_correct += nc

    def accumulate(self):
        p = self.num_correct / self.num_infer if self.num_infer else 0.0
        r = self.num_correct / self.num_label if self.num_label else 0.0
        f1 = 2 * p * r / (p + r) if (p + r) else 0.0
        return p, r, f1

    def name(self):
        return self._name


class EditDistance(Metric):
    """Streaming average edit distance (reference fluid/metrics.py
    EditDistance over edit_distance_op.cc)."""

    def __init__(self, normalized=True, name=None):
        self._name = name or "edit_distance"
        self.normalized = normalized
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0

    def update(self, hyps, refs):
        import numpy as np

        from ..ops import edit_distance
        d, n = edit_distance(hyps, refs, normalized=self.normalized)
        self.total += float(np.asarray(d.numpy()).sum())
        self.count += n

    def accumulate(self):
        return self.total / self.count if self.count else 0.0

    def name(self):
        return self._name


__all__ += ["ChunkEvaluator", "EditDistance"]


__all__ += ["DetectionMAP"]


class DetectionMAP:
    """Mean average precision for detection (reference
    detection/detection_map_op.cc + fluid/metrics.py DetectionMAP).
    Host-side accumulator in the TPU design: detections come off-device
    per batch, AP math is numpy.

    update(detections, gt_boxes, gt_labels, difficult=None):
      detections [M, 6] rows (label, score, x1, y1, x2, y2) for ONE image;
      gt_boxes [G, 4]; gt_labels [G]. Call per image.
    """

    def __init__(self, overlap_threshold=0.5, ap_version="integral",
                 evaluate_difficult=False, class_num=None):
        import collections as _c
        self.overlap_threshold = float(overlap_threshold)
        self.ap_version = ap_version
        self.evaluate_difficult = evaluate_difficult
        self._dets = _c.defaultdict(list)   # cls -> [(score, img, box)]
        self._gts = _c.defaultdict(list)    # (img, cls) -> [box, ...]
        self._npos = _c.defaultdict(int)
        self._img = 0

    def reset(self):
        self._dets.clear()
        self._gts.clear()
        self._npos.clear()
        self._img = 0

    @staticmethod
    def _np(v):
        import numpy as _np
        return _np.asarray(v.numpy() if hasattr(v, "numpy") else v)

    def update(self, detections, gt_boxes, gt_labels, difficult=None):
        import numpy as _np
        det = self._np(detections).reshape(-1, 6)
        gb = self._np(gt_boxes).reshape(-1, 4)
        gl = self._np(gt_labels).reshape(-1).astype(int)
        dif = (self._np(difficult).reshape(-1).astype(bool)
               if difficult is not None else _np.zeros(len(gl), bool))
        img = self._img
        self._img += 1
        for box, lab, d in zip(gb, gl, dif):
            self._gts[(img, int(lab))].append((box, bool(d)))
            if self.evaluate_difficult or not d:
                self._npos[int(lab)] += 1
        for row in det:
            self._dets[int(row[0])].append((float(row[1]), img, row[2:6]))

    @staticmethod
    def _iou(a, b):
        import numpy as _np
        x1 = _np.maximum(a[0], b[:, 0])
        y1 = _np.maximum(a[1], b[:, 1])
        x2 = _np.minimum(a[2], b[:, 2])
        y2 = _np.minimum(a[3], b[:, 3])
        inter = _np.maximum(x2 - x1, 0) * _np.maximum(y2 - y1, 0)
        area_a = max((a[2] - a[0]) * (a[3] - a[1]), 0)
        area_b = _np.maximum(b[:, 2] - b[:, 0], 0) * \
            _np.maximum(b[:, 3] - b[:, 1], 0)
        return inter / _np.maximum(area_a + area_b - inter, 1e-10)

    def accumulate(self):
        import numpy as _np
        aps = []
        for cls, dets in self._dets.items():
            npos = self._npos.get(cls, 0)
            if npos == 0:
                continue
            dets = sorted(dets, key=lambda t: -t[0])
            matched = {}
            tp = _np.zeros(len(dets))
            fp = _np.zeros(len(dets))
            for i, (_score, img, box) in enumerate(dets):
                entries = self._gts.get((img, cls), [])
                if not entries:
                    fp[i] = 1
                    continue
                boxes = _np.stack([e[0] for e in entries])
                ious = self._iou(box, boxes)
                j = int(ious.argmax())
                if ious[j] >= self.overlap_threshold:
                    difficult = entries[j][1]
                    if difficult and not self.evaluate_difficult:
                        continue  # neither tp nor fp
                    if (img, cls, j) not in matched:
                        matched[(img, cls, j)] = True
                        tp[i] = 1
                    else:
                        fp[i] = 1
                else:
                    fp[i] = 1
            ctp, cfp = _np.cumsum(tp), _np.cumsum(fp)
            rec = ctp / npos
            prec = ctp / _np.maximum(ctp + cfp, 1e-10)
            if self.ap_version == "11point":
                ap = 0.0
                for t in _np.arange(0.0, 1.1, 0.1):
                    p = prec[rec >= t].max() if (rec >= t).any() else 0.0
                    ap += p / 11.0
            else:  # integral
                ap = 0.0
                mrec = _np.concatenate([[0.0], rec, [1.0]])
                mpre = _np.concatenate([[0.0], prec, [0.0]])
                for i in range(len(mpre) - 2, -1, -1):
                    mpre[i] = max(mpre[i], mpre[i + 1])
                idx = _np.where(mrec[1:] != mrec[:-1])[0]
                ap = float(((mrec[idx + 1] - mrec[idx]) *
                            mpre[idx + 1]).sum())
            aps.append(ap)
        return float(sum(aps) / len(aps)) if aps else 0.0
