"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up re-design of the reference PaddlePaddle (~v2.0-rc) for TPU:
the user API keeps the reference's shape (`paddle.*` tensor functions,
`nn.Layer`, `optimizer`, `Model.fit`, `paddle.static`, `paddle.distributed`/
fleet), while the execution model is XLA-first — eager ops are jnp kernels,
training steps are traced once and compiled (jit/pjit), parallelism is mesh
sharding + compiler-inserted ICI collectives instead of NCCL rings.
See /root/repo/SURVEY.md for the layer-by-layer mapping to the reference.
"""
from __future__ import annotations

__version__ = "0.1.0"

import jax as _jax

# Paddle semantics: int64 indices/labels are first-class. Enable x64 so they
# survive; float tensors still default to float32 (core/tensor._coerce), and
# the compute path prefers bf16 on the MXU (ops/linalg.py).
_jax.config.update("jax_enable_x64", True)

from .core.dtype import (bfloat16, bool_, complex128, complex64, float16,  # noqa: F401
                         float32, float64, int16, int32, int64, int8, uint8)
from .core.dtype import bool_ as bool  # noqa: F401,A001
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.rng import seed  # noqa: F401
from .core.tensor import Tensor, to_tensor  # noqa: F401
from .core.tape import (no_grad, enable_grad, is_grad_enabled,  # noqa: F401
                        set_grad_enabled, grad)

from .ops import *  # noqa: F401,F403  — paddle.* tensor functions
from . import ops  # noqa: F401

from . import autograd  # noqa: F401
from . import device  # noqa: F401
from .device import (CPUPlace, CUDAPlace, TPUPlace, get_device,  # noqa: F401
                     set_device, is_compiled_with_cuda)

# flight recorder: arm the fatal-signal dump hook when a dump dir is
# configured (PADDLE_TPU_DUMP_DIR); a pure no-op otherwise
from .core import flight_recorder as _flight_recorder
_flight_recorder.maybe_install()


def in_dynamic_mode():
    try:
        from . import static as _static
    except ImportError:
        return True
    return not _static.in_static_mode()


def enable_static():
    from . import static as _static
    _static.enable_static_()


def disable_static():
    try:
        from . import static as _static
    except ImportError:
        return
    _static.disable_static_()


def disable_signal_handler():  # parity no-op
    pass


# Subpackages are importable lazily (paddle.nn, paddle.optimizer, ...) so the
# core stays importable while higher layers are under construction.
import importlib as _importlib

_SUBMODULES = ("nn", "optimizer", "metric", "io", "amp", "static",
               "distributed", "vision", "jit", "hapi", "incubate",
               "profiler", "text", "sysconfig", "callbacks", "inference",
               "framework", "regularizer", "memory", "quantization",
               "distribution", "version", "utils", "fluid", "reader",
               "dataset", "onnx", "tensor")


from ._legacy_api import *  # noqa: F401,F403  — v1/compat root names
from ._legacy_api import VarBase, LoDTensor, LoDTensorArray  # noqa: F401

# Lazily-injected non-module names (see __getattr__); enumerated so the
# API.spec snapshot is deterministic regardless of import order.
__all_lazy__ = ("Model", "summary", "flops", "save", "load", "batch")


def __getattr__(name):
    if name in _SUBMODULES:
        mod = _importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "Model":
        from .hapi import Model
        globals()["Model"] = Model
        return Model
    if name in ("summary", "flops"):
        from .hapi.summary import flops, summary
        globals().update(summary=summary, flops=flops)
        return globals()[name]
    if name in ("save", "load"):
        from .framework.io import load, save
        globals().update(save=save, load=load)
        return globals()[name]
    if name == "batch":
        from .reader import batch
        globals()["batch"] = batch
        return batch
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
