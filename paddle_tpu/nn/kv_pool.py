"""Paged KV-cache pool — the serving tier's shared decode cache.

`StaticKVCache` (nn/layer/transformer.py) preallocates a private
[b, h, max_seq_len, d] slab per batch row. That is the right shape for
ONE generate() call, and exactly the wrong shape for a serve loop:
requests arrive with different lengths, finish at different times, and a
fixed-batch slab burns max_seq_len slots of HBM per row whether the row
holds a 2000-token context or an idle slot. This module is the vLLM-style
fix, TPU-native:

- **arena**: one physical [n_blocks + 1, h, block_size, d] buffer per
  layer per k/v (`PagedKVCache`). Physical block 0 is RESERVED as the
  trash block — writes from masked/inactive rows and table entries past a
  request's allocation all land there, so the kernel's index maps never
  need a branch;
- **block table**: each request maps logical block j -> physical row
  `block_tables[i, j]`; unallocated entries are 0 (trash) by contract;
- **free list**: `KVBlockPool` hands physical blocks out and takes them
  back the moment a request retires — the pool is the serving tier's
  admission currency (inference/serving.py blocks admissions on it).

Attention over the paged layout dispatches to the block-table Pallas
kernel (ops/pallas/decode_attention.paged_decode_attention — lengths AND
block tables ride the scalar-prefetch path, so per-step KV bytes scale
with live blocks, not max_seq_len) behind the same gate + run_guarded
discipline as every other kernel; `paged_attention_ref` is the jnp
fallback and the parity oracle.
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PagedKVCache", "KVBlockPool", "paged_attention",
           "paged_attention_ref", "write_kv", "pick_block_size"]

TRASH_BLOCK = 0  # physical row 0 of every arena; never allocated


class PagedKVCache(typing.NamedTuple):
    """One layer's paged decode cache. `k`/`v` are the physical arenas
    [n_blocks + 1, h, block_size, d] (row 0 = trash); `block_tables`
    [b, max_blocks] i32 maps each request-slot's logical blocks to
    physical rows (unallocated entries 0); `lengths` [b] i32 counts the
    tokens already written per slot. A pytree — jit/scan-able, and the
    block_tables/lengths leaves are shared by reference across layers."""

    k: object             # [n_blocks + 1, h, block_size, d]
    v: object             # [n_blocks + 1, h, block_size, d]
    block_tables: object  # [b, max_blocks] i32
    lengths: object       # [b] i32

    @property
    def block_size(self):
        return int(self.k.shape[2])


def pick_block_size(max_seq_len, heads, head_dim, dtype="float32",
                    batch=1):
    """Pool block size = the paged kernel's KV block: FLAGS_serve_block_size
    override, else the decode-attention autotune table (measured on TPU,
    disk-cached — same (kernel, shape-bucket, dtype) key family as the
    contiguous kernel), else the 128-column heuristic clamped to the
    sequence budget. Always a multiple of the 8-row sublane tile."""
    from ..core import flags as _flags
    from ..ops.pallas import autotune
    from ..ops.pallas.flash_attention import _ceil_to, _pick_block
    L = _ceil_to(max(int(max_seq_len), 8), 8)
    cfg = int(_flags.flag("FLAGS_serve_block_size") or 0)
    if cfg:
        if cfg % 8 != 0:
            raise ValueError(
                f"FLAGS_serve_block_size={cfg} must be a multiple of 8")
        return cfg
    default = _pick_block(L, 128) or 8

    def measure(params):
        (bs_,) = params
        nb = max(L // bs_, 1)
        h, d = int(heads), int(head_dim)
        ka = jnp.zeros((nb + 1, h, bs_, d), dtype)
        q = jnp.zeros((batch, h, 8, d), dtype)
        bt = jnp.tile(jnp.arange(1, nb + 1, dtype=jnp.int32), (batch, 1))
        lens = jnp.full((batch,), nb * bs_, jnp.int32)
        from ..ops.pallas.decode_attention import _paged_call
        fn = jax.jit(lambda a, k_, v_, b_, ln: _paged_call(
            a, k_, v_, b_, ln, float(d) ** -0.5))
        return autotune.time_thunk(lambda: fn(q, ka, ka, bt, lens))

    cands = [(x,) for x in (256, 128, 64) if L % x == 0]
    if len(cands) <= 1:
        return default
    return autotune.lookup(
        "paged_decode_attention", (autotune.bucket(L), int(head_dim)),
        str(jnp.dtype(dtype)), cands, measure, (default,))[0]


class KVBlockPool:
    """Host-side free-list over the physical arena rows. NOT thread-safe:
    the serve loop owns it from one scheduler thread. Block ids are 1-based
    (0 is the trash block)."""

    def __init__(self, n_blocks, block_size):
        if n_blocks < 1:
            raise ValueError("KVBlockPool needs at least one block")
        if block_size < 8 or block_size % 8 != 0:
            raise ValueError(
                f"block_size {block_size} must be a multiple of the 8-row "
                "sublane tile")
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        # LIFO free list: a just-freed block is hot in whatever cache
        # hierarchy the arena write path touches next
        self._free = list(range(self.n_blocks, 0, -1))

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def used_blocks(self):
        return self.n_blocks - len(self._free)

    def blocks_for(self, n_tokens):
        """Blocks needed to hold n_tokens."""
        return max(0, -(-int(n_tokens) // self.block_size))

    def can_alloc(self, n):
        return len(self._free) >= int(n)

    def alloc(self, n):
        """Pop n physical block ids; returns None (and takes nothing)
        when the pool can't satisfy the whole request — allocation is
        all-or-nothing so a failed admission never leaks blocks."""
        n = int(n)
        if n < 0 or len(self._free) < n:
            return None
        out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, blocks):
        for b in blocks:
            b = int(b)
            if b < 1 or b > self.n_blocks:
                raise ValueError(f"free of invalid block id {b}")
            if b in self._free:  # double-free is a scheduler bug
                raise ValueError(f"double free of block {b}")
            self._free.append(b)

    def arenas(self, layers, heads, head_dim, dtype=jnp.float32):
        """Fresh zeroed k/v arena pairs, one per layer:
        [(k, v), ...] each [n_blocks + 1, h, block_size, d] (row 0 =
        trash). Zeros, not empty: a fresh pool must attend to nothing."""
        shape = (self.n_blocks + 1, int(heads), self.block_size,
                 int(head_dim))
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in range(int(layers))]


# --------------------------------------------------------------------------
# functional pieces used inside jitted serve steps
# --------------------------------------------------------------------------

def write_kv(arena, block_tables, lengths, new_kv):
    """Scatter a chunk's k (or v) into the paged arena. `new_kv` is
    [b, s, h, d] — the s new tokens per slot land at logical positions
    lengths[i]..lengths[i]+s-1. Positions past a slot's table (or rows
    the scheduler parked with an all-zero table) redirect to the trash
    block, so masked/padded rows can never corrupt another request."""
    b, s = new_kv.shape[0], new_kv.shape[1]
    bs = arena.shape[2]
    nb = block_tables.shape[1]
    pos = (jnp.asarray(lengths, jnp.int32)[:, None]
           + jnp.arange(s, dtype=jnp.int32)[None])        # [b, s]
    blk_raw = pos // bs
    blk = jnp.minimum(blk_raw, nb - 1)
    phys = jnp.take_along_axis(jnp.asarray(block_tables, jnp.int32),
                               blk, axis=1)               # [b, s]
    phys = jnp.where(blk_raw < nb, phys, TRASH_BLOCK)
    off = pos % bs
    return arena.at[phys, :, off].set(new_kv.astype(arena.dtype))


def paged_attention_ref(q, k_arena, v_arena, block_tables, lengths,
                        scale):
    """jnp fallback / parity oracle: gather each slot's blocks into a
    contiguous [b, h, max_blocks*bs, d] view and run the same masked
    softmax as _static_cache_attention, with per-row live lengths. Row r
    of slot i attends logical cols <= lengths[i] + r."""
    b, h, s, d = q.shape
    bs = k_arena.shape[2]
    bt = jnp.asarray(block_tables, jnp.int32)
    nb = bt.shape[1]
    L = nb * bs

    def gather(arena):
        g = jnp.take(arena, bt, axis=0)          # [b, nb, h, bs, d]
        return jnp.moveaxis(g, 2, 1).reshape(b, h, L, d)

    kc, vc = gather(k_arena), gather(v_arena)
    lens = jnp.asarray(lengths, jnp.int32)
    row = (lens[:, None] + jnp.arange(s, dtype=jnp.int32)[None])  # [b, s]
    col = jnp.arange(L, dtype=jnp.int32)                          # [L]
    live = col[None, None, :] <= row[:, :, None]                  # [b, s, L]
    scores = jnp.einsum("bhsd,bhld->bhsl", q.astype(kc.dtype), kc,
                        preferred_element_type=jnp.float32) * scale
    scores = jnp.where(live[:, None], scores, -1e9)
    p = jax.nn.softmax(scores, axis=-1).astype(vc.dtype)
    return jnp.einsum("bhsl,bhld->bhsd", p, vc).astype(q.dtype)


def _paged_kernel_eligible(q, k_arena, training):
    """Gate for the block-table Pallas kernel; every rejection bumps
    pallas.gate_reject.paged_decode_attention.{reason} so bench/serve
    output can say why the pool path ran on jnp."""
    from ..core import flags as _flags
    from ..ops.pallas import gate_reject
    if not _flags.flag("FLAGS_use_paged_attention"):
        return gate_reject("paged_decode_attention", "flag_off")
    from . import functional as F
    if not F._pallas_backend_ok():
        return gate_reject("paged_decode_attention", "backend")
    if training:
        # eval-only, like the contiguous decode kernel (no dropout/vjp)
        return gate_reject("paged_decode_attention", "training")
    from ..ops.pallas.decode_attention import paged_supported
    if not paged_supported(tuple(q.shape), tuple(k_arena.shape)):
        return gate_reject("paged_decode_attention", "shape")
    return True


def paged_attention(q, k_arena, v_arena, block_tables, lengths, scale,
                    training=False):
    """Gated + crash-guarded paged attention: the Pallas block-table
    kernel when eligible, `paged_attention_ref` otherwise (and on any
    kernel failure, via ops/pallas.run_guarded)."""
    if _paged_kernel_eligible(q, k_arena, training):
        from ..ops.pallas import run_guarded
        from ..ops.pallas.decode_attention import paged_decode_attention
        return run_guarded(
            "paged_decode_attention",
            lambda: paged_decode_attention(q, k_arena, v_arena,
                                           block_tables, lengths, scale),
            lambda: paged_attention_ref(q, k_arena, v_arena, block_tables,
                                        lengths, scale))
    return paged_attention_ref(q, k_arena, v_arena, block_tables, lengths,
                               scale)
