"""paddle.nn.functional.

Analog of reference python/paddle/nn/functional/: thin functional layer over
the op library (ops/*), plus attention. Most names are re-exports; the ones
with layer-level semantics (linear, embedding lookup argument order,
attention) are defined here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import ops
from ...ops import (  # noqa: F401 — re-exported op families
    relu, relu6, leaky_relu, prelu, elu, selu, celu, gelu, sigmoid,
    hardsigmoid, hardswish, hardtanh, hardshrink, softshrink, tanhshrink,
    silu, swish, mish, softplus, softsign, softmax, log_softmax, log_sigmoid,
    gumbel_softmax, maxout, thresholded_relu, glu, normalize, tanh,
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
    conv3d_transpose,
    max_pool1d, max_pool2d, max_pool3d, avg_pool2d, avg_pool3d,
    adaptive_avg_pool2d, adaptive_max_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool3d, interpolate, pixel_shuffle, unfold, pad,
    layer_norm, instance_norm, group_norm, rms_norm, local_response_norm,
    dropout, one_hot, embedding as _embedding_op,
    cross_entropy, softmax_with_cross_entropy, nll_loss, mse_loss, l1_loss,
    smooth_l1_loss, binary_cross_entropy, binary_cross_entropy_with_logits,
    sigmoid_cross_entropy_with_logits, kl_div, margin_ranking_loss,
    hinge_embedding_loss, cosine_similarity, label_smooth, square_error_cost,
    log_loss, triplet_margin_loss, huber_loss,
)
from ...ops._dispatch import defop
from ...core.tensor import Tensor

upsample = interpolate


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Weight is [in, out] (reference nn.functional.common.linear)."""
    out = ops.matmul(x, weight)
    if bias is not None:
        out = ops.add(out, bias)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Note the paddle-2.0 argument order (ids first).

    sparse=True in eager mode emits SelectedRows gradients for the table
    (reference lookup_table_v2 grad -> framework/selected_rows.h); under
    jit/static the dense gather's scatter-add transpose is already the
    efficient XLA form, so sparse is a no-op there."""
    if sparse:
        import jax
        from ...core import tape as _tape
        if (_tape.is_grad_enabled() and isinstance(weight, Tensor)
                and not weight.stop_gradient
                and weight._value is not None
                and not isinstance(weight._value, jax.core.Tracer)):
            from ...ops.norm_ops import _sparse_embedding
            return _sparse_embedding(weight, x, padding_idx)
    return _embedding_op(weight, x, padding_idx=padding_idx, sparse=sparse)


def bilinear(x1, x2, weight, bias=None):
    out = ops.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


@defop
def _sdpa(q, k, v, mask, scale, is_causal):
    # q,k,v: [batch, heads, seq, head_dim]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if is_causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        logits = jnp.where(causal, logits, jnp.finfo(logits.dtype).min)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
        else:
            logits = logits + mask
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@defop
def _flash_sdpa(q, k, v, mask, scale, is_causal):
    from ...ops.pallas import flash_attention
    bias = None
    if mask is not None:
        # [b,1,1,sk] (bool or additive float) -> additive [b, sk]
        m = mask.reshape(mask.shape[0], mask.shape[-1])
        if m.dtype == jnp.bool_:
            bias = jnp.where(m, 0.0, -1e9).astype(jnp.float32)
        else:
            bias = m.astype(jnp.float32)
    return flash_attention(q, k, v, bias=bias, causal=is_causal, scale=scale)


def _pallas_backend_ok(extra_flag=None):
    """Pallas kernels run compiled on TPU; elsewhere only when an interpret
    flag opts in (tests)."""
    import jax
    from ...core import flags as _flags
    if jax.default_backend() == "tpu":
        return True
    if _flags.flag("FLAGS_pallas_interpret"):
        return True
    return extra_flag is not None and _flags.flag(extra_flag)


def _flash_eligible(query, key, value, attn_mask):
    from ...core import flags as _flags
    if not _flags.flag("FLAGS_use_flash_attention"):
        return False
    if not _pallas_backend_ok("FLAGS_flash_attention_interpret"):
        return False
    # profitability dispatch (measured on v5e): at short seq XLA's fused
    # attention wins — per-grid-step overhead dominates the kernel; the
    # kernel's O(s) memory + blockwise matmuls win in the long-context
    # regime. FLAGS_flash_min_seq=0 forces the kernel on.
    min_seq = int(_flags.flag("FLAGS_flash_min_seq"))
    if min_seq and key.shape[-2] < min_seq:
        return False
    if attn_mask is not None and isinstance(attn_mask, Tensor) \
            and not attn_mask.stop_gradient:
        # the kernel treats the bias as data (no mask gradient); a learned
        # additive mask must take the jnp path, which differentiates it
        return False
    from ...ops.pallas.flash_attention import supported
    mask_shape = None if attn_mask is None else tuple(attn_mask.shape)
    return supported(tuple(query.shape), tuple(key.shape),
                     tuple(value.shape), mask_shape)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, scale=None,
                                 training=True):
    """Fused attention core. On TPU this routes through the Pallas
    flash-attention kernel (paddle_tpu.ops.pallas.flash_attention): O(s)
    attention memory, blockwise online softmax on the MXU. The jnp fallback
    (_sdpa) covers general mask shapes and non-TPU backends, where XLA
    fuses the softmax chain."""
    sc = scale if scale is not None else query.shape[-1] ** -0.5
    if _flash_eligible(query, key, value, attn_mask):
        out = _flash_sdpa(query, key, value, attn_mask, sc, is_causal)
    else:
        out = _sdpa(query, key, value, attn_mask, sc, is_causal)
    if dropout_p > 0.0 and training:
        out = dropout(out, p=dropout_p, training=True)
    return out


def unfold_linear(*a, **k):  # placeholder parity helper
    raise NotImplementedError


@defop
def _fused_ce_op(hidden, weight, bias, labels, ignore_index):
    from ...ops.pallas.fused_ce import fused_linear_cross_entropy as _k
    return _k(hidden, weight, bias, labels, ignore_index=ignore_index)


@defop
def _ce_head_fallback(hidden, weight, bias, labels, ignore_index):
    # same contract as the kernel: f32 per-token losses, 0 where ignored
    logits = jnp.dot(hidden, weight.T).astype(jnp.float32)
    if bias is not None:
        logits = logits + bias
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    safe = jnp.where(labels == ignore_index, 0, labels).astype(jnp.int32)
    tgt = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
    return jnp.where(labels == ignore_index, 0.0, lse - tgt)


def fused_linear_cross_entropy(hidden, weight, bias=None, labels=None,
                               ignore_index=-100, reduction="mean"):
    """Cross-entropy of `hidden @ weight^T + bias` against `labels` without
    materializing the [n_tokens, vocab] logits (Pallas kernel on TPU,
    paddle_tpu.ops.pallas.fused_ce). hidden: [..., H] (flattened
    internally); weight: [vocab, H]; labels: [...] int. The usual LM/MLM
    loss head, fused.
    """
    from ...core import flags as _flags
    h2 = ops.reshape(hidden, [-1, hidden.shape[-1]])
    y = ops.reshape(labels, [-1])
    n, hd = h2.shape[0], h2.shape[1]
    from ...ops.pallas.fused_ce import supported
    use_kernel = (_flags.flag("FLAGS_use_fused_ce")
                  and _pallas_backend_ok()
                  and supported(n, hd, weight.shape[0]))
    op = _fused_ce_op if use_kernel else _ce_head_fallback
    losses = op(h2, weight, bias, y, int(ignore_index))
    if reduction == "none":
        return losses
    total = ops.sum(losses)
    if reduction == "sum":
        return total
    valid = ops.sum((y != ignore_index).astype("float32"))
    return total / ops.maximum(valid, ops.ones([], "float32"))


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    """Mask [..., maxlen] with 1 where position < length.

    The mask width is a *shape*, so it must be static under jit: a traced
    `maxlen` (or `maxlen=None` with traced lengths) raises a clear error
    instead of an opaque ConcretizationTypeError mid-trace.
    """
    import jax
    import jax.numpy as jnp
    from ...ops._dispatch import unwrap, wrap
    lv = unwrap(lengths)
    m = unwrap(maxlen) if maxlen is not None else None
    if m is None:
        m = lv.max() if hasattr(lv, "max") else max(lv)
    if isinstance(m, jax.core.Tracer):
        raise ValueError(
            "sequence_mask needs a concrete mask width, but "
            + ("maxlen is a traced value" if maxlen is not None
               else "maxlen=None and `lengths` is traced")
            + "; under jit the output shape must be static — pass a "
              "Python-int maxlen")
    m = int(m)
    mask = jnp.arange(m)[None, :] < lv[..., None]
    from ...core.dtype import to_jax_dtype
    return wrap(mask.astype(to_jax_dtype(dtype)))
