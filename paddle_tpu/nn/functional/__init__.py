"""paddle.nn.functional.

Analog of reference python/paddle/nn/functional/: thin functional layer over
the op library (ops/*), plus attention. Most names are re-exports; the ones
with layer-level semantics (linear, embedding lookup argument order,
attention) are defined here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import ops
from ...ops import (  # noqa: F401 — re-exported op families
    relu, relu6, leaky_relu, prelu, elu, selu, celu, gelu, sigmoid,
    hardsigmoid, hardswish, hardtanh, hardshrink, softshrink, tanhshrink,
    silu, swish, mish, softplus, softsign, softmax, log_softmax, log_sigmoid,
    gumbel_softmax, maxout, thresholded_relu, glu, normalize, tanh,
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
    conv3d_transpose,
    max_pool1d, max_pool2d, max_pool3d, avg_pool2d, avg_pool3d,
    adaptive_avg_pool2d, adaptive_max_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool3d, interpolate, pixel_shuffle, unfold, pad,
    layer_norm, instance_norm, group_norm, rms_norm, local_response_norm,
    dropout, one_hot, embedding as _embedding_op,
    cross_entropy, softmax_with_cross_entropy, nll_loss, mse_loss, l1_loss,
    smooth_l1_loss, binary_cross_entropy, binary_cross_entropy_with_logits,
    sigmoid_cross_entropy_with_logits, kl_div, margin_ranking_loss,
    hinge_embedding_loss, cosine_similarity, label_smooth, square_error_cost,
    log_loss, triplet_margin_loss, huber_loss,
)
from ...ops._dispatch import defop
from ...core.tensor import Tensor

upsample = interpolate


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Weight is [in, out] (reference nn.functional.common.linear)."""
    out = ops.matmul(x, weight)
    if bias is not None:
        out = ops.add(out, bias)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Note the paddle-2.0 argument order (ids first).

    sparse=True in eager mode emits SelectedRows gradients for the table
    (reference lookup_table_v2 grad -> framework/selected_rows.h); under
    jit/static the dense gather's scatter-add transpose is already the
    efficient XLA form, so sparse is a no-op there."""
    if sparse:
        import jax
        from ...core import tape as _tape
        if (_tape.is_grad_enabled() and isinstance(weight, Tensor)
                and not weight.stop_gradient
                and weight._value is not None
                and not isinstance(weight._value, jax.core.Tracer)):
            from ...ops.norm_ops import _sparse_embedding
            return _sparse_embedding(weight, x, padding_idx)
    return _embedding_op(weight, x, padding_idx=padding_idx, sparse=sparse)


def bilinear(x1, x2, weight, bias=None):
    out = ops.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


@defop
def _sdpa(q, k, v, mask, scale, is_causal):
    # q,k,v: [batch, heads, seq, head_dim]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if is_causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        logits = jnp.where(causal, logits, jnp.finfo(logits.dtype).min)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
        else:
            logits = logits + mask
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@defop
def _flash_sdpa(q, k, v, mask, scale, is_causal):
    from ...ops.pallas import flash_attention
    bias = None
    if mask is not None:
        # [b,1,1,sk] (bool or additive float) -> additive [b, sk]
        m = mask.reshape(mask.shape[0], mask.shape[-1])
        if m.dtype == jnp.bool_:
            bias = jnp.where(m, 0.0, -1e9).astype(jnp.float32)
        else:
            bias = m.astype(jnp.float32)
    return flash_attention(q, k, v, bias=bias, causal=is_causal, scale=scale)


def _pallas_backend_ok(extra_flag=None):
    """Pallas kernels run compiled on TPU; elsewhere only when an interpret
    flag opts in (tests) or FLAGS_pallas_force_compile is on (AOT TPU
    lowering on a dev box — tools/hlo_evidence.py)."""
    import jax
    from ...core import flags as _flags
    if jax.default_backend() == "tpu":
        return True
    if _flags.flag("FLAGS_pallas_interpret"):
        return True
    if _flags.flag("FLAGS_pallas_force_compile"):
        return True
    return extra_flag is not None and _flags.flag(extra_flag)


def _flash_eligible(query, key, value, attn_mask):
    from ...core import flags as _flags
    from ...ops.pallas import gate_reject
    if not _flags.flag("FLAGS_use_flash_attention"):
        return gate_reject("flash_attention", "flag_off")
    if not _pallas_backend_ok("FLAGS_flash_attention_interpret"):
        return gate_reject("flash_attention", "backend")
    # profitability dispatch (measured on v5e): at short seq XLA's fused
    # attention wins — per-grid-step overhead dominates the kernel; the
    # kernel's O(s) memory + blockwise matmuls win in the long-context
    # regime. FLAGS_flash_min_seq=0 forces the kernel on.
    min_seq = int(_flags.flag("FLAGS_flash_min_seq"))
    if min_seq and key.shape[-2] < min_seq:
        return gate_reject("flash_attention", "min_seq")
    if attn_mask is not None and isinstance(attn_mask, Tensor) \
            and not attn_mask.stop_gradient:
        # the kernel treats the bias as data (no mask gradient); a learned
        # additive mask must take the jnp path, which differentiates it
        return gate_reject("flash_attention", "mask_grad")
    from ...ops.pallas.flash_attention import supported
    mask_shape = None if attn_mask is None else tuple(attn_mask.shape)
    if not supported(tuple(query.shape), tuple(key.shape),
                     tuple(value.shape), mask_shape):
        return gate_reject("flash_attention", "shape")
    return True


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, scale=None,
                                 training=True):
    """Fused attention core. On TPU this routes through the Pallas
    flash-attention kernel (paddle_tpu.ops.pallas.flash_attention): O(s)
    attention memory, blockwise online softmax on the MXU. The jnp fallback
    (_sdpa) covers general mask shapes, non-TPU backends (where XLA fuses
    the softmax chain), and any kernel failure — run_guarded demotes a
    crashed kernel to _sdpa instead of aborting the step."""
    sc = scale if scale is not None else query.shape[-1] ** -0.5
    if _flash_eligible(query, key, value, attn_mask):
        from ...ops.pallas import run_guarded
        out = run_guarded(
            "flash_attention",
            lambda: _flash_sdpa(query, key, value, attn_mask, sc, is_causal),
            lambda: _sdpa(query, key, value, attn_mask, sc, is_causal))
    else:
        out = _sdpa(query, key, value, attn_mask, sc, is_causal)
    if dropout_p > 0.0 and training:
        out = dropout(out, p=dropout_p, training=True)
    return out


def unfold_linear(*a, **k):  # placeholder parity helper
    raise NotImplementedError


@defop
def _fused_ce_op(hidden, weight, bias, labels, ignore_index):
    from ...ops.pallas.fused_ce import fused_linear_cross_entropy as _k
    return _k(hidden, weight, bias, labels, ignore_index=ignore_index)


@defop
def _ce_head_fallback(hidden, weight, bias, labels, ignore_index):
    # same contract as the kernel: f32 per-token losses, 0 where ignored
    logits = jnp.dot(hidden, weight.T).astype(jnp.float32)
    if bias is not None:
        logits = logits + bias
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    safe = jnp.where(labels == ignore_index, 0, labels).astype(jnp.int32)
    tgt = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
    return jnp.where(labels == ignore_index, 0.0, lse - tgt)


def fused_linear_cross_entropy(hidden, weight, bias=None, labels=None,
                               ignore_index=-100, reduction="mean"):
    """Cross-entropy of `hidden @ weight^T + bias` against `labels` without
    materializing the [n_tokens, vocab] logits (Pallas kernel on TPU,
    paddle_tpu.ops.pallas.fused_ce). hidden: [..., H] (flattened
    internally); weight: [vocab, H]; labels: [...] int. The usual LM/MLM
    loss head, fused.
    """
    from ...core import flags as _flags
    from ...ops.pallas import gate_reject, run_guarded
    h2 = ops.reshape(hidden, [-1, hidden.shape[-1]])
    y = ops.reshape(labels, [-1])
    n, hd = h2.shape[0], h2.shape[1]
    from ...ops.pallas.fused_ce import supported
    if not _flags.flag("FLAGS_use_fused_ce"):
        use_kernel = gate_reject("fused_ce", "flag_off")
    elif not _pallas_backend_ok():
        use_kernel = gate_reject("fused_ce", "backend")
    elif not supported(n, hd, weight.shape[0]):
        use_kernel = gate_reject("fused_ce", "shape")
    else:
        use_kernel = True
    if use_kernel:
        losses = run_guarded(
            "fused_ce",
            lambda: _fused_ce_op(h2, weight, bias, y, int(ignore_index)),
            lambda: _ce_head_fallback(h2, weight, bias, y,
                                      int(ignore_index)))
    else:
        losses = _ce_head_fallback(h2, weight, bias, y, int(ignore_index))
    if reduction == "none":
        return losses
    total = ops.sum(losses)
    if reduction == "sum":
        return total
    valid = ops.sum((y != ignore_index).astype("float32"))
    return total / ops.maximum(valid, ops.ones([], "float32"))


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    """Mask [..., maxlen] with 1 where position < length.

    The mask width is a *shape*, so it must be static under jit: a traced
    `maxlen` (or `maxlen=None` with traced lengths) raises a clear error
    instead of an opaque ConcretizationTypeError mid-trace.
    """
    import jax
    import jax.numpy as jnp
    from ...ops._dispatch import unwrap, wrap
    lv = unwrap(lengths)
    m = unwrap(maxlen) if maxlen is not None else None
    if m is None:
        m = lv.max() if hasattr(lv, "max") else max(lv)
    if isinstance(m, jax.core.Tracer):
        raise ValueError(
            "sequence_mask needs a concrete mask width, but "
            + ("maxlen is a traced value" if maxlen is not None
               else "maxlen=None and `lengths` is traced")
            + "; under jit the output shape must be static — pass a "
              "Python-int maxlen")
    m = int(m)
    mask = jnp.arange(m)[None, :] < lv[..., None]
    from ...core.dtype import to_jax_dtype
    return wrap(mask.astype(to_jax_dtype(dtype)))


# -- round-4: close the functional-surface gap vs the reference ------------
# (python/paddle/nn/functional/__init__.py re-exports the v1 layer names
# too; the implementations live in ops/ — re-export the done ones and
# implement the remaining small kernels below)

from ...ops.math_extra import (affine_grid, diag_embed, grid_sample,  # noqa: E402,F401
                               bilinear_tensor_product, fsp_matrix,
                               filter_by_instag, cvm as continuous_value_model,
                               hash_bucket as hash,  # noqa: A004
                               batch_fc, rank_attention,
                               match_matrix_tensor, conv_shift,
                               gru_unit, lstm_unit, accuracy, auc)
from ...ops.detection import (anchor_generator, bipartite_match, box_clip,  # noqa: E402,F401
                              box_coder, box_decoder_and_assign,
                              collect_fpn_proposals, density_prior_box,
                              distribute_fpn_proposals, iou_similarity,
                              matrix_nms, mine_hard_examples,
                              multiclass_nms, polygon_box_transform,
                              prior_box, roi_align, roi_pool, target_assign,
                              yolo_box, yolov3_loss)
from ...ops.loss import (bpr_loss, center_loss, ctc_loss, hinge_loss,  # noqa: E402,F401
                         hsigmoid_loss, linear_chain_crf, nce, npair_loss,
                         rank_loss, sigmoid_focal_loss,
                         teacher_student_sigmoid_loss,
                         ctc_loss as warpctc, viterbi_decode)
from ...ops.conv import (affine_channel, deform_conv2d,  # noqa: E402,F401
                         deform_conv2d as deformable_conv, im2sequence,
                         psroi_pool, random_crop, row_conv)
from ...ops.norm_ops import data_norm, l2_normalize  # noqa: E402,F401
from ...ops.manipulation import (pad2d, pad3d, pad_constant_like,  # noqa: E402,F401
                                 shuffle_channel, space_to_depth,
                                 temporal_shift)
from ...ops import sequence as _seq  # noqa: E402
# NB: F.sequence_mask stays the jit-aware version defined above — the
# ops.sequence one is eager/RaggedTensor-oriented
from ...ops.sequence import (sequence_concat, sequence_conv,  # noqa: E402,F401
                             sequence_enumerate, sequence_expand,
                             sequence_expand_as, sequence_first_step,
                             sequence_last_step,
                             sequence_pad, sequence_pool, sequence_reshape,
                             sequence_reverse, sequence_scatter,
                             sequence_slice, sequence_softmax,
                             sequence_unpad)


def image_resize(x, out_shape=None, scale=None, resample="BILINEAR",
                 align_corners=True, data_format="NCHW"):
    """v1 alias over interpolate (reference image_resize)."""
    mode = {"BILINEAR": "bilinear", "NEAREST": "nearest",
            "TRILINEAR": "trilinear"}[resample.upper()]
    return interpolate(x, size=out_shape, scale_factor=scale, mode=mode,
                       data_format=data_format)


def resize_bilinear(x, out_shape=None, scale=None, **kw):
    return image_resize(x, out_shape, scale, "BILINEAR")


def resize_nearest(x, out_shape=None, scale=None, **kw):
    return image_resize(x, out_shape, scale, "NEAREST")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False):
    """reference pool_op.cc 1-D avg (squeeze-through-2D like max_pool1d)."""
    from ... import ops as _ops
    x4 = _ops.unsqueeze(x, [2])
    k = (1, kernel_size if isinstance(kernel_size, int) else kernel_size[0])
    s = (1, (stride if isinstance(stride, int) else
             (stride[0] if stride else k[1])) or k[1])
    p = (0, padding if isinstance(padding, int) else padding[0])
    out = avg_pool2d(x4, k, stride=s, padding=p, ceil_mode=ceil_mode,
                     exclusive=exclusive)
    return _ops.squeeze(out, [2])


def adaptive_avg_pool1d(x, output_size):
    from ... import ops as _ops
    x4 = _ops.unsqueeze(x, [2])
    out = adaptive_avg_pool2d(x4, (1, output_size))
    return _ops.squeeze(out, [2])


def adaptive_max_pool1d(x, output_size):
    from ... import ops as _ops
    x4 = _ops.unsqueeze(x, [2])
    out = adaptive_max_pool2d(x4, (1, output_size))
    return _ops.squeeze(out, [2])


def alpha_dropout(x, p=0.5, training=True):
    """SELU-preserving dropout (reference alpha_dropout): keeps mean/var
    under the SELU fixed point by dropping to alpha' with affine fixup."""
    if not training or p == 0.0:
        return x
    import jax

    from ...core import rng as _rng
    from ...core.tensor import Tensor
    alpha_p = -1.7580993408473766
    v = x._value if isinstance(x, Tensor) else x
    keep = 1.0 - p
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    mask = jax.random.bernoulli(_rng.next_key(), keep, v.shape)
    out = a * jnp.where(mask, v, alpha_p) + b
    return Tensor(out.astype(v.dtype), _internal=True)


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    """Channel-wise dropout (reference dropout_nd): zero whole feature
    maps."""
    if not training or p == 0.0:
        return x
    import jax

    from ...core import rng as _rng
    from ...core.tensor import Tensor
    v = x._value if isinstance(x, Tensor) else x
    shape = (v.shape[0], v.shape[1], 1, 1) if data_format == "NCHW" \
        else (v.shape[0], 1, 1, v.shape[-1])
    keep = 1.0 - p
    mask = jax.random.bernoulli(_rng.next_key(), keep, shape)
    return Tensor((jnp.where(mask, v, 0) / keep).astype(v.dtype),
                  _internal=True)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    if not training or p == 0.0:
        return x
    import jax

    from ...core import rng as _rng
    from ...core.tensor import Tensor
    v = x._value if isinstance(x, Tensor) else x
    shape = (v.shape[0], v.shape[1], 1, 1, 1) if data_format == "NCDHW" \
        else (v.shape[0], 1, 1, 1, v.shape[-1])
    keep = 1.0 - p
    mask = jax.random.bernoulli(_rng.next_key(), keep, shape)
    return Tensor((jnp.where(mask, v, 0) / keep).astype(v.dtype),
                  _internal=True)


def dice_loss(input, label, epsilon=1e-5):  # noqa: A002
    """reference dice_loss (fluid/layers/loss.py): 1 - 2|X∩Y|/(|X|+|Y|)
    over the class axis (input [N, ..., C] probabilities, label ints)."""
    from ... import ops as _ops
    lab = _ops.one_hot(label.squeeze(-1) if label.shape[-1] == 1 else label,
                       input.shape[-1]).astype(input.dtype)
    reduce_dims = list(range(1, len(input.shape)))
    inter = _ops.sum(input * lab, axis=reduce_dims)
    union = _ops.sum(input, axis=reduce_dims) + _ops.sum(lab,
                                                         axis=reduce_dims)
    return _ops.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))


def soft_relu(x, threshold=40.0):
    """reference soft_relu: log(1 + exp(clip(x)))."""
    from ... import ops as _ops
    return _ops.log1p(_ops.exp(_ops.clip(x, -threshold, threshold)))


def add_position_encoding(x, alpha=1.0, beta=1.0):
    """reference add_position_encoding_op.cc: sinusoidal PE added with
    x*alpha + pe*beta; x [B, T, D]."""
    from ...core.tensor import Tensor
    v = x._value if isinstance(x, Tensor) else x
    b, t, d = v.shape
    half = d // 2
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos / div[None, :]
    pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=1)
    if pe.shape[1] < d:
        pe = jnp.pad(pe, ((0, 0), (0, d - pe.shape[1])))
    out = alpha * v + beta * pe[None].astype(v.dtype)
    return Tensor(out, _internal=True)
