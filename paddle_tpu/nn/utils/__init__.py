"""paddle.nn.utils — weight reparameterizations and parameter utilities.

Analog of reference python/paddle/nn/utils/weight_norm_hook.py
(weight_norm :155, remove_weight_norm :202) plus the SpectralNorm weight
transform (reference fluid SpectralNorm layer / spectral_norm_op.cc) in
the 2.x functional form. Both install a forward-pre-hook that recomputes
the target weight from the reparameterized pieces INSIDE the traced
region, so gradients flow to the pieces and the recomputation fuses into
the step under jit.
"""
from __future__ import annotations

import numpy as np

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]


def _require_eager(p, fn_name):
    if getattr(p, "_value", None) is None:
        raise TypeError(
            f"nn.utils.{fn_name} operates on eager parameters; got a "
            "static-graph Variable — apply the transform before "
            "paddle.enable_static() (the reparameterization is part of "
            "the layer, and traces into any later static program)")


def _norm_except_dim(w, dim):
    import jax.numpy as jnp
    if dim is None:
        return jnp.sqrt(jnp.sum(w * w))
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """w = g * v / ||v||  (Salimans & Kingma; reference
    weight_norm_hook.py:155). Replaces `name` with `{name}_g` and
    `{name}_v` parameters and recomputes w in a forward-pre-hook."""
    import jax.numpy as jnp
    from ..layer.layers import Parameter
    from ...core.tensor import Tensor

    if name not in layer._parameters:
        raise ValueError(f"layer has no parameter {name!r}")
    _require_eager(layer._parameters[name], "weight_norm")
    w = layer._parameters.pop(name)
    wv = np.asarray(w._value)
    g0 = np.asarray(_norm_except_dim(jnp.asarray(wv), dim))
    layer.add_parameter(name + "_g", Parameter(g0, name=w.name + "_g"))
    layer.add_parameter(name + "_v", Parameter(wv, name=w.name + "_v"))

    def hook(lyr, inputs):
        # Tensor-level math: the tape must record the reparameterization
        # so grads flow to g and v
        from ... import ops
        g = lyr._parameters[name + "_g"]
        v = lyr._parameters[name + "_v"]
        if dim is None:
            vn = ops.sqrt(ops.sum(v * v))
        else:
            axes = tuple(i for i in range(len(v.shape)) if i != dim)
            vn = ops.sqrt(ops.sum(v * v, axis=axes, keepdim=True))
        wt = g * v / (vn + 1e-12)
        object.__setattr__(lyr, name, wt)
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer.__dict__.setdefault("_wn_hooks", {})[name] = (handle, dim)
    hook(layer, ())  # materialize immediately for direct weight reads
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g,v back into a single parameter (reference
    weight_norm_hook.py:202)."""
    import jax.numpy as jnp
    from ..layer.layers import Parameter

    hooks = layer.__dict__.get("_wn_hooks", {})
    if name not in hooks:
        raise ValueError(f"{name!r} has no weight_norm applied")
    handle, dim = hooks.pop(name)
    handle.remove()
    g = layer._parameters.pop(name + "_g")
    v = layer._parameters.pop(name + "_v")
    vn = _norm_except_dim(v._value, dim)
    w = np.asarray(g._value * v._value / (vn + 1e-12))  # same formula as
    # the forward hook, so pre/post-remove outputs agree exactly
    layer.__dict__.pop(name, None)  # drop the hook-computed attr
    layer.add_parameter(name, Parameter(w))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """w_sn = w / sigma_max(w), sigma estimated by power iteration with
    persistent u/v buffers (reference spectral_norm_op.cc; paddle 2.x
    nn.utils.spectral_norm). Each forward advances the iteration FROM the
    stored u/v and writes the new vectors back into the buffers, so the
    estimate converges as training proceeds. dim defaults to 1 for
    Linear/Conv*Transpose (output dim second in their weights), else 0 —
    the reference's rule."""
    import jax.numpy as jnp
    from ...core.tensor import Tensor

    if name not in layer._parameters:
        raise ValueError(f"layer has no parameter {name!r}")
    _require_eager(layer._parameters[name], "spectral_norm")
    w = layer._parameters[name]
    if dim is None:
        cls = type(layer).__name__
        dim = 1 if (cls == "Linear" or "Transpose" in cls) else 0
    shape = w.shape
    h = shape[dim]
    rest = int(np.prod(shape)) // h
    rng = np.random.RandomState(0)
    u0 = rng.randn(h).astype("float32")
    v0 = rng.randn(rest).astype("float32")
    u0 /= np.linalg.norm(u0) + eps
    v0 /= np.linalg.norm(v0) + eps
    layer.register_buffer(name + "_u", Tensor(jnp.asarray(u0),
                                              _internal=True))
    layer.register_buffer(name + "_v", Tensor(jnp.asarray(v0),
                                              _internal=True))
    # rename the raw parameter so the hook-computed attr can own `name`
    orig = layer._parameters.pop(name)
    layer.add_parameter(name + "_orig", orig)

    def hook(lyr, inputs):
        import jax
        from ... import ops
        worig = lyr._parameters[name + "_orig"]
        # power iteration on the CURRENT weight, gradient-stopped (the
        # direction is a constant, as in the reference op)
        wm = jax.lax.stop_gradient(
            jnp.moveaxis(worig._value, dim, 0).reshape(h, -1))
        u = lyr._buffers[name + "_u"]._value
        v = lyr._buffers[name + "_v"]._value
        for _ in range(max(int(n_power_iterations), 1)):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        u = jax.lax.stop_gradient(u)
        v = jax.lax.stop_gradient(v)
        # sigma on the TAPE (Tensor ops) so grads flow through w/sigma(w)
        perm = [dim] + [i for i in range(len(shape)) if i != dim]
        wmat_t = ops.reshape(ops.transpose(worig, perm), [h, -1])
        u_t = Tensor(u, _internal=True)
        v_t = Tensor(v, _internal=True)
        sigma = ops.sum(u_t * ops.matmul(wmat_t, v_t))
        wsn = worig / (sigma + eps)
        # persist the advanced u/v so the estimate accumulates; the hapi
        # engine reads named_buffers back out of the traced step
        lyr._buffers[name + "_u"] = Tensor(u, _internal=True)
        lyr._buffers[name + "_v"] = Tensor(v, _internal=True)
        object.__setattr__(lyr, name, wsn)
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer.__dict__.setdefault("_sn_hooks", {})[name] = handle
    hook(layer, ())
    return layer


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """In-place global-norm gradient clip over .grad (reference
    paddle.nn.utils.clip_grad_norm_). Returns the total norm."""
    import jax.numpy as jnp
    from ...core.tensor import Tensor
    params = [p for p in parameters if getattr(p, "grad", None) is not None]
    if not params:
        return Tensor(jnp.zeros(()), _internal=True)
    grads = [p.grad._value if isinstance(p.grad, Tensor)
             else jnp.asarray(p.grad) for p in params]
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g) ** norm_type) for g in grads])) \
            ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"gradient norm is {float(total)}; set "
            "error_if_nonfinite=False to clip anyway")
    coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p, g in zip(params, grads):
        p.grad = Tensor(g * coef, _internal=True)
    return Tensor(total, _internal=True)


def clip_grad_value_(parameters, clip_value):
    """In-place elementwise gradient clip to [-clip_value, clip_value]
    (reference paddle.nn.utils.clip_grad_value_)."""
    import jax.numpy as jnp
    from ...core.tensor import Tensor
    cv = abs(float(clip_value))
    for p in parameters:
        if getattr(p, "grad", None) is None:
            continue
        g = p.grad._value if isinstance(p.grad, Tensor) \
            else jnp.asarray(p.grad)
        p.grad = Tensor(jnp.clip(g, -cv, cv), _internal=True)


def parameters_to_vector(parameters):
    """Flatten parameters into one 1-D tensor (reference
    nn/utils/transform_parameters.py)."""
    import jax.numpy as jnp
    from ...core.tensor import Tensor
    parameters = list(parameters)
    for p in parameters:
        _require_eager(p, "parameters_to_vector")
    vals = [jnp.ravel(p._value) for p in parameters]
    return Tensor(jnp.concatenate(vals) if vals
                  else jnp.zeros((0,), jnp.float32), _internal=True)


def vector_to_parameters(vec, parameters):
    """Write a flat vector back into the parameter list."""
    import numpy as _np
    v = _np.asarray(vec.numpy() if hasattr(vec, "numpy") else vec)
    parameters = list(parameters)
    need = sum(int(_np.prod(p.shape)) if p.shape else 1
               for p in parameters)
    if need != v.size:
        raise ValueError(f"vector has {v.size} elements; parameters "
                         f"consume {need}")
    off = 0
    for p in parameters:
        n = int(_np.prod(p.shape)) if p.shape else 1
        p.set_value(v[off:off + n].reshape(p.shape))
        off += n
    return parameters
