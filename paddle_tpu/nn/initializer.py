"""Weight initializers.

Analog of reference python/paddle/fluid/initializer.py (ConstantInitializer,
UniformInitializer, NormalInitializer, TruncatedNormal, Xavier, MSRA/Kaiming,
NumpyArrayInitializer) and python/paddle/nn/initializer/. Initializers are
eager: they produce the parameter value at Layer construction from the global
PRNG chain — there are no init ops in a startup program (the reference's
startup-program mechanism collapses in an eager/XLA world).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as _rng
from ..core.dtype import to_jax_dtype

__all__ = ["Initializer", "Constant", "Uniform", "Normal", "TruncatedNormal",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "calculate_gain"]


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv2d": 1.0,
             "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 3.0 / 4.0}
    return gains[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weight OIHW: fan_in = C_in * k*k, fan_out = C_out * k*k
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, to_jax_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        return jax.random.uniform(_rng.next_key(), tuple(shape),
                                  to_jax_dtype(dtype), self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        return (jax.random.normal(_rng.next_key(), tuple(shape),
                                  to_jax_dtype(dtype)) * self.std + self.mean)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        out = jax.random.truncated_normal(_rng.next_key(), -2.0, 2.0,
                                          tuple(shape), to_jax_dtype(dtype))
        return out * self.std + self.mean


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(_rng.next_key(), tuple(shape),
                                  to_jax_dtype(dtype), -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(_rng.next_key(), tuple(shape),
                                 to_jax_dtype(dtype)) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(_rng.next_key(), tuple(shape),
                                  to_jax_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return jax.random.normal(_rng.next_key(), tuple(shape),
                                 to_jax_dtype(dtype)) * std


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        arr = jnp.asarray(np.asarray(self.value), to_jax_dtype(dtype))
        if tuple(arr.shape) != tuple(shape):
            raise ValueError(f"Assign shape {arr.shape} != param shape {shape}")
        return arr
