"""paddle.nn — layers, functional, initializers.

Analog of reference python/paddle/nn/ (layer zoo over the dygraph Layer base,
fluid/dygraph/layers.py:65).
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer import Layer, Parameter, ParamAttr  # noqa: F401

def __getattr__(name):
    # clip classes live in optimizer but are exposed as paddle.nn.* for parity
    if name in ("ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue"):
        from ..optimizer import clip
        return getattr(clip, name)
    raise AttributeError(f"module 'paddle_tpu.nn' has no attribute {name!r}")
