"""Dynamic decoding: Decoder / BeamSearchDecoder / dynamic_decode.

Analog of reference fluid/layers/rnn.py (Decoder :~640, BeamSearchDecoder
:~700, dynamic_decode :~1000) — the generation-time control-flow surface
SURVEY hard part 2 calls out. The reference drives a While op over
sub-blocks; here decoding is a host loop of compiled steps (the natural
TPU inference form for modest step counts) with a `maximum length`
bound, early exit when every hypothesis finishes, and the classic beam
bookkeeping: per-step top-k over (beam x vocab) joint scores, state
gather by parent beam, finished-beam freezing.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from ..core import tape as _tape

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """Contract: initialize() -> (inputs, states, finished);
    step(time, inputs, states) -> (outputs, states, inputs, finished);
    finalize(outputs, states) -> (outputs, states)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states


class BeamSearchDecoder(Decoder):
    """reference BeamSearchDecoder: expand each batch item to `beam_size`
    hypotheses, advance all beams through the cell each step, keep the
    top-k joint log-prob continuations, freeze finished beams on
    end_token."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers over [batch*beam, ...] arrays ------------------------------
    def _merge(self, x):
        return ops.reshape(x, [-1] + list(x.shape[2:]))

    def _split(self, x, b):
        return ops.reshape(x, [b, self.beam_size] + list(x.shape[1:]))

    def initialize(self, inits):
        """inits: initial cell states for batch b (pytree of [b, ...])."""
        import jax.tree_util as jtu
        from ..core.tensor import Tensor
        from .layer.transformer import StaticKVCache

        def tile(t):
            if isinstance(t, StaticKVCache):
                import jax.numpy as jnp
                return StaticKVCache(jnp.repeat(t.k, self.beam_size, 0),
                                     jnp.repeat(t.v, self.beam_size, 0),
                                     t.index)
            v = t if isinstance(t, Tensor) else t
            e = ops.unsqueeze(v, [1])
            reps = [1, self.beam_size] + [1] * (v.ndim - 1)
            return self._merge(ops.tile(e, reps))

        states = jtu.tree_map(tile, inits,
                              is_leaf=lambda t: isinstance(
                                  t, (Tensor, StaticKVCache)))
        leaf = jtu.tree_leaves(states)[0]
        b = leaf.shape[0] // self.beam_size
        ids = ops.full([b * self.beam_size], self.start_token, "int64")
        # only beam 0 is live at t=0 (standard first-step trick)
        neg = np.zeros((b, self.beam_size), np.float32)
        neg[:, 1:] = -1e9
        import paddle_tpu as paddle
        self._cum = paddle.to_tensor(neg.reshape(-1))
        finished = paddle.to_tensor(
            np.zeros(b * self.beam_size, bool))
        self._batch = b
        return ids, states, finished

    def step(self, time, inputs, states):
        import paddle_tpu as paddle
        b, k = self._batch, self.beam_size
        emb = self.embedding_fn(inputs) if self.embedding_fn else inputs
        cell_out, new_states = self.cell(emb, states)
        logits = self.output_fn(cell_out) if self.output_fn else cell_out
        logp = ops.log_softmax(logits, axis=-1)          # [b*k, V]
        V = logp.shape[-1]

        fin = np.asarray(self._finished_np)
        cum = self._cum                                   # [b*k]
        # finished beams: only end_token continues, at zero added cost
        mask = np.full((b * k, V), 0.0, np.float32)
        mask[fin, :] = -1e9
        mask[fin, self.end_token] = 0.0
        logp = logp * paddle.to_tensor((~fin).astype("float32"))[:, None] \
            + paddle.to_tensor(mask)
        joint = ops.reshape(cum[:, None] + logp, [b, k * V])
        top_val, top_idx = ops.topk(joint, k, axis=-1)   # [b, k]
        parent = top_idx // V                            # beam index
        token = top_idx % V                              # vocab id
        # gather states by parent beam; StaticKVCache states reorder their
        # k/v buffers along batch*beam and keep the shared fill index —
        # incremental decoding under beam search (reference beam_search_op
        # + the C++ predictor's cache reorder)
        flat_parent = (np.arange(b)[:, None] * k
                       + np.asarray(parent._value)).reshape(-1)
        import jax.tree_util as jtu
        from ..core.tensor import Tensor
        from .layer.transformer import StaticKVCache
        gather_idx = paddle.to_tensor(flat_parent.astype("int64"))

        def gather_state(t):
            if isinstance(t, StaticKVCache):
                gi = gather_idx._value
                return StaticKVCache(t.k[gi], t.v[gi], t.index)
            return ops.gather(t, gather_idx)

        new_states = jtu.tree_map(
            gather_state, new_states,
            is_leaf=lambda t: isinstance(t, (Tensor, StaticKVCache)))
        token_flat = ops.reshape(token, [-1]).astype("int64")
        self._cum = ops.reshape(top_val, [-1])
        finished_now = np.asarray(token_flat._value) == self.end_token
        self._finished_np = fin[flat_parent] | finished_now
        finished = paddle.to_tensor(self._finished_np)
        # outputs per step: (token, parent) for traceback
        return (token_flat, paddle.to_tensor(flat_parent)), new_states, \
            token_flat, finished

    def finalize(self, step_outputs, final_states, sequence_lengths):
        """Backtrack parents to materialize [b, beam, T] token paths."""
        tokens = [np.asarray(t._value) for t, _ in step_outputs]
        parents = [np.asarray(p._value) for _, p in step_outputs]
        T = len(tokens)
        b, k = self._batch, self.beam_size
        n = b * k
        out = np.zeros((T, n), np.int64)
        idx = np.arange(n)
        for t in range(T - 1, -1, -1):
            out[t] = tokens[t][idx]
            idx = parents[t][idx]
        import paddle_tpu as paddle
        paths = out.T.reshape(b, k, T)
        scores = np.asarray(self._cum._value).reshape(b, k)
        return (paddle.to_tensor(paths), paddle.to_tensor(scores)), \
            final_states


def dynamic_decode(decoder, inits=None, max_step_num=64, **kwargs):
    """reference dynamic_decode: run decoder.step until every hypothesis
    finishes or max_step_num is hit. Returns (outputs, final_states)."""
    with _tape.no_grad():
        inputs, states, finished = decoder.initialize(inits)
        if isinstance(decoder, BeamSearchDecoder):
            decoder._finished_np = np.asarray(finished._value)
        step_outputs = []
        lengths = None
        for t in range(max_step_num):
            out, states, inputs, finished = decoder.step(t, inputs, states)
            step_outputs.append(out)
            if bool(np.asarray(finished._value).all()):
                break
        return decoder.finalize(step_outputs, states, lengths)
