"""Activation layers (reference python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "PReLU", "ELU", "SELU", "CELU",
           "GELU", "Sigmoid", "Hardsigmoid", "Hardswish", "Hardtanh",
           "Hardshrink", "Softshrink", "Tanhshrink", "Silu", "Swish", "Mish",
           "Softplus", "Softsign", "Softmax", "LogSoftmax", "LogSigmoid",
           "Tanh", "ThresholdedReLU", "Maxout", "GLU"]


def _unary(fname, **defaults):
    class _Act(Layer):
        def __init__(self, name=None, **kwargs):
            super().__init__()
            self.kwargs = {**defaults, **kwargs}

        def forward(self, x):
            return getattr(F, fname)(x, **self.kwargs)
    _Act.__name__ = fname
    return _Act


ReLU = _unary("relu")
ReLU6 = _unary("relu6")
Sigmoid = _unary("sigmoid")
Tanh = _unary("tanh")
Silu = _unary("silu")
Swish = _unary("swish")
Mish = _unary("mish")
Softsign = _unary("softsign")
LogSigmoid = _unary("log_sigmoid")
Hardswish = _unary("hardswish")
Hardsigmoid = _unary("hardsigmoid")
Tanhshrink = _unary("tanhshrink")


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        w = self.weight
        if w.shape[0] > 1 and x.ndim > 2:
            from ... import ops
            w = ops.reshape(w, [1, -1] + [1] * (x.ndim - 2))
        return F.prelu(x, w)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, self.approximate)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):  # noqa: A002
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)
