"""Loss layers (reference python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
           "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "HuberLoss",
           "MarginRankingLoss", "HingeEmbeddingLoss", "TripletMarginLoss",
           "CosineEmbeddingLoss", "CTCLoss",
           "HSigmoidLoss", "NCELoss"]


class CTCLoss(Layer):
    """reference paddle.nn.CTCLoss over operators/warpctc_op.cc (here a
    lax.scan alpha recursion, ops/loss.py ctc_loss)."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        from ... import ops
        return ops.ctc_loss(log_probs, labels, input_lengths,
                            label_lengths, blank=self.blank,
                            reduction=self.reduction,
                            norm_by_times=norm_by_times)


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.kw = dict(ignore_index=ignore_index, reduction=reduction,
                       soft_label=soft_label, axis=axis,
                       use_softmax=use_softmax,
                       label_smoothing=label_smoothing)

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(input, label, weight=self.weight, **self.kw)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.nll_loss(input, label, weight=self.weight,
                          ignore_index=self.ignore_index,
                          reduction=self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.binary_cross_entropy(input, label, weight=self.weight,
                                      reduction=self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, weight=self.weight, reduction=self.reduction,
            pos_weight=self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.kl_div(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):  # noqa: A002
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):  # noqa: A002
        from ... import ops
        loss = F.huber_loss(input, label, self.delta)
        if self.reduction == "mean":
            return ops.mean(loss)
        if self.reduction == "sum":
            return ops.sum(loss)
        return loss


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):  # noqa: A002
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.kw = dict(margin=margin, p=p, epsilon=epsilon,
                       reduction=reduction)

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_loss(input, positive, negative, **self.kw)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        from ... import ops
        sim = F.cosine_similarity(input1, input2, axis=1)
        pos = 1.0 - sim
        neg = ops.clip(sim - self.margin, min=0.0)
        loss = ops.where(label == 1, pos, neg)
        if self.reduction == "mean":
            return ops.mean(loss)
        if self.reduction == "sum":
            return ops.sum(loss)
        return loss


class HSigmoidLoss(Layer):
    """reference nn/layer/loss.py HSigmoidLoss over ops.hsigmoid_loss
    (default complete-binary-tree paths)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        from .. import initializer as I
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_classes - 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label):  # noqa: A002
        from ... import ops
        return ops.hsigmoid_loss(input, label, self.weight, self.bias,
                                 num_classes=self.num_classes)


class NCELoss(Layer):
    """NCE loss layer over ops.nce (host-sampled negatives passed per
    call; reference fluid/dygraph NCE)."""

    def __init__(self, num_total_classes, dim, num_neg_samples=5,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        from .. import initializer as I
        self.num_total_classes = num_total_classes
        self.num_neg_samples = num_neg_samples
        self.weight = self.create_parameter(
            [num_total_classes, dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_total_classes], attr=bias_attr, is_bias=True)

    def forward(self, input, label, sample_ids=None):  # noqa: A002
        import numpy as _np

        from ... import ops, to_tensor
        if sample_ids is None:
            sample_ids = to_tensor(_np.random.randint(
                0, self.num_total_classes,
                self.num_neg_samples).astype("int64"))
        return ops.nce(input, label, self.weight, self.bias,
                       sample_ids=sample_ids,
                       num_neg_samples=self.num_neg_samples,
                       num_total_classes=self.num_total_classes)
