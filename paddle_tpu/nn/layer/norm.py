"""Normalization layers.

Analog of reference python/paddle/nn/layer/norm.py; BatchNorm running stats
live in buffers and are threaded functionally through the batch_norm op so
the layer works identically in eager mode and inside a jitted train step
(see Layer.functional_state). SyncBatchNorm reduces moments over the data-
parallel mesh axis (reference: operators/sync_batch_norm_op.cu → lax.pmean).
"""
from __future__ import annotations

import numpy as np

from ... import ops
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm", "RMSNorm",
           "SpectralNorm"]


class _BatchNormBase(Layer):
    _sync_axis = None

    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = "NCHW" if data_format in ("NC", "NCL", "NCHW", "NCDHW") else "NHWC"
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", ops.zeros([num_features]))
        self.register_buffer("_variance", ops.ones([num_features]))

    def forward(self, x):
        training = self.training and not self._use_global_stats
        out, new_rm, new_rv = ops.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, sync_axis=self._sync_axis)
        if training:
            # buffers adopt the new values (tracers inside jit — by design)
            self._mean._rebind(new_rm.detach())
            self._variance._rebind(new_rv.detach())
        return out


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (acts on any rank with channel axis 1)."""


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm: moments averaged over the 'dp' mesh axis
    when running inside a shard_mapped/pjit step (reference:
    sync_batch_norm_op.cu NCCL allreduce of mean/var)."""

    def __init__(self, *args, sync_axis="dp", **kwargs):
        super().__init__(*args, **kwargs)
        self._sync_axis_name = sync_axis

    def forward(self, x):
        from ...distributed.mesh import in_spmd_region
        self._sync_axis = self._sync_axis_name if in_spmd_region(self._sync_axis_name) else None
        try:
            return super().forward(x)
        finally:
            self._sync_axis = None

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Recursively convert BatchNorm* sublayers (reference
        nn/layer/norm.py SyncBatchNorm.convert_sync_batchnorm)."""
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer.weight.shape[0], layer._momentum,
                                layer._epsilon)
            new.weight.set_value(layer.weight)
            new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(self._normalized_shape,
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        begin = -len(self._normalized_shape)
        return F.layer_norm(x, self.weight, self.bias, self._epsilon,
                            begin_norm_axis=begin)


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias,
                            self._epsilon)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, self.weight, self.bias, self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Spectral normalization: weight / sigma_max(weight), sigma estimated
    by power iteration (reference operators/spectral_norm_op.cc /
    python/paddle/fluid/layers/nn.py spectral_norm). u/v are persistent
    buffers updated each forward (stop-gradient, like the reference's
    in-place power iteration); sigma = u^T W v differentiates through W.
    The whole iteration is a static Python loop over tiny matvecs — XLA
    fuses it into the surrounding graph."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        import numpy as np

        from ...core.tensor import to_tensor
        self._dim = int(dim)
        self._power_iters = int(power_iters)
        self._eps = float(eps)
        self._shape = tuple(int(s) for s in weight_shape)
        h = self._shape[self._dim]
        w = int(np.prod(self._shape)) // h
        rng = np.random.RandomState(0)
        self.register_buffer("weight_u", to_tensor(
            rng.normal(size=h).astype(dtype)))
        self.register_buffer("weight_v", to_tensor(
            rng.normal(size=w).astype(dtype)))

    def forward(self, weight):
        import jax
        import jax.numpy as jnp

        from ...core.tensor import Tensor
        wv = weight._value if isinstance(weight, Tensor) \
            else jnp.asarray(weight)
        perm = (self._dim,) + tuple(i for i in range(len(self._shape))
                                    if i != self._dim)
        h = self._shape[self._dim]
        mat = jnp.transpose(wv, perm).reshape(h, -1)     # [h, w]
        u = self.weight_u._value.astype(mat.dtype)
        v = self.weight_v._value.astype(mat.dtype)

        def _norm(x):
            return x / (jnp.linalg.norm(x) + self._eps)

        for _ in range(self._power_iters):
            v = _norm(mat.T @ u)
            u = _norm(mat @ v)
        u = jax.lax.stop_gradient(u)
        v = jax.lax.stop_gradient(v)
        self.weight_u.set_value(u.astype(self.weight_u._value.dtype))
        self.weight_v.set_value(v.astype(self.weight_v._value.dtype))
        sigma = u @ (mat @ v)
        return Tensor(wv / sigma, _internal=True)
