"""Recurrent layers.

Analog of reference python/paddle/nn/layer/rnn.py (RNNCellBase, SimpleRNN,
LSTM, GRU) backed by operators/cudnn_lstm_op.cu / rnn_op. TPU design delta:
the time loop is a `lax.scan`, which XLA compiles into a single fused loop
with the gate matmuls batched on the MXU — the analog of cuDNN's fused RNN
kernels. No dynamic LoD: variable-length sequences use `sequence_length`
masking over a dense [batch, time, ...] layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ... import ops
from ...ops._dispatch import defop
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN",
           "LSTM", "GRU", "BiRNN", "RNNCellBase"]


# -- fused scan kernels ------------------------------------------------------

@defop
def _rnn_scan_tanh(x, h0, wi, wh, bi, bh, mask):
    def step(h, inp):
        xt, mt = inp
        nh = jnp.tanh(xt @ wi.T + h @ wh.T + bi + bh)
        nh = jnp.where(mt[:, None], nh, h)
        return nh, nh
    hT, hs = jax.lax.scan(step, h0, (jnp.swapaxes(x, 0, 1), mask.T))
    return jnp.swapaxes(hs, 0, 1), hT


@defop
def _lstm_scan(x, h0, c0, wi, wh, bi, bh, mask):
    H = h0.shape[-1]

    def step(carry, inp):
        h, c = carry
        xt, mt = inp
        gates = xt @ wi.T + h @ wh.T + bi + bh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        nc = f * c + i * g
        nh = o * jnp.tanh(nc)
        m = mt[:, None]
        nh = jnp.where(m, nh, h)
        nc = jnp.where(m, nc, c)
        return (nh, nc), nh

    (hT, cT), hs = jax.lax.scan(step, (h0, c0),
                                (jnp.swapaxes(x, 0, 1), mask.T))
    return jnp.swapaxes(hs, 0, 1), hT, cT


@defop
def _gru_scan(x, h0, wi, wh, bi, bh, mask):
    def step(h, inp):
        xt, mt = inp
        xg = xt @ wi.T + bi
        hg = h @ wh.T + bh
        xr, xz, xn = jnp.split(xg, 3, axis=-1)
        hr, hz, hn = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        nh = (1.0 - z) * n + z * h
        nh = jnp.where(mt[:, None], nh, h)
        return nh, nh
    hT, hs = jax.lax.scan(step, h0, (jnp.swapaxes(x, 0, 1), mask.T))
    return jnp.swapaxes(hs, 0, 1), hT


# -- cells -------------------------------------------------------------------

class RNNCellBase(Layer):
    def _init_weights(self, input_size, hidden_size, gates, weight_ih_attr,
                      weight_hh_attr, bias_ih_attr, bias_hh_attr):
        std = 1.0 / np.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [gates * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [gates * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [gates * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [gates * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=u)
        self.hidden_size = hidden_size
        self.input_size = input_size

    def get_initial_states(self, batch_size, dtype="float32"):
        return ops.zeros([batch_size, self.hidden_size], dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.activation = activation
        self._init_weights(input_size, hidden_size, 1, weight_ih_attr,
                           weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        h = states if states is not None else self.get_initial_states(inputs.shape[0])
        z = (ops.matmul(inputs, self.weight_ih, transpose_y=True)
             + ops.matmul(h, self.weight_hh, transpose_y=True)
             + self.bias_ih + self.bias_hh)
        nh = ops.tanh(z) if self.activation == "tanh" else F.relu(z)
        return nh, nh


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self._init_weights(input_size, hidden_size, 4, weight_ih_attr,
                           weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            b = inputs.shape[0]
            states = (self.get_initial_states(b), self.get_initial_states(b))
        h, c = states
        gates = (ops.matmul(inputs, self.weight_ih, transpose_y=True)
                 + ops.matmul(h, self.weight_hh, transpose_y=True)
                 + self.bias_ih + self.bias_hh)
        i, f, g, o = ops.split(gates, 4, axis=-1)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = ops.tanh(g)
        nc = f * c + i * g
        nh = o * ops.tanh(nc)
        return nh, (nh, nc)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self._init_weights(input_size, hidden_size, 3, weight_ih_attr,
                           weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        h = states if states is not None else self.get_initial_states(inputs.shape[0])
        xg = ops.matmul(inputs, self.weight_ih, transpose_y=True) + self.bias_ih
        hg = ops.matmul(h, self.weight_hh, transpose_y=True) + self.bias_hh
        xr, xz, xn = ops.split(xg, 3, axis=-1)
        hr, hz, hn = ops.split(hg, 3, axis=-1)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = ops.tanh(xn + r * hn)
        nh = (1.0 - z) * n + z * h
        return nh, nh


# -- multi-layer wrappers ----------------------------------------------------

class _RNNBase(Layer):
    MODE = None  # "RNN_TANH" | "LSTM" | "GRU"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirect else 1
        gates = {"RNN_TANH": 1, "LSTM": 4, "GRU": 3}[self.MODE]
        std = 1.0 / np.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer == 0 \
                    else hidden_size * self.num_directions
                sfx = f"{layer}" + ("_reverse" if d else "")
                self.add_parameter(f"weight_ih_l{sfx}", self.create_parameter(
                    [gates * hidden_size, in_sz], default_initializer=u))
                self.add_parameter(f"weight_hh_l{sfx}", self.create_parameter(
                    [gates * hidden_size, hidden_size], default_initializer=u))
                self.add_parameter(f"bias_ih_l{sfx}", self.create_parameter(
                    [gates * hidden_size], is_bias=True, default_initializer=u))
                self.add_parameter(f"bias_hh_l{sfx}", self.create_parameter(
                    [gates * hidden_size], is_bias=True, default_initializer=u))

    def _scan(self, x, init, wi, wh, bi, bh, mask):
        if self.MODE == "LSTM":
            out, hT, cT = _lstm_scan(x, init[0], init[1], wi, wh, bi, bh, mask)
            return out, (hT, cT)
        if self.MODE == "GRU":
            out, hT = _gru_scan(x, init, wi, wh, bi, bh, mask)
            return out, hT
        out, hT = _rnn_scan_tanh(x, init, wi, wh, bi, bh, mask)
        return out, hT

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs
        if self.time_major:
            x = ops.transpose(x, [1, 0, 2])
        b, t = x.shape[0], x.shape[1]
        if sequence_length is not None:
            mask = F.sequence_mask(sequence_length, maxlen=t, dtype="bool")
        else:
            mask = ops.ones([b, t], "bool")

        def zeros():
            return ops.zeros([b, self.hidden_size], "float32")

        is_lstm = self.MODE == "LSTM"
        n_states = self.num_layers * self.num_directions
        if initial_states is None:
            if is_lstm:
                init_h = [zeros() for _ in range(n_states)]
                init_c = [zeros() for _ in range(n_states)]
            else:
                init_h = [zeros() for _ in range(n_states)]
        else:
            if is_lstm:
                h0, c0 = initial_states
                init_h = ops.unbind(h0, 0)
                init_c = ops.unbind(c0, 0)
            else:
                init_h = ops.unbind(initial_states, 0)

        final_h, final_c = [], []
        out = x
        for layer in range(self.num_layers):
            outs = []
            for d in range(self.num_directions):
                sfx = f"{layer}" + ("_reverse" if d else "")
                wi = getattr(self, f"weight_ih_l{sfx}")
                wh = getattr(self, f"weight_hh_l{sfx}")
                bi = getattr(self, f"bias_ih_l{sfx}")
                bh = getattr(self, f"bias_hh_l{sfx}")
                idx = layer * self.num_directions + d
                seq = ops.flip(out, [1]) if d else out
                m = ops.flip(mask, [1]) if d else mask
                init = (init_h[idx], init_c[idx]) if is_lstm else init_h[idx]
                o, hT = self._scan(seq, init, wi, wh, bi, bh, m)
                if d:
                    o = ops.flip(o, [1])
                outs.append(o)
                if is_lstm:
                    final_h.append(hT[0])
                    final_c.append(hT[1])
                else:
                    final_h.append(hT)
            out = ops.concat(outs, axis=-1) if len(outs) > 1 else outs[0]
            if self.dropout > 0 and layer < self.num_layers - 1:
                out = F.dropout(out, p=self.dropout, training=self.training)

        if self.time_major:
            out = ops.transpose(out, [1, 0, 2])
        h_stack = ops.stack(final_h, axis=0)
        if is_lstm:
            c_stack = ops.stack(final_c, axis=0)
            return out, (h_stack, c_stack)
        return out, h_stack


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"


def _masked_state(m, new, old):
    """Freeze state past each sequence's end (per-timestep select)."""
    if isinstance(new, (tuple, list)):
        return type(new)(_masked_state(m, n, o) for n, o in zip(new, old))
    return new * m + old * (1.0 - m)


class RNN(Layer):
    """Generic cell-driven RNN wrapper (reference nn/layer/rnn.py RNN):
    runs any cell over time with a python loop traced into the step graph."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs
        if self.time_major:
            x = ops.transpose(x, [1, 0, 2])
        t = x.shape[1]
        mask = None
        if sequence_length is not None:
            from .. import functional as F
            mask = F.sequence_mask(sequence_length, maxlen=t, dtype="float32")
        steps = range(t - 1, -1, -1) if self.is_reverse else range(t)
        state = initial_states
        outs = [None] * t
        for i in steps:
            o, new_state = self.cell(x[:, i], state)
            if mask is not None and state is not None:
                m = ops.unsqueeze(mask[:, i], -1)
                o = o * m  # zero outputs past each sequence's end
                new_state = _masked_state(m, new_state, state)
            outs[i] = o
            state = new_state
        out = ops.stack(outs, axis=1)
        if self.time_major:
            out = ops.transpose(out, [1, 0, 2])
        return out, state


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        sf = sb = None
        if initial_states is not None:
            sf, sb = initial_states
        of, sf = self.fw(inputs, sf)
        ob, sb = self.bw(inputs, sb)
        return ops.concat([of, ob], axis=-1), (sf, sb)
